"""Continuous batching: the slot-pool engine and its greedy
equivalence to whole-batch ``generate``.

Two layers of coverage:

* ENGINE properties against a deterministic fake model (no jax): the
  scheduling machinery — FIFO admission, slot reuse, early per-row
  retirement, queue timeout, occupancy accounting, error fan-out —
  must not change any row's token chain no matter how requests
  arrive, because each row's next token depends only on that row's
  own (token, position) state.  A hypothesis sweep drives arbitrary
  request mixes through a thread swarm.

* REAL-MODEL equivalence (tiny flagship on CPU): tokens produced
  under continuous batching — staggered arrival, arbitrary admission
  order, early slot retirement, int8 KV pool — are IDENTICAL to
  whole-batch ``generate`` on the same prompts, including through the
  gang driver's ADMIT/DECODE broadcast protocol executed for real
  (single-process gang sim: broadcast_one_to_all is the identity, so
  rank 0's driver path runs unmodified).
"""

import os
import threading
import time

import numpy as np
import pytest

from dcos_commons_tpu.serve.engine import SlotEngine
from dcos_commons_tpu.utils.microbatch import QueueTimeoutError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _racecheck_probes():
    """Dynamic race probes (SDKLINT_RACECHECK=1): watch every attribute
    the static pass reports as cross-thread shared on the engine loop's
    classes; the session fixture fails the run on any unordered write
    pair.  No-op in the fast tier."""
    from dcos_commons_tpu.utils.microbatch import MicroBatcher

    from conftest import racecheck_watch_guard

    yield from racecheck_watch_guard(SlotEngine, MicroBatcher)


# -- fake model: deterministic per-row chain ---------------------------


_V = 97  # fake vocab (prime: the chain wanders)


def _chain_first(prompt):
    return (sum(prompt) * 31 + len(prompt)) % _V


def _chain_next(tok, pos):
    return (tok * 7 + pos * 3 + 1) % _V


def _chain_oracle(prompt, n, eos=None):
    """What whole-batch generate would produce for this row."""
    out = [_chain_first(prompt)]
    pos = len(prompt)
    while len(out) < n and (eos is None or out[-1] != eos):
        out.append(_chain_next(out[-1], pos))
        pos += 1
    if eos is not None and eos in out:
        out = out[: out.index(eos) + 1]
    return out


class FakeModel:
    """prefill/decode over host state only; each row's next token is
    a pure function of that row's (token, position) — exactly the
    independence the real pool provides — so ANY admission order must
    reproduce the oracle chain."""

    def __init__(self, slots, step_gate=None, fail=None):
        self.slots = slots
        self.step_gate = step_gate    # Event the test pulses per tick
        self.fail = fail              # exception decode should raise
        self.prefills = 0
        self.max_active = 0
        self.decode_calls = 0

    def prefill(self, padded, slot, true_len, temp, seed):
        assert 0 <= slot < self.slots
        self.prefills += 1
        return _chain_first([int(t) for t in padded[0, :true_len]])

    def decode(self, tok, pos, temps, seeds, n_active):
        if self.fail is not None:
            raise self.fail
        if self.step_gate is not None:
            assert self.step_gate.wait(10), "test never released the tick"
            self.step_gate.clear()
        self.decode_calls += 1
        self.max_active = max(self.max_active, n_active)
        return np.asarray(
            [_chain_next(int(t), int(p)) for t, p in zip(tok, pos)],
            np.int32,
        )


def _engine(model, slots, max_len=64, prompt_len=32, **kw):
    return SlotEngine(
        model.prefill, model.decode, slots, max_len, prompt_len, **kw
    )


def _swarm(engine, jobs):
    """Submit each (rows, n, eos) concurrently; returns results."""
    results = [None] * len(jobs)
    errors = []

    def client(i):
        rows, n, eos = jobs[i]
        try:
            results[i] = engine.submit(rows, n, eos_id=eos)
        except Exception as e:  # noqa: BLE001 — surfaced via assert
            errors.append(e)

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(len(jobs))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    return results


def test_engine_rows_reproduce_oracle_under_concurrency():
    model = FakeModel(slots=3)
    engine = _engine(model, slots=3)
    try:
        jobs = [
            ([[1, 2, 3]], 8, None),
            ([[4], [5, 6]], 5, None),
            ([[7, 8, 9, 10]], 1, None),   # retires at admission
            ([[2, 2]], 8, None),
        ]
        results = _swarm(engine, jobs)
        for (rows, n, eos), result in zip(jobs, results):
            assert result == [_chain_oracle(r, n, eos) for r in rows]
        assert model.max_active >= 2  # rows really shared ticks
        stats = engine.stats()
        assert stats["active_slots"] == 0
        assert stats["free_slots"] == 3
        assert stats["requests_completed"] == len(jobs)
        assert stats["tokens_out"] == sum(
            len(r) for result in results for r in result
        )
    finally:
        engine.stop()


def test_engine_eos_retires_row_early():
    model = FakeModel(slots=2)
    engine = _engine(model, slots=2)
    try:
        prompt = [3, 1]
        full = _chain_oracle(prompt, 10)
        eos = full[4]
        got = engine.submit([prompt], 10, eos_id=eos)[0]
        assert got == full[:5]  # cut at (and including) the eos token
        assert engine.stats()["active_slots"] == 0
    finally:
        engine.stop()


def test_engine_slot_exhaustion_queues_and_completes():
    """More concurrent requests than slots: the overflow WAITS for a
    retirement (no error, no corruption) and every chain still
    matches the oracle."""
    model = FakeModel(slots=2)
    engine = _engine(model, slots=2)
    try:
        jobs = [([[i + 1]], 6, None) for i in range(7)]
        results = _swarm(engine, jobs)
        for (rows, n, eos), result in zip(jobs, results):
            assert result == [_chain_oracle(rows[0], n, eos)]
        assert model.max_active <= 2  # never more rows than slots
    finally:
        engine.stop()


def test_engine_queue_timeout_is_distinguishable_overload():
    """A wedged pool raises QueueTimeoutError (-> HTTP 503), and the
    timed-out request leaves the queue (abandoned work never reaches
    the chip)."""
    gate = threading.Event()  # never set: decode wedges
    model = FakeModel(slots=1, step_gate=gate)
    engine = _engine(model, slots=1, queue_timeout_s=0.3)
    try:
        # one long-running occupant wedges the only slot
        occupant = threading.Thread(
            target=lambda: pytest.raises(
                Exception, engine.submit, [[9]], 8
            ),
            daemon=True,
        )
        occupant.start()
        time.sleep(0.1)  # let it admit
        t0 = time.monotonic()
        with pytest.raises(QueueTimeoutError) as exc:
            engine.submit([[5]], 4)
        assert time.monotonic() - t0 < 5.0
        assert isinstance(exc.value, RuntimeError)  # 503 mapping basis
        # BOTH requests overran: the wedged occupant times out too
        # (its slot is retired as abandoned at the next tick)
        deadline = time.monotonic() + 5
        while (engine.stats()["requests_timed_out"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert engine.stats()["requests_timed_out"] == 2
        assert engine.stats()["queue_depth"] == 0  # removed itself
    finally:
        gate.set()
        engine.stop()


def test_engine_occupancy_accounting_mid_flight():
    """KV occupancy tracks live positions per tick: with the decode
    gated, stats between ticks show the admitted rows' prompt+output
    positions and drop back to zero at retirement."""
    gate = threading.Event()
    model = FakeModel(slots=2, step_gate=gate)
    engine = _engine(model, slots=2, max_len=64, prompt_len=32)
    try:
        # ONE submit carrying both rows: they enter the queue
        # atomically, so the first admission pass seats them together
        # (separate clients could race the first gated tick)
        result = [None]

        def client():
            result[0] = engine.submit([[1, 2, 3], [4, 5]], 3)

        swarm = threading.Thread(target=client)
        swarm.start()

        def wait_stats(pred, what):
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                s = engine.stats()
                if pred(s):
                    return s
                time.sleep(0.01)
            raise AssertionError(f"{what}: {engine.stats()}")

        # both admitted (first token each), blocked before tick 1:
        # live = prompt positions (3 + 2)
        s = wait_stats(
            lambda s: s["active_slots"] == 2, "both rows admitted"
        )
        assert s["kv_live_tokens"] == 5
        assert s["kv_occupancy"] == round(5 / (2 * 64.0), 4)
        gate.set()  # tick 1: each row +1 position
        s = wait_stats(
            lambda s: s["kv_live_tokens"] == 7, "tick 1 accounted"
        )
        gate.set()  # tick 2: rows hit n=3 and retire
        s = wait_stats(
            lambda s: s["active_slots"] == 0, "rows retired"
        )
        assert s["kv_live_tokens"] == 0
        assert s["free_slots"] == 2
        swarm.join(timeout=10)
        assert not swarm.is_alive()
        assert result[0] == [
            _chain_oracle([1, 2, 3], 3), _chain_oracle([4, 5], 3),
        ]
    finally:
        gate.set()
        engine.stop()


def test_engine_prefill_failure_signals_group_and_frees_slot():
    """A prefill failure must surface to ITS OWN group immediately
    (not leave the client waiting out the full timeout) and return
    the popped slot to the pool (review finding: transient device
    errors must not drain the pool)."""
    model = FakeModel(slots=2)
    boom = RuntimeError("prefill exploded")
    model.prefill = lambda *a, **kw: (_ for _ in ()).throw(boom)
    engine = _engine(model, slots=2, queue_timeout_s=30)
    try:
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="prefill exploded"):
            engine.submit([[1, 2]], 4)
        assert time.monotonic() - t0 < 5.0  # error, not timeout
        stats = engine.stats()
        assert stats["free_slots"] == 2 and stats["active_slots"] == 0
    finally:
        engine.stop()


def test_engine_slow_healthy_generation_is_not_cut_off():
    """The timeout bounds saturation (no slot) and stalls (no new
    token for a window) — NOT total duration: a generation slower
    than the window that keeps producing completes."""
    model = FakeModel(slots=1)
    orig = model.decode

    def slow_decode(*args):
        time.sleep(0.15)  # half a timeout window per tick: slow, but
        return orig(*args)  # a token lands inside every window

    model.decode = slow_decode
    engine = _engine(model, slots=1, queue_timeout_s=0.3)
    try:
        # 6 tokens x 0.15s/tick ~= 0.9s total, 3x the window — but a
        # token lands every window, so the request must complete
        got = engine.submit([[4, 2]], 6)[0]
        assert got == _chain_oracle([4, 2], 6)
        assert engine.stats()["requests_timed_out"] == 0
    finally:
        engine.stop()


def test_engine_model_failure_fans_out():
    model = FakeModel(slots=2, fail=RuntimeError("chip gone"))
    engine = _engine(model, slots=2)
    try:
        with pytest.raises(RuntimeError, match="chip gone"):
            engine.submit([[1, 2]], 4)
        # the pool is clean afterwards: slots freed, nothing active
        stats = engine.stats()
        assert stats["active_slots"] == 0 and stats["free_slots"] == 2
    finally:
        engine.stop()


def test_engine_survives_malformed_decode_output():
    """A decode_fn returning the wrong shape (gang payload bug) blows
    up in BOOKKEEPING, not in the guarded model call — the loop must
    fan the error out fast and keep serving, not die silently and
    hang every later client for the full timeout."""
    model = FakeModel(slots=2)
    bad = [True]
    orig = model.decode

    def decode(tok, pos, temps, seeds, n_active):
        if bad[0]:
            return np.zeros(0, np.int32)  # too short: IndexError later
        return orig(tok, pos, temps, seeds, n_active)

    model.decode = decode
    engine = _engine(model, slots=2, queue_timeout_s=30)
    try:
        t0 = time.monotonic()
        with pytest.raises(IndexError):
            engine.submit([[1, 2]], 4)
        assert time.monotonic() - t0 < 5.0  # fast fan-out, no timeout
        # the loop survived: a well-formed request still serves
        bad[0] = False
        assert engine.submit([[3]], 4)[0] == _chain_oracle([3], 4)
    finally:
        engine.stop()


def test_engine_rejects_caller_errors():
    model = FakeModel(slots=1)
    engine = _engine(model, slots=1, max_len=16, prompt_len=8)
    try:
        with pytest.raises(ValueError):
            engine.submit([], 4)
        with pytest.raises(ValueError):
            engine.submit([[]], 4)
        with pytest.raises(ValueError):
            engine.submit([[1] * 9], 4)       # prompt > prompt_len
        with pytest.raises(ValueError):
            engine.submit([[1] * 8], 0)       # n < 1
        with pytest.raises(ValueError):
            engine.submit([[1] * 8], 9)       # prompt + n > max_len
    finally:
        engine.stop()


def test_engine_property_any_request_mix_matches_oracle():
    hypothesis = pytest.importorskip("hypothesis")
    st = hypothesis.strategies

    @hypothesis.given(
        st.lists(
            st.tuples(
                st.lists(
                    st.lists(st.integers(0, _V - 1), min_size=1,
                             max_size=6),
                    min_size=1, max_size=3,
                ),
                st.integers(1, 8),
                st.one_of(st.none(), st.integers(0, _V - 1)),
            ),
            min_size=1, max_size=6,
        ),
        st.integers(1, 4),
    )
    @hypothesis.settings(
        max_examples=40, deadline=None,
        suppress_health_check=[hypothesis.HealthCheck.too_slow],
    )
    def run(jobs, slots):
        model = FakeModel(slots=slots)
        engine = _engine(
            model, slots=slots, max_len=16, prompt_len=6
        )
        try:
            results = _swarm(engine, jobs)
            for (rows, n, eos), result in zip(jobs, results):
                assert result == [
                    _chain_oracle(r, n, eos) for r in rows
                ]
            stats = engine.stats()
            assert stats["active_slots"] == 0
            assert stats["free_slots"] == slots
            assert stats["queue_depth"] == 0
        finally:
            engine.stop()

    run()


# -- real model: token-identical to whole-batch generate ---------------


@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import TransformerConfig, init_params

    config = TransformerConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=8, n_kv_heads=4,
        d_ff=96, max_seq=64, dtype=jnp.float32, remat=False,
    )
    return config, init_params(config, jax.random.key(0))


MAX_LEN, NEW = 48, 8
PROMPT_LEN = MAX_LEN - NEW
PROMPTS = [[1, 2, 3, 4], [9, 8], [5, 6, 7, 2, 1], [3], [11, 12, 13]]


def _oracle(config, params, prompt, n):
    import jax.numpy as jnp

    from dcos_commons_tpu.models import generate

    out = generate(
        config, params, jnp.asarray([prompt], jnp.int32),
        max_new_tokens=n,
    )
    return [int(t) for t in out[0]]


@pytest.mark.parametrize("kv_dtype", ["native", "int8"])
def test_pool_engine_greedy_equals_whole_batch_generate(tiny, kv_dtype):
    """Staggered concurrent admission over a 3-slot pool reproduces
    whole-batch generate token for token — including the int8 KV
    pool, whose quantized math is the same on both paths."""
    from dcos_commons_tpu.serve.pool import PoolModel

    config, params = tiny
    pool = PoolModel(config, params, 3, MAX_LEN, kv_dtype=kv_dtype)
    engine = SlotEngine(
        pool.prefill, pool.decode, 3, MAX_LEN, PROMPT_LEN,
        queue_timeout_s=120,
    )
    try:
        results = [None] * len(PROMPTS)
        errors = []

        def client(i):
            try:
                results[i] = engine.submit([PROMPTS[i]], NEW)[0]
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(len(PROMPTS))
        ]
        for t in threads:
            t.start()
            time.sleep(0.01)  # staggered arrivals: mid-flight admission
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        if kv_dtype == "native":
            oracles = [
                _oracle(config, params, p, NEW) for p in PROMPTS
            ]
            assert results == oracles
        else:
            # int8 equivalence is engine-vs-engine determinism: the
            # quantization error vs the native oracle is expected, but
            # the pool path must be self-consistent per prompt
            again = [
                engine.submit([p], NEW)[0] for p in PROMPTS
            ]
            assert results == again
    finally:
        engine.stop()


def test_pool_engine_early_retirement_and_eos_prefixes(tiny):
    """Mixed requested lengths retire slots early; an EOS cut is a
    PREFIX of the whole-batch generation (plus the eos token)."""
    from dcos_commons_tpu.serve.pool import PoolModel

    config, params = tiny
    pool = PoolModel(config, params, 2, MAX_LEN)
    engine = SlotEngine(
        pool.prefill, pool.decode, 2, MAX_LEN, PROMPT_LEN,
        queue_timeout_s=120,
    )
    try:
        full = [_oracle(config, params, p, NEW) for p in PROMPTS[:3]]
        # mixed lengths in ONE submit: 5 rows > 2 slots exercises
        # queue + retirement interleaving; each row a prefix
        mixed = engine.submit(PROMPTS[:3], 3)
        assert mixed == [row[:3] for row in full]
        # eos: pick each row's 3rd token as its stop token
        for prompt, row in zip(PROMPTS[:3], full):
            eos = row[2]
            got = engine.submit([prompt], NEW, eos_id=eos)[0]
            assert got == row[: row.index(eos) + 1]
    finally:
        engine.stop()


def test_gang_sim_broadcast_protocol_equivalence(tiny):
    """The gang driver's ADMIT/DECODE broadcast protocol, executed
    FOR REAL in a single-process gang sim (broadcast_one_to_all is
    the identity with one process): rank 0's engine callbacks
    broadcast each tick and _execute_tick runs the identical payload
    — greedy replies must stay token-identical to whole-batch
    generate."""
    import importlib.util

    from jax.experimental import multihost_utils

    from dcos_commons_tpu.serve.pool import PoolModel

    path = os.path.join(REPO, "frameworks", "jax", "serve_gang_worker.py")
    spec = importlib.util.spec_from_file_location("gang_worker_ut", path)
    gw = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gw)

    config, params = tiny
    slots = 3
    pool = PoolModel(config, params, slots, MAX_LEN)

    ticks = {"admit": 0, "decode": 0, "noop": 0}

    def prefill_fn(padded, slot, true_len, temp, seed):
        head = np.asarray(
            [gw.OP_ADMIT, slot, true_len, seed, int(temp * 1e6), 0],
            np.int64,
        )
        _, zero_rows, _ = gw._zero_payload(slots, PROMPT_LEN)
        head, rows, prompt = gw._broadcast_tick(
            multihost_utils,
            (head, zero_rows, padded.astype(np.int32)),
            slots, PROMPT_LEN,
        )
        ticks["admit"] += 1
        return gw._execute_tick(pool, head, rows, prompt)

    def decode_fn(tok, pos, temps, seeds, n_active):
        head = np.asarray(
            [gw.OP_DECODE, n_active, 0, 0, 0, 0], np.int64
        )
        rows = np.stack([
            tok.astype(np.int64), pos.astype(np.int64),
            np.round(temps.astype(np.float64) * 1e6).astype(np.int64),
            seeds.astype(np.int64),
        ], axis=1)
        head, rows, prompt = gw._broadcast_tick(
            multihost_utils,
            (head, rows, np.zeros((1, PROMPT_LEN), np.int32)),
            slots, PROMPT_LEN,
        )
        ticks["decode"] += 1
        return gw._execute_tick(pool, head, rows, prompt)

    def idle():
        head, rows, prompt = gw._broadcast_tick(
            multihost_utils, None, slots, PROMPT_LEN
        )
        assert gw._execute_tick(pool, head, rows, prompt) is None
        ticks["noop"] += 1

    engine = SlotEngine(
        prefill_fn, decode_fn, slots, MAX_LEN, PROMPT_LEN,
        queue_timeout_s=120, on_idle=idle, idle_every_s=0.01,
    )
    try:
        results = engine.submit(PROMPTS, NEW)
        oracles = [_oracle(config, params, p, NEW) for p in PROMPTS]
        assert results == oracles
        assert ticks["admit"] == len(PROMPTS)
        assert ticks["decode"] >= NEW - 1
        # idle NOOP ticks keep the gang meeting between requests
        deadline = time.monotonic() + 5
        while not ticks["noop"] and time.monotonic() < deadline:
            time.sleep(0.01)
        assert ticks["noop"] >= 1
    finally:
        engine.stop()
