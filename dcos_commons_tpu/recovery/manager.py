"""DefaultRecoveryPlanManager: synthesize recovery steps from failures.

Reference: recovery/DefaultRecoveryPlanManager.java — updatePlan
(:164) scans the state store for failed tasks each status update and
appends recovery steps for pods not already being recovered; the
FailureMonitor decides TRANSIENT (relaunch in place, reservations
kept) vs PERMANENT (destroy + replace, :378-420); per-service
RecoveryPlanOverriders may replace the default steps with a custom
phase (Cassandra seed-replace choreography is the reference example).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Set

from dcos_commons_tpu.common import Label, TaskState, TaskStatus, task_name_of
from dcos_commons_tpu.plan.backoff import Backoff
from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.plan import RECOVERY_PLAN_NAME, Plan
from dcos_commons_tpu.plan.plan_manager import PlanManager
from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.plan.step import (
    DeploymentStep,
    PodInstanceRequirement,
    RecoveryType,
    Step,
)
from dcos_commons_tpu.plan.strategy import ParallelStrategy
from dcos_commons_tpu.recovery.monitor import FailureMonitor, NeverFailureMonitor
from dcos_commons_tpu.specification.specs import (
    GoalState,
    ServiceSpec,
    pod_instance_name,
    task_full_name,
)
from dcos_commons_tpu.state.state_store import StateStore

# A RecoveryPlanOverrider may return a replacement Phase for a failed
# pod instance (reference: RecoveryPlanOverrider(Factory)); return
# None to keep the default single-step recovery.
RecoveryPlanOverrider = Callable[
    [str, List[int], RecoveryType], Optional[Phase]
]


class DefaultRecoveryPlanManager(PlanManager):
    def __init__(
        self,
        spec: ServiceSpec,
        state_store: StateStore,
        failure_monitor: Optional[FailureMonitor] = None,
        backoff: Optional[Backoff] = None,
        overriders: Optional[List[RecoveryPlanOverrider]] = None,
        externally_managed: Optional[Callable[[str], bool]] = None,
    ):
        self._spec = spec
        self._state_store = state_store
        self._monitor = failure_monitor or NeverFailureMonitor()
        self._backoff = backoff
        self._overriders = list(overriders or [])
        # pods with incomplete work in another plan (deploy/update) are
        # that plan's responsibility — recovering them here would race
        # the rollout (reference: recovery defers to dirtied assets)
        self._externally_managed = externally_managed or (lambda _name: False)
        self._lock = threading.RLock()
        # active recovery elements keyed by pod instance name
        self._phases: Dict[str, Phase] = {}
        self._plan = Plan(RECOVERY_PLAN_NAME, [], ParallelStrategy())

    def set_spec(self, spec: ServiceSpec) -> None:
        with self._lock:
            self._spec = spec

    # -- PlanManager --------------------------------------------------

    def get_plan(self) -> Plan:
        with self._lock:
            self._prune_completed()
            self._plan.phases = list(self._phases.values())
            return self._plan

    def get_candidates(self, dirty_assets: Set[str]) -> List[Step]:
        with self._lock:
            self._refresh()
            return self.get_plan().candidates(dirty_assets)

    def update(self, status: TaskStatus) -> None:
        with self._lock:
            for phase in self._phases.values():
                phase.update(status)
            self._refresh()

    # -- plan synthesis ----------------------------------------------

    def _prune_completed(self) -> None:
        for key in [k for k, p in self._phases.items() if p.is_complete]:
            del self._phases[key]

    def _refresh(self) -> None:
        """Reference: updatePlan (DefaultRecoveryPlanManager.java:164)."""
        self._prune_completed()
        failed = self._find_failed_pods()
        for (pod_type, instances), recovery_type in failed.items():
            key = pod_instance_name(pod_type, instances[0])
            if any(
                self._externally_managed(pod_instance_name(pod_type, i))
                for i in instances
            ):
                continue
            existing = self._phases.get(key)
            if existing is not None:
                # escalate in place: TRANSIENT phase upgraded if the
                # monitor now says PERMANENT (reference :378-420)
                if recovery_type is RecoveryType.PERMANENT:
                    for step in existing.steps:
                        if isinstance(step, DeploymentStep) and \
                                step.requirement.recovery_type is RecoveryType.TRANSIENT:
                            step.requirement.recovery_type = RecoveryType.PERMANENT
                continue
            phase = self._make_phase(pod_type, list(instances), recovery_type)
            if phase is not None:
                self._phases[key] = phase

    def _find_failed_pods(self) -> Dict[tuple, RecoveryType]:
        """Scan stored statuses for tasks needing recovery, grouped by
        pod instance (whole pod for gang pods)."""
        out: Dict[tuple, RecoveryType] = {}
        for pod in self._spec.pods:
            gang_failed: Set[int] = set()
            gang_type = RecoveryType.TRANSIENT
            for index in range(pod.count):
                for task_spec in pod.tasks:
                    full = task_full_name(pod.type, index, task_spec.name)
                    info = self._state_store.fetch_task(full)
                    status = self._state_store.fetch_status(full)
                    if info is None or status is None:
                        continue
                    needs, rtype = self._needs_recovery(
                        full, info, status, task_spec.goal
                    )
                    if not needs:
                        continue
                    if pod.gang:
                        gang_failed.add(index)
                        if rtype is RecoveryType.PERMANENT:
                            gang_type = RecoveryType.PERMANENT
                    else:
                        out[(pod.type, (index,))] = rtype
            if pod.gang and gang_failed:
                # one worker down takes the whole slice through recovery
                out[(pod.type, tuple(range(pod.count)))] = gang_type
        return out

    def _needs_recovery(self, full, info, status, goal):
        if info.labels.get(Label.PERMANENTLY_FAILED):
            return True, RecoveryType.PERMANENT
        if not status.state.is_terminal:
            self._monitor.clear(full)
            return False, RecoveryType.NONE
        # a terminal state satisfying the goal is success, not failure:
        # FINISHED satisfies FINISH/ONCE; nothing terminal satisfies
        # RUNNING (even exit 0 means the server died — relaunch it)
        if goal in (GoalState.FINISH, GoalState.ONCE) and \
                status.state is TaskState.FINISHED:
            return False, RecoveryType.NONE
        if self._monitor.has_failed_permanently(full, status):
            # stamp the label so the escalation survives restart
            self._state_store.store_tasks(
                [info.with_label(Label.PERMANENTLY_FAILED, "true")]
            )
            return True, RecoveryType.PERMANENT
        return True, RecoveryType.TRANSIENT

    def _make_phase(
        self, pod_type: str, instances: List[int], recovery_type: RecoveryType
    ) -> Optional[Phase]:
        for overrider in self._overriders:
            phase = overrider(pod_type, instances, recovery_type)
            if phase is not None:
                return phase
        pod = self._spec.pod(pod_type)
        requirement = PodInstanceRequirement(
            pod=pod, instances=instances, recovery_type=recovery_type
        )
        name = f"recover-{pod_instance_name(pod_type, instances[0])}" if len(
            instances
        ) == 1 else f"recover-{pod_type}-gang"
        step = DeploymentStep(name, requirement, backoff=self._backoff)
        return Phase(name, [step], ParallelStrategy())
