"""Preemption, maintenance windows, and elastic re-slicing (ISSUE 13).

Covers the whole spine: inventory host states and placement
exclusion, the drain/preempt/up operator verbs (HTTP + journal),
pre-kill draining in /v1/endpoints, the gang-granular recovery plan
(kill survivors -> unreserve -> re-place honoring torus adjacency),
elastic shrink with surplus trim, the preemption-storm chaos matrix
(every span-boundary kind, storm-within-recovery, scheduler-kill
composition), checkpoint fencing of a zombie pre-preemption writer,
bit-identical elastic restore across a dp re-layout, and the health
auto-replace seam.
"""

import json
import urllib.error
import urllib.request

import pytest

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.offer.inventory import (
    SliceInventory,
    TpuHost,
    make_test_fleet,
)
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    DrainHost,
    ExpectDeploymentComplete,
    HostUp,
    PreemptHost,
    SendTaskRunning,
    ServiceTestRunner,
)

GANG_YAML = """
name: preemptsvc
pods:
  trainer:
    count: 4
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
    tasks:
      worker:
        goal: RUNNING
        cmd: "train"
        cpus: 1.0
        memory: 256
"""

ELASTIC_YAML = GANG_YAML.replace(
    "      topology: 4x4\n",
    "      topology: 4x4\n      elastic: true\n      min-hosts: 2\n",
).replace("name: preemptsvc", "name: elasticsvc")


def two_slice_fleet():
    return make_test_fleet("pod-a") + make_test_fleet("pod-b")


def deploy_gang(yaml_text=GANG_YAML, hosts=None):
    runner = ServiceTestRunner(
        yaml_text, hosts=hosts if hosts is not None else two_slice_fleet()
    )
    runner.run([
        AdvanceCycles(1),
        *[SendTaskRunning(f"trainer-{i}-worker") for i in range(4)],
        ExpectDeploymentComplete(),
    ])
    return runner


def gang_hosts(scheduler):
    return {
        info.name: info.agent_id
        for info in scheduler.state_store.fetch_tasks()
    }


def ack_new_launches(world, acked):
    """RUNNING-ack every WAL'd launch whose process is still alive."""
    scheduler = world.scheduler
    for info in list(world.agent.launched):
        if info.task_id in acked:
            continue
        if info.task_id not in world.agent.active_task_ids():
            continue
        status = scheduler.state_store.fetch_status(info.name)
        if status is not None and status.task_id == info.task_id and \
                status.state is TaskState.STAGING:
            acked.add(info.task_id)
            world.agent.send(TaskStatus(
                task_id=info.task_id, state=TaskState.RUNNING,
                ready=True, agent_id=info.agent_id,
            ))


def drive_to_recovered(world, cycles=20):
    acked = set()
    for _ in range(cycles):
        world.scheduler.run_cycle()
        ack_new_launches(world, acked)
        if world.scheduler.plan("recovery").is_complete:
            return True
    return False


# -- inventory host states --------------------------------------------


def test_host_states_and_placement_exclusion():
    inv = SliceInventory(make_test_fleet("pod-a"))
    host = "pod-a-h0-0"
    assert inv.host_state(host) == "up"
    gen = inv.topology_generation

    assert inv.set_maintenance(host)
    assert inv.host_state(host) == "maintenance"
    assert inv.topology_generation > gen
    # maintenance: still UP (running work keeps running)...
    assert inv.is_up(host)
    # ...but hard-excluded from candidate sets and snapshots
    assert host not in inv._up_ids()
    snaps = inv.snapshots(_EmptyView())
    assert host not in {s.host.host_id for s in snaps}

    assert inv.clear_host_state(host)
    assert inv.host_state(host) == "up"
    assert host in inv._up_ids()

    assert inv.set_preempted(host)
    assert inv.host_state(host) == "preempted"
    assert not inv.is_up(host)  # preempted = down with a cause
    assert host not in inv._up_ids()
    # mark_up (agent heartbeat) sheds the preemption mark
    inv.mark_up(host)
    assert inv.host_state(host) == "up"

    # unknown hosts are refused, never dirty the fleet
    gen = inv.topology_generation
    assert not inv.set_preempted("nope")
    assert not inv.set_maintenance("nope")
    assert not inv.clear_host_state("nope")
    assert inv.topology_generation == gen


def test_maintenance_window_recorded():
    inv = SliceInventory(make_test_fleet("pod-a"))
    assert inv.set_maintenance("pod-a-h0-0", window_end=123.0)
    assert inv.maintenance_window("pod-a-h0-0") == 123.0
    assert inv.maintenance_hosts() == {"pod-a-h0-0": 123.0}
    states = inv.host_states()
    assert states["pod-a-h0-0"]["state"] == "maintenance"
    assert states["pod-a-h0-0"]["window_end"] == 123.0
    stats = inv.debug_stats()
    assert stats["maintenance_hosts"] == {"pod-a-h0-0": 123.0}


class _EmptyView:
    def reserved_on(self, host_id):
        return []


def test_drain_blocks_new_placement_but_not_inplace_relaunch():
    """Soft drain: a maintenance host takes no NEW work, but a
    TRANSIENT crash of a pod already there relaunches in place."""
    yaml_text = """
name: drainsvc
pods:
  app:
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
"""
    runner = ServiceTestRunner(
        yaml_text, hosts=[TpuHost(host_id=f"h{i}") for i in range(2)]
    )
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("app-0-server"),
        ExpectDeploymentComplete(),
    ])
    world = runner.world
    placed = gang_hosts(world.scheduler)["app-0-server"]
    runner.run([DrainHost(placed)])
    # transient crash: relaunch lands IN PLACE on the draining host
    from dcos_commons_tpu.testing import SendTaskFailed

    runner.run([SendTaskFailed("app-0-server"), AdvanceCycles(2)])
    assert gang_hosts(world.scheduler)["app-0-server"] == placed
    # journal carries the drain
    kinds = [e["verb"] for e in world.scheduler.journal.events(
        kinds=("host",))]
    assert "drain" in kinds


# -- the gang recovery plan -------------------------------------------


def test_preemption_synthesizes_gang_recovery_plan():
    runner = deploy_gang()
    world = runner.world
    scheduler = world.scheduler
    before = gang_hosts(scheduler)
    victim = before["trainer-0-worker"]
    old_ids = {
        info.name: info.task_id
        for info in scheduler.state_store.fetch_tasks()
    }

    runner.run([PreemptHost(victim)])
    # the choreography exists with the right shape and order
    plan = scheduler.plan("recovery")
    steps = [s.name for p in plan.phases for s in p.steps]
    assert steps == [
        "kill-trainer-survivors", "unreserve-trainer-slice",
        "replace-trainer-gang", "trim-trainer-surplus",
    ]
    assert getattr(plan.phases[0], "gang_recovery", False)

    assert drive_to_recovered(world)
    after = gang_hosts(scheduler)
    # whole gang re-placed (fresh ids), torus adjacency held: all four
    # workers share ONE slice, and nothing sits on the preempted host
    new_ids = {
        info.name: info.task_id
        for info in scheduler.state_store.fetch_tasks()
    }
    assert set(after) == set(before)
    assert all(new_ids[n] != old_ids[n] for n in old_ids)
    slices = {h.rsplit("-h", 1)[0] for h in after.values()}
    assert len(slices) == 1
    assert victim not in after.values()
    # zero reservations left on the preempted host, no double-claims
    assert not [
        r for r in scheduler.ledger.all() if r.host_id == victim
    ]
    claimed = set()
    for r in scheduler.ledger.all():
        for chip in r.chip_ids:
            assert (r.host_id, chip) not in claimed
            claimed.add((r.host_id, chip))
    # survivors were killed (wedged in a dead collective)
    killed = set(world.agent.killed_names())
    assert {"trainer-1-worker", "trainer-2-worker",
            "trainer-3-worker"} <= killed
    # journal tells the story
    verbs = [
        e.get("verb") for e in scheduler.journal.events(
            kinds=("host", "recovery"))
    ]
    assert "preempt" in verbs and "unreserve" in verbs


def test_elastic_shrink_when_capacity_cannot_return():
    """No same-size sub-slice exists and nothing promises capacity
    back: the elastic gang shrinks to a clean divisor, the surplus
    instances are erased, and the shrunken env contract is coherent
    (scaled topology, scaled worker count)."""
    hosts = make_test_fleet("pod-a") + make_test_fleet(
        "pod-b", host_grid=(2, 1)
    )
    runner = deploy_gang(ELASTIC_YAML, hosts=hosts)
    world = runner.world
    scheduler = world.scheduler
    placed = gang_hosts(scheduler)
    # two pod-a hosts die: only 2 fully-free hosts exist anywhere
    victims = sorted(set(placed.values()))[:2]
    runner.run([PreemptHost(victims[0]), PreemptHost(victims[1])])
    assert drive_to_recovered(world)
    after = gang_hosts(scheduler)
    assert len(after) == 2  # trainer-2/3 trimmed
    envs = {
        info.name: info.env
        for info in scheduler.state_store.fetch_tasks()
    }
    for env in envs.values():
        assert env["TPU_TOPOLOGY"] == "4x2"
        assert env["TPU_WORKER_COUNT"] == "2"
    # surplus state erased: the failure scan chases no ghosts
    assert scheduler.state_store.fetch_task("trainer-2-worker") is None
    assert scheduler.state_store.fetch_task("trainer-3-worker") is None
    scheduler.run_cycle()
    assert scheduler.plan("recovery").is_complete
    # journaled for the operator
    verbs = [
        e.get("verb")
        for e in scheduler.journal.events(kinds=("recovery",))
    ]
    assert "elastic-shrink" in verbs and "trim-surplus" in verbs


def test_elastic_waits_for_finite_maintenance_window():
    """Drained hosts with a FINITE window promise the capacity back:
    the decision rule waits instead of shrinking, and recovery
    completes at FULL size once the window ends."""
    # a full-size spare slice exists (pod-b) but two of its hosts sit
    # in a finite maintenance window, so full-size placement is
    # temporarily impossible after pod-a loses a host
    hosts = two_slice_fleet()
    runner = deploy_gang(ELASTIC_YAML, hosts=hosts)
    world = runner.world
    scheduler = world.scheduler
    placed = gang_hosts(scheduler)
    gang_slice = sorted(set(placed.values()))[0].rsplit("-h", 1)[0]
    spare_slice = "pod-b" if gang_slice == "pod-a" else "pod-a"
    drained = [f"{spare_slice}-h0-0", f"{spare_slice}-h1-0"]
    runner.run([
        DrainHost(drained[0], window_s=3600.0),
        DrainHost(drained[1], window_s=3600.0),
        PreemptHost(sorted(set(placed.values()))[0]),
    ])
    for _ in range(10):
        scheduler.run_cycle()
    plan = scheduler.plan("recovery")
    replace = [
        s for p in plan.phases for s in p.steps
        if s.name == "replace-trainer-gang"
    ]
    assert replace and replace[0].target_hosts == 4  # no shrink
    assert not plan.is_complete
    # window ends -> the drained hosts return -> full-size recovery
    runner.run([HostUp(drained[0]), HostUp(drained[1])])
    assert drive_to_recovered(world)
    after = gang_hosts(scheduler)
    assert len(after) == 4
    assert {h.rsplit("-h", 1)[0] for h in after.values()} == {
        spare_slice
    }


def test_elastic_decision_rule_pure_properties():
    from dcos_commons_tpu.recovery.elastic import (
        ElasticPolicy,
        decide_resize,
        shrink_candidates,
        shrink_topology,
        shrunken_pod,
    )
    from dcos_commons_tpu.specification.specs import TpuSpec

    off = ElasticPolicy(enabled=False)
    on = ElasticPolicy(enabled=True, min_hosts=2, shrink_after_declines=3)

    assert decide_resize(8, 8, 99, off, False).target_hosts == 8
    assert decide_resize(8, 8, 2, on, False).target_hosts == 8  # budget
    assert decide_resize(8, 8, 3, on, True).target_hosts == 8   # window
    assert decide_resize(8, 8, 3, on, False).target_hosts == 4  # shrink
    # shrink targets are divisors of the FULL size at/above the floor
    assert shrink_candidates(8, 2) == [4, 2]
    assert shrink_candidates(6, 1) == [3, 2, 1]
    assert shrink_candidates(4, 3) == []  # 3 does not divide 4
    # topology scales by halving the largest dimension
    tpu = TpuSpec(chips_per_host=4, topology="4x4")
    assert shrink_topology(tpu, 2) == "4x2"
    assert shrink_topology(tpu, 1) == "2x2"
    # a pod copy carries the scaled shape; the spec keeps full width
    from dcos_commons_tpu.specification.yaml_spec import from_yaml

    pod = from_yaml(ELASTIC_YAML).pod("trainer")
    small = shrunken_pod(pod, 2)
    assert small.count == 2 and small.tpu.topology == "4x2"
    assert pod.count == 4 and pod.tpu.topology == "4x4"
    # multi-slice gangs shrink by WHOLE slices (ISSUE 20): the
    # per-slice topology is untouched, only `slices` (the dcn axis)
    # drops — and a target that is not a slice multiple is refused
    import dataclasses as _dc

    multi = _dc.replace(pod, count=8, tpu=_dc.replace(pod.tpu, slices=2))
    one_slice = shrunken_pod(multi, 4)
    assert one_slice.count == 4 and one_slice.tpu.slices == 1
    assert one_slice.tpu.topology == "4x4"  # per-slice shape untouched
    assert shrunken_pod(multi, 3) is None   # not a slice multiple
    assert multi.count == 8 and multi.tpu.slices == 2  # spec untouched
    # decide_resize shrinks onto divisors of the FULL size even from
    # an already-shrunk width (8 -> 4 -> 2, never 3)
    assert decide_resize(4, 8, 3, on, False).target_hosts == 2
    # multi-slice quantum: valid widths are whole-slice multiples
    from dcos_commons_tpu.recovery.elastic import slice_shrink_candidates

    assert slice_shrink_candidates(12, 1, 4) == [8, 4]
    assert slice_shrink_candidates(8, 5, 4) == []  # floor above 1 slice
    assert decide_resize(8, 8, 3, on, False, host_quantum=4).target_hosts == 4
    assert decide_resize(12, 12, 3, on, False, host_quantum=4).target_hosts == 8


# -- HTTP surface ------------------------------------------------------


def _get(server, path, expect=200):
    try:
        with urllib.request.urlopen(server.url + path) as resp:
            code, raw = resp.status, resp.read()
    except urllib.error.HTTPError as e:
        code, raw = e.code, e.read()
    assert code == expect, f"GET {path} -> {code}: {raw[:200]}"
    return json.loads(raw.decode("utf-8"))


def _post(server, path, body=None, expect=200):
    data = json.dumps(body).encode() if body is not None else b""
    req = urllib.request.Request(
        server.url + path, method="POST", data=data,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            code, raw = resp.status, resp.read()
    except urllib.error.HTTPError as e:
        code, raw = e.code, e.read()
    assert code == expect, f"POST {path} -> {code}: {raw[:200]}"
    return json.loads(raw.decode("utf-8"))


SERVE_YAML = """
name: servesvc
pods:
  web:
    count: 1
    tasks:
      srv:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
        ports:
          http:
            env-key: PORT_HTTP
"""


def test_host_verbs_and_pre_kill_endpoint_draining():
    """The satellite bugfix: a host entering maintenance flips its
    serve backends to draining in /v1/endpoints while the task is
    still RUNNING and ready — BEFORE any kill fires — so the router
    stops placing new requests there."""
    from dcos_commons_tpu.http import ApiServer

    runner = ServiceTestRunner(
        SERVE_YAML, hosts=[TpuHost(host_id=f"h{i}") for i in range(2)]
    )
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("web-0-srv"),
        ExpectDeploymentComplete(),
    ])
    world = runner.world
    server = ApiServer(world.scheduler).start()
    try:
        hosts = _get(server, "/v1/hosts")["hosts"]
        assert set(hosts) == {"h0", "h1"}
        assert all(row["state"] == "up" for row in hosts.values())

        placed = gang_hosts(world.scheduler)["web-0-srv"]
        endpoint = _get(server, "/v1/endpoints/http")
        row = endpoint["backends"][0]
        assert row["state"] == "TASK_RUNNING" and not row["draining"]
        generation = endpoint["generation"]

        body = _post(
            server, f"/v1/hosts/{placed}/drain", {"window_s": 60}
        )
        assert body["changed"] and body["state"] == "maintenance"
        endpoint = _get(server, "/v1/endpoints/http")
        row = endpoint["backends"][0]
        # the task was NOT killed — it drains purely on host state
        assert row["state"] == "TASK_RUNNING" and row["ready"]
        assert row["draining"]
        assert endpoint["generation"] != generation

        body = _post(server, f"/v1/hosts/{placed}/up")
        assert body["changed"]
        row = _get(server, "/v1/endpoints/http")["backends"][0]
        assert not row["draining"]

        # preempt over HTTP: LOST tasks reported, state flips
        body = _post(server, f"/v1/hosts/{placed}/preempt")
        assert body["tasks_lost"] == ["web-0-srv"]
        assert _get(server, "/v1/hosts")["hosts"][placed]["state"] == \
            "preempted"

        _post(server, "/v1/hosts/nope/drain", {}, expect=404)
        _post(server, "/v1/hosts/nope/preempt", expect=404)
    finally:
        server.stop()


def test_cli_host_verbs():
    from dcos_commons_tpu.cli.commands import build_parser, run
    from dcos_commons_tpu.http import ApiServer

    runner = ServiceTestRunner(
        SERVE_YAML, hosts=[TpuHost(host_id=f"h{i}") for i in range(2)]
    )
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("web-0-srv"),
        ExpectDeploymentComplete(),
    ])
    server = ApiServer(runner.world.scheduler).start()
    try:
        parser = build_parser()
        out = run(parser.parse_args(
            ["--url", server.url, "host", "list"]
        ))
        assert set(out["hosts"]) == {"h0", "h1"}
        out = run(parser.parse_args(
            ["--url", server.url, "host", "drain", "h0",
             "--window-s", "30"]
        ))
        assert out["state"] == "maintenance"
        out = run(parser.parse_args(
            ["--url", server.url, "host", "up", "h0"]
        ))
        assert out["state"] == "up"
    finally:
        server.stop()


# -- preemption storms (chaos) ----------------------------------------


def test_storm_single_preemption_converges():
    from dcos_commons_tpu.testing.chaos import (
        STORM_START,
        PreemptSpec,
        PreemptionStorm,
    )

    storm = PreemptionStorm([PreemptSpec(at=STORM_START, hosts=1)])
    try:
        report = storm.run(timeout_s=60.0)
    finally:
        storm.shutdown()
    assert report.converged and len(report.preempted) == 1


def test_storm_second_host_mid_recovery():
    """The storm-within-recovery case: a second host dies while the
    first loss's gang recovery plan is mid-flight.  Converges with
    zero double-reservations and exactly one surviving incarnation
    (assert_invariants inside run())."""
    from dcos_commons_tpu.testing.chaos import (
        RECOVERY_ACTIVE,
        STORM_START,
        PreemptSpec,
        PreemptionStorm,
    )

    storm = PreemptionStorm([
        PreemptSpec(at=STORM_START, hosts=1),
        PreemptSpec(at=RECOVERY_ACTIVE, occurrence=2, hosts=1),
    ])
    try:
        report = storm.run(timeout_s=60.0)
    finally:
        storm.shutdown()
    assert report.converged and len(report.preempted) == 2


def test_storm_composed_with_scheduler_kill():
    """Preemption AND failover at one boundary: the successor
    scheduler inherits the half-done recovery and converges it."""
    from dcos_commons_tpu.testing.chaos import (
        STORM_START,
        PreemptSpec,
        PreemptionStorm,
    )

    storm = PreemptionStorm([
        PreemptSpec(at=STORM_START, hosts=1),
        PreemptSpec(at="post-wal", occurrence=1, hosts=1,
                    kill_scheduler=True),
    ])
    try:
        report = storm.run(timeout_s=60.0)
    finally:
        storm.shutdown()
    assert report.converged and report.incarnations == 2


@pytest.mark.chaos
@pytest.mark.slow
def test_storm_matrix_every_kill_point():
    """K>=2 host kills across EVERY span-boundary kind, including
    mid-recovery-plan — the acceptance matrix."""
    from dcos_commons_tpu.testing.chaos import (
        CHAOS_KINDS,
        RECOVERY_ACTIVE,
        STORM_START,
        PreemptSpec,
        PreemptionStorm,
    )

    cases = [
        [PreemptSpec(at=STORM_START, hosts=2)],
        [
            PreemptSpec(at=STORM_START, hosts=2),
            PreemptSpec(at=RECOVERY_ACTIVE, occurrence=1, hosts=1),
        ],
    ]
    for kind in CHAOS_KINDS:
        cases.append([
            PreemptSpec(at=STORM_START, hosts=1),
            PreemptSpec(at=kind, occurrence=1, hosts=1),
        ])
        cases.append([
            PreemptSpec(at=STORM_START, hosts=1),
            PreemptSpec(at=kind, occurrence=1, hosts=1,
                        kill_scheduler=True),
        ])
    for specs in cases:
        storm = PreemptionStorm(specs)
        try:
            report = storm.run(timeout_s=60.0)
        finally:
            storm.shutdown()
        assert report.converged, report.describe()


# -- checkpoint fencing + elastic restore -----------------------------


def test_zombie_preempted_writer_late_save_is_fenced(tmp_path):
    """A writer that survived preemption (network partition, zombie
    VM) flushes one last save AFTER recovery relaunched a newer
    incarnation: the save must be refused and restore must keep the
    newer incarnation's frontier."""
    import numpy as np

    from dcos_commons_tpu.utils.checkpoint import (
        StaleWriterError,
        claim_incarnation,
        restore_checkpoint,
        save_checkpoint,
    )

    ckpt = str(tmp_path / "ckpt")
    tree_v1 = {"w": np.arange(4, dtype=np.float32)}
    inc1 = claim_incarnation(ckpt)
    save_checkpoint(ckpt, 10, tree_v1, incarnation=inc1)

    # the gang recovery relaunch claims the next incarnation and
    # resumes from the newest fenced checkpoint
    inc2 = claim_incarnation(ckpt)
    assert inc2 > inc1
    tree_v2 = {"w": np.arange(4, dtype=np.float32) * 2}
    save_checkpoint(ckpt, 12, tree_v2, incarnation=inc2)

    # the zombie's late flush is refused...
    with pytest.raises(StaleWriterError):
        save_checkpoint(
            ckpt, 14, {"w": np.full(4, -1.0, np.float32)},
            incarnation=inc1,
        )
    # ...and the frontier still belongs to the live incarnation
    restored, step = restore_checkpoint(ckpt, {"w": np.zeros(4, np.float32)})
    assert step == 12
    assert np.array_equal(restored["w"], tree_v2["w"])


def test_elastic_restore_is_bit_identical_across_dp_shrink():
    """8-host -> 4-host DP shrink: params AND optimizer state restore
    bit-identically (same leaves, new layout), and the shrunken mesh
    trains.  Runs on the 8 forced CPU devices conftest provides."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dcos_commons_tpu.models import (
        config_from_env,
        init_params,
        make_train_step,
    )
    from dcos_commons_tpu.parallel.mesh import MeshSpec, make_mesh
    from dcos_commons_tpu.utils import (
        restore_checkpoint,
        save_checkpoint,
        synthetic_tokens,
    )

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 forced host devices")
    config = config_from_env(
        {"D_MODEL": "32", "N_LAYERS": "1", "N_HEADS": "2",
         "N_KV_HEADS": "2", "D_FF": "64", "VOCAB": "64",
         "SEQ_LEN": "16"},
        dtype=jnp.float32,
    )
    optimizer = optax.adamw(1e-3)

    mesh8 = make_mesh(MeshSpec(dp=8), devices=devices[:8])
    with mesh8:
        params = init_params(config, jax.random.key(0))
        opt_state = optimizer.init(params)
        step_fn = make_train_step(config, optimizer, mesh=mesh8)
        tokens, targets = synthetic_tokens(
            jax.random.key(1), 8, config.max_seq, config.vocab
        )
        params, opt_state, _loss = step_fn(
            params, opt_state, tokens, targets
        )
        state8 = {"params": params, "opt_state": opt_state}
        import tempfile

        ckpt = tempfile.mkdtemp(prefix="elastic-ckpt-")
        save_checkpoint(ckpt, 1, state8)
        flat8 = jax.tree.leaves(state8)

    # the SHRUNKEN mesh: same model axes, half the dp width
    mesh4 = make_mesh(MeshSpec(dp=4), devices=devices[:4])
    with mesh4:
        params4 = init_params(config, jax.random.key(7))  # junk seed
        state4 = {"params": params4, "opt_state": optimizer.init(params4)}
        restored, step = restore_checkpoint(ckpt, state4)
        assert step == 1
        flat4 = jax.tree.leaves(restored)
        assert len(flat4) == len(flat8)  # same leaves...
        for a, b in zip(flat8, flat4):
            assert np.array_equal(np.asarray(a), np.asarray(b)), \
                "elastic restore must be bit-identical"
        # ...new layout: the restored tree trains on the 4-wide mesh
        step_fn4 = make_train_step(config, optimizer, mesh=mesh4)
        tokens4, targets4 = synthetic_tokens(
            jax.random.key(1), 8, config.max_seq, config.vocab
        )
        p, o, loss = step_fn4(
            restored["params"], restored["opt_state"], tokens4, targets4
        )
        assert np.isfinite(float(loss))


def test_resume_from_fenced_checkpoint_matches_unpreempted_run():
    """Training resumed from the newest fenced checkpoint produces
    EXACTLY the loss sequence an unpreempted run produces from that
    checkpoint — preemption recovery loses wall time, never math."""
    import jax
    import jax.numpy as jnp
    import optax

    from dcos_commons_tpu.models import (
        config_from_env,
        init_params,
        make_train_step,
    )
    from dcos_commons_tpu.utils import (
        restore_checkpoint,
        save_checkpoint,
        synthetic_tokens,
    )

    config = config_from_env(
        {"D_MODEL": "32", "N_LAYERS": "1", "N_HEADS": "2",
         "N_KV_HEADS": "2", "D_FF": "64", "VOCAB": "64",
         "SEQ_LEN": "16"},
        dtype=jnp.float32,
    )
    optimizer = optax.adamw(1e-3)
    step_fn = make_train_step(config, optimizer, donate=False)
    tokens, targets = synthetic_tokens(
        jax.random.key(1), 4, config.max_seq, config.vocab
    )

    def run(params, opt_state, start, steps, save_at=None, ckpt=None):
        losses = []
        for i in range(start, steps):
            params, opt_state, loss = step_fn(
                params, opt_state, tokens, targets
            )
            losses.append(float(loss))
            if save_at is not None and i + 1 == save_at:
                save_checkpoint(
                    ckpt, i + 1,
                    {"params": params, "opt_state": opt_state},
                )
        return params, opt_state, losses

    import tempfile

    ckpt = tempfile.mkdtemp(prefix="resume-ckpt-")
    params = init_params(config, jax.random.key(0))
    opt_state = optimizer.init(params)
    # the reference run: 6 uninterrupted steps, checkpoint at step 3
    _p, _o, full_losses = run(
        params, opt_state, 0, 6, save_at=3, ckpt=ckpt
    )
    # the preempted run: restore the step-3 checkpoint, finish 3..6
    like = {
        "params": init_params(config, jax.random.key(9)),
        "opt_state": opt_state,
    }
    state, start = restore_checkpoint(ckpt, like)
    assert start == 3
    _p, _o, resumed_losses = run(
        state["params"], state["opt_state"], start, 6
    )
    assert resumed_losses == full_losses[3:]


def test_elastic_reshard_contract():
    from dcos_commons_tpu.parallel.mesh import MeshSpec, elastic_reshard_ok

    assert elastic_reshard_ok(MeshSpec(dp=8), MeshSpec(dp=4))
    assert elastic_reshard_ok(
        MeshSpec(dcn=2, dp=4, tp=4), MeshSpec(dcn=1, dp=2, tp=4)
    )
    # any model-axis change is NOT a pure re-layout
    assert not elastic_reshard_ok(MeshSpec(dp=4, tp=2), MeshSpec(dp=8))
    assert not elastic_reshard_ok(
        MeshSpec(dp=4, fsdp=2), MeshSpec(dp=8, fsdp=1)
    )


# -- health auto-replace seam -----------------------------------------


def test_auto_replace_straggler_gang_member():
    """A confirmed straggler episode on a gang-member host triggers
    exactly ONE automated pod replace (and only with the default-off
    gate opened); the replace rides the gang recovery plan."""
    from dcos_commons_tpu.scheduler.config import SchedulerConfig

    runner = ServiceTestRunner(
        GANG_YAML,
        hosts=two_slice_fleet(),
        scheduler_config=SchedulerConfig(
            backoff_enabled=False, revive_capacity=10**9,
            health_auto_replace=True,
        ),
    )
    runner.run([
        AdvanceCycles(1),
        *[SendTaskRunning(f"trainer-{i}-worker") for i in range(4)],
        ExpectDeploymentComplete(),
    ])
    world = runner.world
    scheduler = world.scheduler
    monitor = scheduler.health
    assert monitor.auto_replace
    placed = gang_hosts(scheduler)
    slow = placed["trainer-0-worker"]

    def steplogs(slow_wall):
        out = {}
        for name, host in placed.items():
            wall = slow_wall if host == slow else 1.0
            out[host] = [[
                {"step": s, "wall_s": wall, "blocked_s": 0.0}
                for s in range(5)
            ]]
        return out

    # feed the detector directly (the telemetry fan-in is exercised
    # by test_health; this test owns the ACTION seam).  Collection is
    # parked far in the future so _observe scores the injected
    # snapshot instead of re-collecting over the FakeAgent.
    monitor.telemetry_interval_s = 1e9
    monitor._last_telemetry = 1e18
    monitor._steplogs_by_host = steplogs(10.0)
    monitor._telemetry_seq += 1
    events = monitor._observe(scheduler, None)
    replaces = [e for e in events if e.get("verb") == "auto-replace"]
    assert len(replaces) == 1
    assert replaces[0]["host"] == slow
    # the PERMANENT escalation landed: gang recovery synthesizes
    scheduler.run_cycle()
    plan = scheduler.plan("recovery")
    assert any(
        getattr(p, "gang_recovery", False) for p in plan.phases
    )
    # still-confirmed episode on the next pass: NO second replace
    monitor._steplogs_by_host = steplogs(10.0)
    monitor._telemetry_seq += 1
    events = monitor._observe(scheduler, None)
    assert not [e for e in events if e.get("verb") == "auto-replace"]
    # journal carries the audited action
    health_events = scheduler.journal.events(kinds=("health",))
    assert any(
        e.get("verb") == "auto-replace" for e in health_events
    )


def test_auto_replace_default_off():
    runner = deploy_gang()
    monitor = runner.world.scheduler.health
    assert not monitor.auto_replace
