"""End-to-end scheduler tests with real subprocess tasks.

The minimum end-to-end slice of SURVEY.md section 7: YAML -> spec ->
plans -> evaluation over a fake fleet -> REAL processes launched by
LocalProcessAgent -> statuses drive the plan to COMPLETE.  Mirrors the
reference's ServiceTestRunner-based ServiceTest.java flows (deploy,
task kill -> recovery, scheduler restart).
"""

import os
import time

import pytest

from dcos_commons_tpu.agent import LocalProcessAgent
from dcos_commons_tpu.common import TaskState
from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
from dcos_commons_tpu.recovery.monitor import TestingFailureMonitor
from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
from dcos_commons_tpu.specification import from_yaml
from dcos_commons_tpu.storage import FileWalPersister, MemPersister

@pytest.fixture(autouse=True)
def _lock_order_checker():
    """sdklint's dynamic half rides every e2e test here: the scheduler
    cycle nests DefaultScheduler._lock over state-store/plan/agent
    locks, and any cycle observed in that nesting graph is a latent
    deadlock the static rules cannot see."""
    from conftest import lockcheck_guard

    yield from lockcheck_guard()


HELLO_YAML = """
name: hello-world
pods:
  hello:
    count: 2
    placement: 'max-per-host:1'
    tasks:
      server:
        goal: RUNNING
        cmd: "echo hello-$POD_INSTANCE_INDEX > out.txt && sleep 60"
        cpus: 0.1
        memory: 32
"""

ONCE_YAML = """
name: once-svc
pods:
  job:
    count: 1
    tasks:
      run:
        goal: FINISH
        cmd: "echo done > result.txt"
        cpus: 0.1
        memory: 32
"""


def cpu_hosts(n):
    return [TpuHost(host_id=f"h{i}") for i in range(n)]


def build_scheduler(yaml_text, hosts, tmp_path, persister=None, **cfg_kw):
    spec = from_yaml(yaml_text)
    config = SchedulerConfig(
        sandbox_root=str(tmp_path / "sandboxes"),
        backoff_enabled=False,
        **cfg_kw,
    )
    builder = SchedulerBuilder(spec, config, persister or MemPersister())
    builder.set_inventory(SliceInventory(hosts))
    builder.set_agent(LocalProcessAgent(str(tmp_path / "sandboxes")))
    return builder


def drive(scheduler, until, timeout_s=15.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        scheduler.run_cycle()
        if until(scheduler):
            return True
        time.sleep(interval_s)
    return False


def deploy_complete(s):
    return s.deploy_manager.get_plan().is_complete


def test_deploy_to_complete(tmp_path):
    scheduler = build_scheduler(HELLO_YAML, cpu_hosts(3), tmp_path).build()
    try:
        assert drive(scheduler, deploy_complete), _debug(scheduler)
        # placement respected: 2 pods on 2 distinct hosts
        agents = {i.agent_id for i in scheduler.state_store.fetch_tasks()}
        assert len(agents) == 2
        # the tasks really ran: their sandboxes contain the output
        out = os.path.join(
            scheduler.agent.sandbox_of("hello-0-server"), "out.txt"
        )
        assert open(out).read().strip() == "hello-0"
    finally:
        scheduler.agent.shutdown()


def test_finish_goal_task_completes(tmp_path):
    scheduler = build_scheduler(ONCE_YAML, cpu_hosts(1), tmp_path).build()
    try:
        assert drive(scheduler, deploy_complete), _debug(scheduler)
        status = scheduler.state_store.fetch_status("job-0-run")
        assert status.state == TaskState.FINISHED
        # FINISHED FINISH-goal tasks are not "recovered"
        scheduler.run_cycle()
        assert scheduler.recovery_manager.get_plan().phases == []
    finally:
        scheduler.agent.shutdown()


def test_task_kill_triggers_recovery(tmp_path):
    scheduler = build_scheduler(HELLO_YAML, cpu_hosts(3), tmp_path).build()
    try:
        assert drive(scheduler, deploy_complete)
        victim = scheduler.state_store.fetch_task("hello-0-server")
        # kill the process out-of-band (simulates a crash)
        scheduler.agent.kill(victim.task_id)

        def recovered(s):
            info = s.state_store.fetch_task("hello-0-server")
            status = s.state_store.fetch_status("hello-0-server")
            return (
                info.task_id != victim.task_id
                and status.task_id == info.task_id
                and status.state == TaskState.RUNNING
            )

        assert drive(scheduler, recovered), _debug(scheduler)
        # deploy plan unaffected (stays COMPLETE); recovery did the work
        assert scheduler.deploy_manager.get_plan().is_complete
        # relaunch reused the same host (TRANSIENT, in place)
        info2 = scheduler.state_store.fetch_task("hello-0-server")
        assert info2.agent_id == victim.agent_id
    finally:
        scheduler.agent.shutdown()


def test_permanent_failure_replaces(tmp_path):
    spec_builder = build_scheduler(HELLO_YAML, cpu_hosts(3), tmp_path)
    spec_builder.set_failure_monitor(
        TestingFailureMonitor(permanent_tasks={"hello-0-server"})
    )
    scheduler = spec_builder.build()
    try:
        assert drive(scheduler, deploy_complete)
        victim = scheduler.state_store.fetch_task("hello-0-server")
        scheduler.agent.kill(victim.task_id)

        def replaced(s):
            info = s.state_store.fetch_task("hello-0-server")
            status = s.state_store.fetch_status("hello-0-server")
            return (
                info.task_id != victim.task_id
                and status.task_id == info.task_id
                and status.state == TaskState.RUNNING
            )

        assert drive(scheduler, replaced), _debug(scheduler)
        # fresh reservations were claimed; old ones GC'd
        new_info = scheduler.state_store.fetch_task("hello-0-server")
        assert set(new_info.resource_ids) != set(victim.resource_ids)
        live_ids = {r.reservation_id for r in scheduler.ledger.all()}
        assert not (live_ids & set(victim.resource_ids))
    finally:
        scheduler.agent.shutdown()


def test_scheduler_restart_resumes(tmp_path):
    """Crash the scheduler mid-deploy; a rebuilt one finishes the plan.

    Reference: SchedulerRestartServiceTest via ServiceTestRunner state
    handoff (ServiceTest.java:57-77).
    """
    persister = FileWalPersister(str(tmp_path / "state"), fsync=False)
    builder = build_scheduler(HELLO_YAML, cpu_hosts(3), tmp_path, persister)
    scheduler = builder.build()
    agent = scheduler.agent
    # run only until the FIRST pod instance is running
    def first_running(s):
        status = s.state_store.fetch_status("hello-0-server")
        return status is not None and status.state == TaskState.RUNNING
    assert drive(scheduler, first_running)
    assert not scheduler.deploy_manager.get_plan().is_complete

    # "crash": rebuild the whole scheduler over the same persister and
    # the same (still running) agent
    builder2 = build_scheduler(HELLO_YAML, cpu_hosts(3), tmp_path, persister)
    builder2.set_agent(agent)
    restarted = builder2.build()
    try:
        assert drive(restarted, deploy_complete), _debug(restarted)
        # hello-0 was NOT relaunched (still the original task id)
        original = scheduler.state_store.fetch_task("hello-0-server")
        resumed = restarted.state_store.fetch_task("hello-0-server")
        assert resumed.task_id == original.task_id
    finally:
        agent.shutdown()


def test_reconciliation_recovers_wal_only_launch(tmp_path):
    """Crash between WAL and launch: reconciliation -> LOST -> relaunch."""
    persister = FileWalPersister(str(tmp_path / "state"), fsync=False)
    scheduler = build_scheduler(
        HELLO_YAML, cpu_hosts(3), tmp_path, persister
    ).build()
    # manually WAL a launch that never reached the agent
    from dcos_commons_tpu.plan.step import PodInstanceRequirement

    req = PodInstanceRequirement(pod=scheduler.spec.pod("hello"), instances=[0])
    result = scheduler.evaluator.evaluate(req, scheduler.inventory)
    scheduler.ledger.commit(result.reservations)
    scheduler.launch_recorder.record(result.task_infos)
    ghost_id = result.task_infos[0].task_id

    try:
        assert drive(scheduler, deploy_complete), _debug(scheduler)
        info = scheduler.state_store.fetch_task("hello-0-server")
        assert info.task_id != ghost_id  # ghost was declared LOST, relaunched
    finally:
        scheduler.agent.shutdown()


def test_config_update_rolls_changed_pods(tmp_path):
    persister = FileWalPersister(str(tmp_path / "state"), fsync=False)
    scheduler = build_scheduler(
        HELLO_YAML, cpu_hosts(3), tmp_path, persister
    ).build()
    agent = scheduler.agent
    assert drive(scheduler, deploy_complete)
    old_ids = {
        i.name: i.task_id for i in scheduler.state_store.fetch_tasks()
    }

    updated_yaml = HELLO_YAML.replace("echo hello-", "echo updated-")
    builder2 = build_scheduler(updated_yaml, cpu_hosts(3), tmp_path, persister)
    builder2.set_agent(agent)
    updated = builder2.build()
    try:
        # the new target config makes existing tasks outdated: plan is
        # an update plan with PENDING steps
        plan = updated.deploy_manager.get_plan()
        assert plan.name == "update"
        assert not plan.is_complete
        assert drive(updated, deploy_complete), _debug(updated)
        new_infos = {i.name: i for i in updated.state_store.fetch_tasks()}
        assert all(
            new_infos[name].task_id != old_ids[name] for name in old_ids
        )
        out = os.path.join(agent.sandbox_of("hello-1-server"), "out.txt")
        # the relaunched task reports RUNNING at exec time and writes
        # out.txt asynchronously: poll briefly instead of racing the
        # subprocess on a loaded host
        deadline = time.monotonic() + 10
        content = ""
        while time.monotonic() < deadline:
            try:
                content = open(out).read().strip()
            except OSError:
                content = ""
            if content == "updated-1":
                break
            time.sleep(0.05)
        assert content == "updated-1"
    finally:
        agent.shutdown()


def _debug(scheduler):
    from dcos_commons_tpu.debug.trackers import serialize_plan

    return {
        "plans": {
            n: serialize_plan(p) for n, p in scheduler.plans().items()
        },
        "statuses": {
            n: (s.state.value, s.task_id)
            for n, s in scheduler.state_store.fetch_statuses().items()
        },
        "outcomes": scheduler.outcome_tracker.to_json()[-3:],
    }
