"""traceview: cross-layer span tracing + flight recorder + steplog.

One correlation chain from offer intake to the worker's pjit step
loop: the scheduler mints a trace id per offer cycle
(``scheduler/scheduler.py run_cycle``), threads it through offer
evaluation, the launch WAL, status fan-in, and plan-step transitions;
workers append per-step telemetry (``steplog.py``) that the exporters
merge into the same timeline.  Surfaced at ``GET /v1/debug/trace``
(plain text) and ``GET /v1/debug/trace?fmt=chrome`` (Perfetto).
"""

from dcos_commons_tpu.trace.export import chrome_json, to_chrome, to_text
from dcos_commons_tpu.trace.recorder import (
    NULL_TRACER,
    LaunchRef,
    TraceRecorder,
)
from dcos_commons_tpu.trace.span import NullSpan, Span
from dcos_commons_tpu.trace.steplog import (
    STEPLOG_NAME,
    StepLog,
    read_steplog,
)

__all__ = [
    "NULL_TRACER",
    "STEPLOG_NAME",
    "LaunchRef",
    "NullSpan",
    "Span",
    "StepLog",
    "TraceRecorder",
    "chrome_json",
    "read_steplog",
    "to_chrome",
    "to_text",
]
