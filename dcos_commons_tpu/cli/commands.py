"""argparse command tree mirroring the reference CLI's sections.

Reference: cli/commands.go:39,56 (HandleDefaultSections: config,
debug, endpoints, plan, pod, state, update) and the verb sets in
cli/commands/{plan,pod,config,state,endpoints,debug}.go.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, List, Optional

from dcos_commons_tpu.cli.client import ApiClient, CliError


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusvc",
        description="Operator CLI for a tpu-service-sdk scheduler",
    )
    parser.add_argument(
        "--url",
        default=os.environ.get("SCHEDULER_API_URL", "http://127.0.0.1:8080"),
        help="scheduler API base URL (default: $SCHEDULER_API_URL)",
    )
    parser.add_argument(
        "--auth-token-file",
        default="",
        help="cluster bearer token file; also $AUTH_TOKEN(_FILE)",
    )
    parser.add_argument(
        "--tls-ca",
        default=os.environ.get("TLS_CA_FILE", ""),
        help="CA bundle for verifying an HTTPS scheduler "
             "(default: $TLS_CA_FILE)",
    )
    sections = parser.add_subparsers(dest="section", required=True)

    # plan (reference: cli/commands/plan.go:51-90)
    plan = sections.add_parser("plan").add_subparsers(dest="verb", required=True)
    plan.add_parser("list")
    for verb in ("show", "status"):
        p = plan.add_parser(verb)
        p.add_argument("plan")
    p = plan.add_parser("pause")   # = interrupt
    p.add_argument("plan")
    p.add_argument("phase", nargs="?")
    p = plan.add_parser("resume")  # = continue
    p.add_argument("plan")
    p.add_argument("phase", nargs="?")
    p = plan.add_parser("force-restart")
    p.add_argument("plan")
    p.add_argument("phase", nargs="?")
    p.add_argument("step", nargs="?")
    p = plan.add_parser("force-complete")
    p.add_argument("plan")
    p.add_argument("phase")
    p.add_argument("step")
    p = plan.add_parser("start")
    p.add_argument("plan")
    p.add_argument(
        "-p", "--param", action="append", default=[], metavar="KEY=VALUE",
        help="env override launched into every task of the plan "
             "(reference: `plan start <plan> -p KEY=VALUE`)",
    )
    p = plan.add_parser("stop")
    p.add_argument("plan")

    # pod (reference: cli/commands/pod.go)
    pod = sections.add_parser("pod").add_subparsers(dest="verb", required=True)
    pod.add_parser("list")
    p = pod.add_parser("status")
    p.add_argument("pod", nargs="?")
    for verb in ("info", "restart", "replace"):
        p = pod.add_parser(verb)
        p.add_argument("pod")
    for verb in ("pause", "resume"):
        p = pod.add_parser(verb)
        p.add_argument("pod")
        p.add_argument("-t", "--tasks", action="append")
    # manual scale (ISSUE 15): rides the autoscale plan machinery,
    # single-flight with any automated action on the same pod;
    # scale-abandon drops an in-flight action (count settles to
    # deployed reality, the direction's cooldown latches)
    p = pod.add_parser("scale")
    p.add_argument("pod", help="pod TYPE (not an instance)")
    p.add_argument("count", type=int)
    p = pod.add_parser("scale-abandon")
    p.add_argument("pod", help="pod TYPE (not an instance)")

    # config
    config = sections.add_parser("config").add_subparsers(
        dest="verb", required=True
    )
    config.add_parser("list")
    p = config.add_parser("show")
    p.add_argument("config_id")
    config.add_parser("target")
    config.add_parser("target_id")

    # state
    state = sections.add_parser("state").add_subparsers(
        dest="verb", required=True
    )
    state.add_parser("properties")
    p = state.add_parser("property")
    p.add_argument("key")
    state.add_parser("framework_id")
    state.add_parser("zones")

    # endpoints
    p = sections.add_parser("endpoints")
    p.add_argument("name", nargs="?")

    # hosts: preemption & maintenance lifecycle (ISSUE 13)
    host = sections.add_parser("host").add_subparsers(
        dest="verb", required=True
    )
    host.add_parser("list")
    p = host.add_parser("drain")
    p.add_argument("host_id")
    p.add_argument(
        "--window-s", type=float, default=0.0, metavar="SECONDS",
        help="maintenance window length; a finite window makes elastic "
             "gang recovery wait for the capacity instead of shrinking",
    )
    for verb in ("preempt", "up"):
        p = host.add_parser(verb)
        p.add_argument("host_id")

    # debug
    p = sections.add_parser("debug")
    p.add_argument(
        "tracker",
        choices=["offers", "plans", "taskStatuses", "reservations",
                 "health", "events", "router", "serving"],
    )
    p.add_argument(
        "--metric", default=None, metavar="NAME",
        help="(health) return one metric's full timestamped history "
             "series instead of the summary rows",
    )
    p.add_argument(
        "--since", default=None, metavar="SEQ",
        help="(events) resume the journal cursor past this sequence "
             "number (seqs survive scheduler failovers)",
    )
    p.add_argument(
        "--kind", default=None, metavar="KIND",
        help="(events) filter to one event kind, e.g. alert, operator, "
             "plan, election, recovery, admission",
    )

    # update (reference: cli/commands/update.go — `update start
    # --options=...` pushes new options to the RUNNING scheduler,
    # `update status` watches the resulting rolling update plan)
    update = sections.add_parser("update").add_subparsers(
        dest="verb", required=True
    )
    p = update.add_parser("start")
    p.add_argument(
        "-p", "--param", action="append", default=[], metavar="KEY=VALUE",
        help="service option override (svc.yml template env), e.g. "
             "-p SLEEP_DURATION=30",
    )
    update.add_parser("status")

    sections.add_parser("metrics")
    sections.add_parser("health")
    return parser


def run(args: argparse.Namespace) -> Any:
    from dcos_commons_tpu.security.auth import load_token

    client = ApiClient(
        args.url,
        auth_token=load_token(token_file=getattr(args, "auth_token_file", "")),
        ca_file=getattr(args, "tls_ca", ""),
    )
    section = args.section
    if section == "plan":
        return _plan(client, args)
    if section == "pod":
        return _pod(client, args)
    if section == "config":
        return _config(client, args)
    if section == "state":
        return _state(client, args)
    if section == "endpoints":
        if args.name:
            return client.get(f"/v1/endpoints/{args.name}")
        return client.get("/v1/endpoints")
    if section == "host":
        return _host(client, args)
    if section == "debug":
        return _debug(client, args)
    if section == "update":
        return _update(client, args)
    if section == "metrics":
        return client.get("/v1/metrics")
    if section == "health":
        return client.get("/v1/health")
    raise CliError(0, f"unknown section {section}")


def _host(client: ApiClient, args) -> Any:
    if args.verb == "list":
        return client.get("/v1/hosts")
    if args.verb == "drain":
        return client.post(
            f"/v1/hosts/{args.host_id}/drain",
            body={"window_s": args.window_s},
        )
    return client.post(f"/v1/hosts/{args.host_id}/{args.verb}")


def _debug(client: ApiClient, args) -> Any:
    from urllib.parse import urlencode

    params = {}
    if args.tracker == "health" and args.metric:
        params["metric"] = args.metric
    if args.tracker == "events":
        if args.since:
            params["since"] = args.since
        if args.kind:
            params["kind"] = args.kind
    path = f"/v1/debug/{args.tracker}"
    if params:
        path = f"{path}?{urlencode(params)}"
    return client.get(path)


def _update(client: ApiClient, args) -> Any:
    if args.verb == "start":
        env = _parse_params(getattr(args, "param", None))
        if not env:
            raise CliError(0, "update start needs at least one -p KEY=VALUE")
        return client.post("/v1/update", body={"env": env})
    if args.verb == "status":
        # the rolling update runs as the deploy/update plan
        plans = client.get("/v1/plans")
        name = "update" if "update" in plans else "deploy"
        return client.get(f"/v1/plans/{name}")
    raise CliError(0, f"unknown update verb {args.verb}")


def _parse_params(pairs) -> dict:
    env = {}
    for pair in pairs or []:
        key, sep, value = pair.partition("=")
        if not sep or not key:
            raise CliError(0, f"bad --param {pair!r}; want KEY=VALUE")
        env[key] = value
    return env


def _plan(client: ApiClient, args) -> Any:
    verb = args.verb
    if verb == "list":
        return client.get("/v1/plans")
    if verb in ("show", "status"):
        return client.get(f"/v1/plans/{args.plan}")
    params = {"phase": getattr(args, "phase", None),
              "step": getattr(args, "step", None)}
    if verb == "pause":
        return client.post(f"/v1/plans/{args.plan}/interrupt", params)
    if verb == "resume":
        return client.post(f"/v1/plans/{args.plan}/continue", params)
    if verb == "force-restart":
        return client.post(f"/v1/plans/{args.plan}/restart", params)
    if verb == "force-complete":
        return client.post(f"/v1/plans/{args.plan}/forceComplete", params)
    if verb == "start":
        env = _parse_params(getattr(args, "param", None))
        return client.post(
            f"/v1/plans/{args.plan}/start",
            body={"env": env} if env else None,
        )
    if verb == "stop":
        return client.post(f"/v1/plans/{args.plan}/stop")
    raise CliError(0, f"unknown plan verb {verb}")


def _pod(client: ApiClient, args) -> Any:
    verb = args.verb
    if verb == "list":
        return client.get("/v1/pod")
    if verb == "status":
        if args.pod:
            return client.get(f"/v1/pod/{args.pod}/status")
        return client.get("/v1/pod/status")
    if verb == "info":
        return client.get(f"/v1/pod/{args.pod}/info")
    if verb in ("restart", "replace"):
        return client.post(f"/v1/pod/{args.pod}/{verb}")
    if verb == "scale":
        return client.post(
            f"/v1/pod/{args.pod}/scale", body={"count": args.count}
        )
    if verb == "scale-abandon":
        return client.post(f"/v1/pod/{args.pod}/scale/abandon")
    if verb in ("pause", "resume"):
        params = {}
        if args.tasks:
            params["task"] = args.tasks
        return client.post(f"/v1/pod/{args.pod}/{verb}", params or None)
    raise CliError(0, f"unknown pod verb {verb}")


def _config(client: ApiClient, args) -> Any:
    verb = args.verb
    if verb == "list":
        return client.get("/v1/configs")
    if verb == "show":
        return client.get(f"/v1/configs/{args.config_id}")
    if verb == "target":
        return client.get("/v1/configs/target")
    if verb == "target_id":
        return client.get("/v1/configs/targetId")
    raise CliError(0, f"unknown config verb {verb}")


def _state(client: ApiClient, args) -> Any:
    verb = args.verb
    if verb == "properties":
        return client.get("/v1/state/properties")
    if verb == "property":
        return client.get(f"/v1/state/properties/{args.key}")
    if verb == "framework_id":
        return client.get("/v1/state/frameworkId")
    if verb == "zones":
        return client.get("/v1/state/zones")
    raise CliError(0, f"unknown state verb {verb}")


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    try:
        result = run(args)
    except CliError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if isinstance(result, str):
        print(result)
    else:
        print(json.dumps(result, indent=2, sort_keys=True))
    return 0
