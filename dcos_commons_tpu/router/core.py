"""RequestRouter: the transport-free routing brain of the serving
front door (ISSUE 12).

One router fronts N serve pods.  Every placement decision composes
three signals, in order:

* **drain awareness** — a pod marked draining (operator verb,
  scheduler decommission/pause state from discovery, or a transport
  failure observed mid-request) receives ZERO new admissions; its
  in-flight requests finish normally.  A pod that died mid-request
  fails over to a peer under an honest retry budget — the retry is
  counted, bounded, and only taken when the failure proves no
  response was produced (a transport error), never on an application
  error, so no reply is ever silently doubled.
* **prefix affinity** (router/affinity.py) — the prompt's
  page-aligned prefix chain (the same construction serve/paging.py
  interns) is matched against which pod last served each chain node;
  shared-prefix sessions land on the pod already holding the cached
  pages, so PR 11's prefix hit rate survives fan-out instead of
  being diluted 1/N by random spray.  Affinity yields to load: a
  claimed pod more than ``affinity_slack`` requests busier than the
  least-loaded peer is skipped (a hot system prompt must not weld
  itself to one pod).
* **least-loaded** (router/telemetry.py) — polled queue-depth/
  active-rows/KV-headroom gauges, gated on freshness: a pod whose
  snapshot is stale (poll failed, or the pod's own engine loop
  stopped ticking per its ``stats_age_s`` stamp) is scored
  pessimistically on router-side in-flight counts alone, never on
  its last-good numbers.

The router is transport-free by the same discipline as the serve
engine: ``send(pod_name, address, request) -> response`` is injected
(the HTTP front door binds it to POST /generate; tests and the bench
bind it straight onto in-process engines).  ``PodTransportError``
from ``send`` is the ONLY failover trigger; every other exception
passes through to the caller untouched.

Reference: the reference SDK's EndpointsResource/NamedVIPSpec answer
"where are the backends" (SURVEY §2.1) and leave balancing to
dcos-l4lb; this module is the TPU-serving-aware balancer that VIP
machinery never had.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from dcos_commons_tpu.router.affinity import AffinityMap, prefix_chain_keys
from dcos_commons_tpu.router.telemetry import (
    DEFAULT_STALE_AFTER_S,
    PodTelemetry,
)
from dcos_commons_tpu.serve.migration import SessionMigratedError

ROUTERSTATS_NAME = "servestats.json"  # rides the serving-stats plumbing
_LATENCY_WINDOW = 512


class PodTransportError(RuntimeError):
    """The pod could not be reached or died mid-request — no response
    was produced, so failing over to a peer cannot double a reply."""


class NoPodAvailableError(RuntimeError):
    """No pod is currently admitting (all draining/failed/unknown) —
    the front door maps this to 503."""


class _PodState:
    """Router-side view of one serve pod."""

    __slots__ = (
        "name", "address", "telemetry", "draining",
        "operator_drained", "failed", "in_flight", "admitted",
        "role",
    )

    def __init__(self, name: str, address: str, stale_after_s: float):
        self.name = name
        self.address = address
        self.telemetry = PodTelemetry(stale_after_s)
        # serving role (ISSUE 16 disaggregation): "unified" serves
        # everything; "prefill" pods take long prompts and hand the
        # finished pages to the decode pool; "decode" pods take the
        # short/interactive traffic.  Seeded from discovery (the
        # pod's SERVE_ROLE env, surfaced through /v1/endpoints) and
        # refined by the pod's own serving_role gauge
        self.role = "unified"
        # two INDEPENDENT drain flags, OR'd for admission: discovery
        # state (scheduler-side pause/decommission, refreshed by every
        # update_pods) and the operator's front-door verb (owned by
        # drain()/undrain() ONLY — a discovery refresh must never
        # silently undo a runbook drain mid-decommission)
        self.draining = False
        self.operator_drained = False
        self.failed = False      # transport failure; cleared by fresh stats
        self.in_flight = 0
        self.admitted = 0

    @property
    def admitting_blocked(self) -> bool:
        return self.draining or self.operator_drained

    def load(self, now: float) -> float:
        """Placement score, lower = preferred.  Fresh gauges add the
        pod's polled backlog; stale gauges contribute a flat penalty
        so a pod of UNKNOWN load never outbids one that proves its
        headroom — but an all-stale fleet still spreads by in-flight
        counts instead of wedging."""
        polled = self.telemetry.load_score(now)
        if polled is None:
            return self.in_flight + _STALE_LOAD_PENALTY
        return self.in_flight + polled


# stale pods rank behind any fresh pod with fewer than this many
# queued+active requests; in-flight counts still order stale pods
# among themselves
_STALE_LOAD_PENALTY = 1e6


class RequestRouter:
    """See module docstring.  Thread-safe: submit() runs on client
    threads; discovery/stats observation on the front door's poll
    thread; ``send`` always runs OUTSIDE the lock."""

    def __init__(
        self,
        send: Callable[[str, str, dict], list],
        page_tokens: int = 16,
        policy: str = "affinity",
        stale_after_s: float = DEFAULT_STALE_AFTER_S,
        retry_budget: int = 2,
        affinity_slack: float = 4.0,
        affinity_capacity: int = 65536,
        prefill_route_tokens: Optional[int] = None,
        log: Optional[Callable[[str], None]] = None,
    ):
        if policy not in ("affinity", "least-loaded", "round-robin"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self._send = send
        self._page_tokens = int(page_tokens)
        self._policy = policy
        self._stale_after_s = float(stale_after_s)
        self._retry_budget = max(0, int(retry_budget))
        self._affinity_slack = float(affinity_slack)
        # disaggregation threshold: prompts at least this long go to
        # prefill-role capacity (when any is offered).  None = auto
        # (a prompt spanning 4+ pages is "long" — one chunked-prefill
        # burst a decode tick should not absorb); 0 = never steer by
        # length (prefill pods then only take traffic as a last
        # resort).  Role filtering is inert while every pod is
        # unified, which is every pre-disaggregation deployment.
        if prefill_route_tokens is None:
            self._prefill_route_tokens = 4 * self._page_tokens
        else:
            self._prefill_route_tokens = max(0, int(prefill_route_tokens))
        self._log = log
        self._lock = threading.Lock()
        self._pods: Dict[str, _PodState] = {}
        self._generation: Optional[str] = None
        self._affinity = AffinityMap(affinity_capacity)
        self._rr_next = 0
        # telemetry (counters under the lock; windows pruned on append)
        self._requests = 0
        self._completed = 0
        self._retries = 0
        self._failovers = 0
        self._rejected_no_pod = 0
        self._affinity_lookups = 0
        self._affinity_hits = 0
        self._affinity_overridden = 0
        self._stale_routing_rounds = 0
        self._migration_follows = 0
        self._chain_repoints = 0
        self._latency: deque = deque(maxlen=_LATENCY_WINDOW)
        self._started_mono = time.monotonic()
        self._extra_stats: Dict[str, object] = {}

    def annotate_stats(self, **extra) -> None:
        """Attach static facts to every stats() snapshot (the front
        door's actually-bound http_port — the same /v1/endpoints
        advertisement contract as serve/engine.py)."""
        with self._lock:
            self._extra_stats.update(extra)

    # -- pod set (discovery-driven) -----------------------------------

    def update_pods(self, backends: Dict[str, dict],
                    generation: Optional[str] = None) -> bool:
        """Install the discovered pod set.  ``backends``: name ->
        {"address": "host:port", "draining": bool}.  With a
        ``generation`` matching the last install this is ONE compare
        and no rebuild (the quiet-fleet discipline: the scheduler's
        endpoint generation only moves on task/reservation churn).
        Returns True when the set was (re)installed."""
        with self._lock:
            if generation is not None and generation == self._generation:
                return False
            self._generation = generation
            removed = [n for n in self._pods if n not in backends]
            for name in removed:
                del self._pods[name]
                self._affinity.evict_pod(name)
            for name, entry in backends.items():
                address = entry["address"] if isinstance(entry, dict) \
                    else str(entry)
                draining = bool(entry.get("draining", False)) \
                    if isinstance(entry, dict) else False
                pod = self._pods.get(name)
                if pod is None or pod.address != address:
                    # new pod, or a replaced pod behind the old name:
                    # either way its cache is cold — drop stale claims
                    if pod is not None:
                        self._affinity.evict_pod(name)
                    pod = _PodState(name, address, self._stale_after_s)
                    self._pods[name] = pod
                if draining and not pod.draining:
                    self._affinity.evict_pod(name)
                pod.draining = draining
                role = entry.get("role") if isinstance(entry, dict) \
                    else None
                if role:
                    pod.role = str(role)
        if removed and self._log is not None:
            self._log(f"router: pods left the set: {sorted(removed)}")
        return True

    def observe_stats(self, name: str, stats: dict,
                      now: Optional[float] = None) -> None:
        """Ingest one pod's /stats snapshot (poll thread).  A fresh
        snapshot clears the pod's transport-failure mark: the pod
        answered, so it is dialable again."""
        now = time.monotonic() if now is None else now
        with self._lock:
            pod = self._pods.get(name)
            if pod is None:
                return
            pod.telemetry.observe(stats, now)
            if pod.telemetry.fresh(now):
                pod.failed = False
            if pod.telemetry.serving_role:
                pod.role = pod.telemetry.serving_role

    def drain(self, name: str,
              migrated_to: Optional[str] = None) -> bool:
        """Operator drain: zero new admissions, in-flight finishes.
        The drain runbook's first verb (operations-guide).  Sticky
        against discovery: only undrain() (or the pod leaving the
        set) clears it — a poll-driven pod-set refresh must not undo
        a drain mid-decommission.

        ``migrated_to`` names the pod the drain migrated this pod's
        sessions (and their cached pages) to: the leaving pod's
        prefix-chain claims RE-POINT there instead of being dropped,
        so post-drain requests still hit the moved cache.  Without it
        — the legacy wait-out drain — claims are evicted, because the
        cache genuinely dies with the pod."""
        with self._lock:
            pod = self._pods.get(name)
            if pod is None:
                return False
            pod.operator_drained = True
            dest = self._pods.get(migrated_to) if migrated_to else None
            if dest is not None and dest.name != name:
                moved = self._affinity.repoint_pod(name, dest.name)
                self._chain_repoints += moved
            else:
                moved = -self._affinity.evict_pod(name)
        if self._log is not None:
            if moved > 0:
                self._log(
                    f"router: draining {name}; {moved} prefix claims "
                    f"re-pointed to {migrated_to}"
                )
            else:
                self._log(f"router: draining {name}")
        return True

    def undrain(self, name: str) -> bool:
        with self._lock:
            pod = self._pods.get(name)
            if pod is None:
                return False
            pod.operator_drained = False
        return True

    def pods(self) -> List[str]:
        with self._lock:
            return sorted(self._pods)

    def repoint_prompt(self, tokens: Sequence[int], dest: str) -> int:
        """Re-point one prompt's prefix-chain claims to ``dest`` —
        the rebalance consumer's verb: after migrating a session's
        pages, its chain knowledge follows (drain_sessions report
        rows carry the tokens).  Returns claims moved."""
        keys = prefix_chain_keys(tokens, self._page_tokens)
        with self._lock:
            if not keys or dest not in self._pods:
                return 0
            moved = self._affinity.repoint(keys, dest)
            self._chain_repoints += moved
            return moved

    def rebalance_suggestion(self, min_claims: int = 8,
                             min_skew: float = 2.0) -> Optional[dict]:
        """Prefix-hotspot detection: the pod whose claim count AND
        load dominate its peers is where a hot shared prefix welded
        traffic.  Returns ``{"from", "to", "claims", "load_gap"}`` —
        migrate sessions from/to those pods (serve.migration.
        drain_sessions + repoint_prompt) to shed load WITH the cache
        — or None while the fleet is balanced."""
        now = time.monotonic()
        with self._lock:
            pods = self._eligible_locked(())
            if len(pods) < 2:
                return None
            counts = self._affinity.claims_by_pod()
            hot = max(pods, key=lambda p: (counts.get(p.name, 0),
                                           p.load(now), p.name))
            cold = min(pods, key=lambda p: (counts.get(p.name, 0),
                                            p.load(now), p.name))
            hot_claims = counts.get(hot.name, 0)
            cold_claims = counts.get(cold.name, 0)
            if (hot.name == cold.name
                    or hot_claims < max(1, int(min_claims))
                    or hot_claims < min_skew * max(1, cold_claims)
                    or hot.load(now) <= cold.load(now)):
                return None
            return {
                "from": hot.name, "to": cold.name,
                "claims": hot_claims,
                "load_gap": round(hot.load(now) - cold.load(now), 2),
            }

    # -- placement ----------------------------------------------------

    def _eligible_locked(self, exclude) -> List[_PodState]:
        return [
            p for p in self._pods.values()
            if not p.admitting_blocked and not p.failed
            and p.name not in exclude
        ]

    def _role_filter_locked(self, pods: List[_PodState],
                            prompt_len: int) -> List[_PodState]:
        """Disaggregated placement: long prompts go to prefill-role
        capacity; everything else stays off it (a short prompt on a
        prefill pod would just bounce through a handoff).  Inert
        while no offered pod declares a prefill role — every
        pre-disaggregation fleet."""
        prefill = [p for p in pods if p.role == "prefill"]
        if not prefill or len(prefill) == len(pods):
            return pods
        if (self._prefill_route_tokens > 0
                and prompt_len >= self._prefill_route_tokens):
            return prefill
        return [p for p in pods if p.role != "prefill"]

    def _pick_locked(self, keys: Sequence[int], exclude,
                     prompt_len: int = 0) -> _PodState:
        pods = self._eligible_locked(exclude)
        if not pods:
            self._rejected_no_pod += 1
            raise NoPodAvailableError(
                "no serve pod is admitting (all draining, failed, or "
                "undiscovered)"
            )
        pods = self._role_filter_locked(pods, prompt_len)
        allowed = {p.name for p in pods}
        now = time.monotonic()
        if all(not p.telemetry.fresh(now) for p in pods):
            self._stale_routing_rounds += 1
        if self._policy == "round-robin":
            ordered = sorted(pods, key=lambda p: p.name)
            pod = ordered[self._rr_next % len(ordered)]
            self._rr_next += 1
            return pod
        by_load = min(pods, key=lambda p: (p.load(now), p.name))
        if self._policy == "affinity" and keys:
            self._affinity_lookups += 1
            claimed, _depth = self._affinity.lookup(keys)
            if claimed is not None and claimed in allowed:
                pod = self._pods.get(claimed)
                if (pod is not None and not pod.admitting_blocked
                        and not pod.failed and pod.name not in exclude):
                    if pod.load(now) <= by_load.load(now) + \
                            self._affinity_slack:
                        self._affinity_hits += 1
                        return pod
                    self._affinity_overridden += 1
        return by_load

    def route(self, tokens: Sequence[int]) -> str:
        """Placement decision alone (tests/debug): which pod would
        this prompt go to right now?"""
        keys = prefix_chain_keys(tokens, self._page_tokens)
        with self._lock:
            return self._pick_locked(
                keys, exclude=(), prompt_len=len(tokens)
            ).name

    # -- the request path ---------------------------------------------

    def submit(
        self,
        tokens: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        eos: Optional[int] = None,
    ) -> List[int]:
        """Route one prompt and return its continuation.  Transport
        failures fail over within the retry budget; application
        errors (the pod ANSWERED with an error) pass through — the
        pod produced a verdict, and re-asking a peer would double
        work the client will retry anyway."""
        request = {
            "tokens": [[int(t) for t in tokens]],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
        }
        if eos is not None:
            request["eos"] = int(eos)
        keys = prefix_chain_keys(tokens, self._page_tokens)
        tried: set = set()
        attempts = 0
        t0 = time.monotonic()
        with self._lock:
            self._requests += 1
        while True:
            with self._lock:
                pod = self._pick_locked(
                    keys, tried, prompt_len=len(tokens)
                )
                pod.in_flight += 1
                pod.admitted += 1
                if self._policy == "affinity" and keys:
                    # claim BEFORE the send completes: a concurrent
                    # same-prefix request must follow this one onto
                    # the same pod, not race past it to another
                    self._affinity.record(keys, pod.name)
                name, address = pod.name, pod.address
            try:
                result = self._send(name, address, request)
            except PodTransportError as e:
                with self._lock:
                    pod.in_flight -= 1
                    pod.failed = True
                    self._affinity.evict_pod(name)
                    tried.add(name)
                    attempts += 1
                    self._retries += 1
                    budget_left = attempts <= self._retry_budget
                if self._log is not None:
                    self._log(
                        f"router: {name} failed mid-request ({e}); "
                        + (f"failing over (attempt {attempts}/"
                           f"{self._retry_budget})" if budget_left
                           else "retry budget exhausted")
                    )
                if not budget_left:
                    raise PodTransportError(
                        f"request failed on {attempts} pod(s), retry "
                        f"budget {self._retry_budget} exhausted: {e}"
                    ) from e
                with self._lock:
                    self._failovers += 1
                continue
            except SessionMigratedError as e:
                # the session moved mid-generation (drain, rebalance,
                # or a prefill pod's handoff): follow it with a
                # collect — the destination answers with the FULL
                # output, so the client sees one uninterrupted reply
                with self._lock:
                    pod.in_flight -= 1
                    self._migration_follows += 1
                    dest = self._pods.get(e.moved_to)
                    if dest is not None:
                        dest.in_flight += 1
                if dest is None:
                    raise PodTransportError(
                        f"session migrated to unknown pod "
                        f"{e.moved_to!r}"
                    ) from e
                if self._log is not None:
                    self._log(
                        f"router: following migrated session from "
                        f"{name} to {dest.name}"
                    )
                try:
                    result = self._send(
                        dest.name, dest.address,
                        {"collect": int(e.dest_rid)},
                    )
                finally:
                    with self._lock:
                        dest.in_flight -= 1
                now = time.monotonic()
                with self._lock:
                    self._completed += 1
                    self._latency.append(now - t0)
                return result[0]
            except Exception:
                with self._lock:
                    pod.in_flight -= 1
                raise  # application error: pass through, never retried
            now = time.monotonic()
            with self._lock:
                pod.in_flight -= 1
                self._completed += 1
                self._latency.append(now - t0)
            # send's contract: the pod's row list for the one-row
            # request — the continuation is its first (only) row
            return result[0]

    # -- gauges (the watcher-compatible snapshot) ---------------------

    def stats(self) -> dict:
        """Router load snapshot.  Deliberately shares key names with
        the serve engine's gauges (queue_depth, ttft_p95_s,
        stats_age_s) so the scheduler's ServingSloWatcher watches a
        router task with the SAME env knobs as a serve pod; router_*
        keys carry the front-door-specific counters."""
        from dcos_commons_tpu.metrics.registry import percentile

        with self._lock:
            pods = list(self._pods.values())
            latency = sorted(self._latency)
            out = {
                "router_pods": len(pods),
                "router_pods_draining": sum(
                    p.admitting_blocked for p in pods
                ),
                "router_pods_failed": sum(p.failed for p in pods),
                "queue_depth": sum(p.in_flight for p in pods),
                "requests_admitted": self._requests,
                "requests_completed": self._completed,
                "router_retries": self._retries,
                "router_failovers": self._failovers,
                "router_rejected_no_pod": self._rejected_no_pod,
                "router_affinity_lookups": self._affinity_lookups,
                "router_affinity_hits": self._affinity_hits,
                "router_affinity_overridden": self._affinity_overridden,
                "router_affinity_hit_rate": round(
                    self._affinity_hits / self._affinity_lookups, 4
                ) if self._affinity_lookups else 0.0,
                "router_stale_routing_rounds": self._stale_routing_rounds,
                "router_migration_follows": self._migration_follows,
                "router_chain_repoints": self._chain_repoints,
                "router_prefill_pods": sum(
                    p.role == "prefill" for p in pods
                ),
                "router_policy": self._policy,
                "router_generation": self._generation,
            }
            out.update(self._extra_stats)
        if latency:
            out["ttft_p50_s"] = round(percentile(latency, 50), 4)
            out["ttft_p95_s"] = round(percentile(latency, 95), 4)
        # the router computes its snapshot on demand: age 0 by
        # construction, present so staleness-gated readers need no
        # special case for router tasks
        out["stats_age_s"] = 0.0
        out["t"] = time.time()
        return out

    def describe(self) -> dict:
        """Per-pod debug rows (front door GET /pods; the
        prefix-affinity triage surface)."""
        now = time.monotonic()
        with self._lock:
            return {
                "generation": self._generation,
                "policy": self._policy,
                "affinity_entries": len(self._affinity),
                "pods": {
                    p.name: {
                        "address": p.address,
                        "role": p.role,
                        "draining": p.admitting_blocked,
                        "discovery_draining": p.draining,
                        "operator_drained": p.operator_drained,
                        "failed": p.failed,
                        "in_flight": p.in_flight,
                        "admitted": p.admitted,
                        "telemetry": p.telemetry.describe(now),
                    }
                    for p in self._pods.values()
                },
            }

    def write_stats(self, path: str) -> None:
        """Mirror the router gauges to a sandbox file (same atomic
        pattern as serve/engine.py): the scheduler's /v1/debug/serving
        and /v1/debug/router merge them per task."""
        try:
            tmp = path + ".tmp"
            # durcheck: dur-file-discipline=telemetry mirror: loss on power failure is acceptable, the rename alone keeps readers partial-free
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.stats(), f)
            os.replace(tmp, path)
        except OSError:
            pass  # sdklint: disable=swallowed-exception — telemetry must never take the front door down
