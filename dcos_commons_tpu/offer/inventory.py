"""The fleet model: TPU hosts, chips, torus coordinates, snapshots.

Replaces Mesos agents + offers (reference: offer/MesosResourcePool.java
— the consumable view of one offer — and the agent attributes consumed
by placement rules).  The scheduler owns this inventory and synthesizes
"offers" (ResourceSnapshots) from it each cycle, instead of waiting
for a Mesos master to send them.

Torus model: each physical TPU pod ("slice") is a grid of hosts; each
host owns a contiguous block of chips (e.g. a v5e host owns a 2x2
block; an 8x8-host pod is a 16x16 chip torus).  Chip coordinates are
global within the slice, so ICI adjacency between two hosts is
checkable from their host-grid coordinates alone.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple


@dataclass(frozen=True)
class TpuHost:
    """One TPU VM worker host.

    ``slice_id`` names the physical pod this host belongs to;
    ``grid`` is the host's (x, y) coordinate in that pod's host grid;
    ``chip_block`` is the (w, h) block of chips the host owns.
    CPU-only hosts (the helloworld case) have ``chip_block == (0, 0)``.
    """

    host_id: str
    hostname: str = ""
    slice_id: str = ""
    generation: str = ""             # "" for CPU-only hosts
    grid: Tuple[int, int] = (0, 0)
    chip_block: Tuple[int, int] = (0, 0)
    cpus: float = 8.0
    memory_mb: int = 16384
    disk_mb: int = 102400
    ports: Tuple[Tuple[int, int], ...] = ((10000, 12000),)
    attributes: Dict[str, str] = field(default_factory=dict)
    zone: str = ""
    region: str = ""

    def __post_init__(self) -> None:
        if not self.hostname:
            object.__setattr__(self, "hostname", self.host_id)

    @property
    def chips_per_host(self) -> int:
        return self.chip_block[0] * self.chip_block[1]

    def chip_ids(self) -> List[str]:
        """Global chip ids "slice/x,y" for every chip this host owns.

        Memoized: the dataclass is frozen, so the id list is a pure
        function of the host — snapshot synthesis used to re-format
        these strings for every host on every cycle."""
        cached = self.__dict__.get("_chip_ids")
        if cached is None:
            w, h = self.chip_block
            ox, oy = self.grid[0] * w, self.grid[1] * h
            cached = tuple(
                f"{self.slice_id}/{ox + dx},{oy + dy}"
                for dy in range(h)
                for dx in range(w)
            )
            object.__setattr__(self, "_chip_ids", cached)
        return list(cached)


class ResourceSnapshot:
    """A consumable view of one host's free resources — the offer.

    Reference: offer/MesosResourcePool.java.  Mutated by evaluation
    stages as they claim resources; commit/rollback is handled by the
    evaluator working on copies (gang evaluation is all-or-nothing).
    """

    def __init__(
        self,
        host: TpuHost,
        cpus: float,
        memory_mb: int,
        disk_mb: int,
        free_chips: Set[str],
        used_ports: Set[int],
    ):
        self.host = host
        self.cpus = cpus
        self.memory_mb = memory_mb
        self.disk_mb = disk_mb
        self.free_chips = set(free_chips)
        self.used_ports = set(used_ports)

    def copy(self) -> "ResourceSnapshot":
        return ResourceSnapshot(
            self.host, self.cpus, self.memory_mb, self.disk_mb,
            set(self.free_chips), set(self.used_ports),
        )

    # -- consumption (evaluation stages call these) -------------------

    def try_consume_scalar(self, cpus: float, memory_mb: int, disk_mb: int) -> bool:
        if self.cpus + 1e-9 < cpus or self.memory_mb < memory_mb \
                or self.disk_mb < disk_mb:
            return False
        self.cpus -= cpus
        self.memory_mb -= memory_mb
        self.disk_mb -= disk_mb
        return True

    def try_consume_chips(self, count: int) -> Optional[List[str]]:
        if len(self.free_chips) < count:
            return None
        taken = sorted(self.free_chips)[:count]
        self.free_chips -= set(taken)
        return taken

    def allocate_port(self, requested: int = 0) -> Optional[int]:
        """Fixed port if requested, else next free dynamic port."""
        if requested:
            if requested in self.used_ports:
                return None
            self.used_ports.add(requested)
            return requested
        for lo, hi in self.host.ports:
            for port in range(lo, hi):
                if port not in self.used_ports:
                    self.used_ports.add(port)
                    return port
        return None


class SliceInventory:
    """The fleet: hosts + the reservation ledger's committed claims.

    ``snapshots()`` synthesizes the current "offers": per-host free
    resources after subtracting every committed reservation.  This is
    the L0-replacement — where the reference waits for resourceOffers
    callbacks (FrameworkScheduler.java:196), our scheduler scans this.
    """

    def __init__(self, hosts: Optional[List[TpuHost]] = None):
        self._hosts: Dict[str, TpuHost] = {}
        self._down: Set[str] = set()
        # snapshot cache (offer-cycle fast path): host_id -> (host
        # object, ledger host-generation token, built snapshot).  An
        # entry is valid while the exact host object is registered and
        # the view reports the same per-host generation; callers get a
        # copy, so the cached master is never mutated by evaluation.
        self._snap_cache: Dict[str, tuple] = {}
        # the view object itself is held (not its id()): id reuse
        # after GC must never validate a stale cache
        self._snap_view = None
        self.cache_hits = 0
        self.cache_misses = 0
        # bumped on any host add/remove/up/down so per-cycle consumers
        # (EvaluationContext's hosts dict) know when to rebuild
        self._topology_gen = 0
        for host in hosts or []:
            self.add_host(host)

    @property
    def topology_generation(self) -> int:
        return self._topology_gen

    def add_host(self, host: TpuHost) -> None:
        self._hosts[host.host_id] = host
        self._snap_cache.pop(host.host_id, None)
        self._topology_gen += 1

    def remove_host(self, host_id: str) -> None:
        self._hosts.pop(host_id, None)
        self._down.discard(host_id)
        self._snap_cache.pop(host_id, None)
        self._topology_gen += 1

    def mark_down(self, host_id: str) -> None:
        """Host lost/maintenance: excluded from snapshots (the TASK_LOST
        / PARTITION_AWARE analogue, SURVEY.md section 5.3)."""
        if host_id in self._hosts:
            self._down.add(host_id)
            self._topology_gen += 1

    def mark_up(self, host_id: str) -> None:
        self._down.discard(host_id)
        self._topology_gen += 1

    def is_up(self, host_id: str) -> bool:
        return host_id in self._hosts and host_id not in self._down

    def host(self, host_id: str) -> Optional[TpuHost]:
        return self._hosts.get(host_id)

    def hosts(self) -> List[TpuHost]:
        return list(self._hosts.values())

    def up_hosts(self) -> List[TpuHost]:
        return [h for h in self._hosts.values() if h.host_id not in self._down]

    def snapshots(self, ledger: "ReservationLedgerView") -> List[ResourceSnapshot]:
        """Synthesize the current offers, reusing cached per-host
        snapshots while the ledger view's per-host generation is
        unchanged.  A view without ``host_generation`` (or returning
        None) disables caching for that host — correctness never
        depends on the view being generation-aware."""
        gen_of = getattr(ledger, "host_generation", None)
        prepare = getattr(ledger, "prepare_pass", None)
        if prepare is not None:
            # composite views capture their member set once per pass
            # instead of once per host
            prepare()
        if ledger is not self._snap_view:
            # a different view object arbitrates now (e.g. the merged
            # multi-service view replacing the bare ledger): its
            # generations are not comparable with the cached tokens
            self._snap_cache.clear()
            self._snap_view = ledger
        out = []
        for host in self.up_hosts():
            token = gen_of(host.host_id) if gen_of is not None else None
            cached = self._snap_cache.get(host.host_id)
            if (
                token is not None
                and cached is not None
                and cached[0] is host
                and cached[1] == token
            ):
                self.cache_hits += 1
                out.append(cached[2].copy())
                continue
            self.cache_misses += 1
            snap = self._build_snapshot(host, ledger)
            if token is not None:
                self._snap_cache[host.host_id] = (host, token, snap)
                snap = snap.copy()
            out.append(snap)
        return out

    def _build_snapshot(
        self, host: TpuHost, ledger: "ReservationLedgerView"
    ) -> ResourceSnapshot:
        free_chips = set(host.chip_ids())
        used_ports: Set[int] = set()
        cpus, mem, disk = host.cpus, host.memory_mb, host.disk_mb
        for res in ledger.reserved_on(host.host_id):
            cpus -= res.cpus
            mem -= res.memory_mb
            disk -= res.disk_mb
            free_chips -= set(res.chip_ids)
            used_ports |= set(res.ports)
        return ResourceSnapshot(host, cpus, mem, disk, free_chips, used_ports)


class ReservationLedgerView:
    """What SliceInventory needs from the ledger (breaks import cycle)."""

    def reserved_on(self, host_id: str):  # pragma: no cover - interface
        raise NotImplementedError

    def host_generation(self, host_id: str):
        """Change token for ``reserved_on(host_id)``; snapshots cached
        against it are reused while it compares equal.  None (the
        default) means "unknown — never cache"."""
        return None


def make_test_fleet(
    slice_id: str = "pod-0",
    host_grid: Tuple[int, int] = (2, 2),
    chip_block: Tuple[int, int] = (2, 2),
    generation: str = "v5e",
    cpus: float = 16.0,
    memory_mb: int = 65536,
    zone_of=None,
) -> List[TpuHost]:
    """Fabricate a TPU pod's hosts (the SendOffer-builder equivalent,
    reference: sdk/testing Expect/SendOffer fixtures)."""
    hosts = []
    for gy in range(host_grid[1]):
        for gx in range(host_grid[0]):
            host_id = f"{slice_id}-h{gx}-{gy}"
            hosts.append(
                TpuHost(
                    host_id=host_id,
                    slice_id=slice_id,
                    generation=generation,
                    grid=(gx, gy),
                    chip_block=chip_block,
                    cpus=cpus,
                    memory_mb=memory_mb,
                    zone=zone_of(gx, gy) if zone_of else f"zone-{gx}",
                )
            )
    return hosts
