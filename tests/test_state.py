"""State layer tests (mirrors reference StateStoreTest/ConfigStoreTest)."""

import pytest

from dcos_commons_tpu.common import TaskInfo, TaskState, TaskStatus, new_task_id, task_name_of
from dcos_commons_tpu.state import (
    ConfigStore,
    FrameworkStore,
    GoalStateOverride,
    OverrideProgress,
    PersistentLaunchRecorder,
    SchemaVersionStore,
    StateStore,
    StateStoreException,
)
from dcos_commons_tpu.storage import MemPersister


def make_info(name="hello-0-server", agent="host-0"):
    return TaskInfo(
        name=name,
        task_id=new_task_id(name),
        agent_id=agent,
        pod_type="hello",
        pod_index=0,
        command="echo hi",
        env={"FOO": "bar"},
        tpu_chip_ids=["host-0/chip-0"],
        labels={"target_configuration": "cfg-1"},
    )


def test_task_id_scheme():
    tid = new_task_id("hello-0-server")
    assert task_name_of(tid) == "hello-0-server"
    with pytest.raises(ValueError):
        task_name_of("no-separator")


def test_task_info_roundtrip():
    info = make_info()
    restored = TaskInfo.from_bytes(info.to_bytes())
    assert restored == info


def test_state_store_tasks():
    store = StateStore(MemPersister())
    info = make_info()
    store.store_tasks([info])
    assert store.fetch_task_names() == ["hello-0-server"]
    assert store.fetch_task("hello-0-server") == info
    assert store.fetch_task("missing") is None
    assert store.fetch_tasks() == [info]
    store.clear_task("hello-0-server")
    assert store.fetch_tasks() == []


def test_state_store_status_validation():
    store = StateStore(MemPersister())
    info = make_info()
    store.store_tasks([info])
    status = TaskStatus(task_id=info.task_id, state=TaskState.RUNNING)
    store.store_status(info.name, status)
    fetched = store.fetch_status(info.name)
    assert fetched.state == TaskState.RUNNING
    assert fetched.state.is_running
    # stale task-id dropped, not stored (reference: StateStore.java
    # storeStatus validation; late statuses from old launches are normal)
    assert not store.store_status(
        info.name, TaskStatus(task_id="other__123", state=TaskState.FAILED)
    )
    assert store.fetch_status(info.name).state == TaskState.RUNNING


def test_state_store_rejects_bad_task_names():
    store = StateStore(MemPersister())
    with pytest.raises(StateStoreException):
        store.store_tasks([make_info("evil/name")])


def test_store_launch_atomic():
    store = StateStore(MemPersister())
    infos = [make_info("p-0-a"), make_info("p-0-b")]
    store.store_launch(infos)
    assert store.fetch_status("p-0-a").state == TaskState.STAGING
    assert store.fetch_task("p-0-b") == infos[1]


def test_state_store_namespacing():
    persister = MemPersister()
    a = StateStore(persister, namespace="services/svc-a")
    b = StateStore(persister, namespace="services/svc-b")
    a.store_tasks([make_info("a-0-node")])
    b.store_tasks([make_info("b-0-node")])
    assert a.fetch_task_names() == ["a-0-node"]
    assert b.fetch_task_names() == ["b-0-node"]


def test_goal_override_roundtrip():
    store = StateStore(MemPersister())
    assert store.fetch_goal_override("t") == (
        GoalStateOverride.NONE,
        OverrideProgress.COMPLETE,
    )
    store.store_goal_override("t", GoalStateOverride.PAUSED, OverrideProgress.PENDING)
    assert store.fetch_goal_override("t") == (
        GoalStateOverride.PAUSED,
        OverrideProgress.PENDING,
    )


def test_properties_and_deploy_bit():
    store = StateStore(MemPersister())
    store.store_property("suppressed", b"true")
    assert store.fetch_property("suppressed") == b"true"
    assert "suppressed" in store.fetch_property_keys()
    store.clear_property("suppressed")
    assert store.fetch_property("suppressed") is None
    with pytest.raises(StateStoreException):
        store.store_property("bad/key", b"x")
    assert not store.deployment_was_completed()
    store.set_deployment_completed()
    assert store.deployment_was_completed()


def test_config_store():
    cs = ConfigStore(MemPersister())
    cfg = {"name": "svc", "pods": [{"name": "hello", "count": 1}]}
    cid = cs.store(cfg)
    assert cs.fetch(cid) == cfg
    cs.set_target_config(cid)
    assert cs.get_target_config() == cid
    assert cs.fetch_target() == cfg
    cid2 = cs.store({"name": "svc", "pods": []})
    cs.set_target_config(cid2)
    removed = cs.prune(referenced_ids=[])
    assert removed == [cid]
    assert cs.fetch(cid) is None
    assert cs.fetch(cid2) is not None


def test_framework_store():
    fs = FrameworkStore(MemPersister())
    fid = fs.get_or_create_framework_id()
    assert fs.get_or_create_framework_id() == fid
    fs.store_coordinator_address("trainer", "10.0.0.1:8476")
    assert fs.fetch_coordinator_address("trainer") == "10.0.0.1:8476"
    assert fs.fetch_coordinator_address("other") is None
    fs.clear_framework_id()
    assert fs.fetch_framework_id() is None


def test_schema_version():
    p = MemPersister()
    svs = SchemaVersionStore(p)
    svs.check()  # initializes
    assert svs.fetch() == SchemaVersionStore.CURRENT
    svs.store(99)
    with pytest.raises(RuntimeError):
        SchemaVersionStore(p).check()


def test_launch_recorder_seeds_staging():
    store = StateStore(MemPersister())
    recorder = PersistentLaunchRecorder(store)
    infos = [make_info("p-0-a"), make_info("p-0-b")]
    recorder.record(infos)
    assert store.fetch_status("p-0-a").state == TaskState.STAGING
    assert store.fetch_status("p-0-b").state == TaskState.STAGING
    assert store.fetch_task("p-0-a").task_id == infos[0].task_id
