"""Offer disciplines: which services may GROW footprint this cycle.

Reference: scheduler/multi/OfferDiscipline.java:11-33 +
ParallelFootprintDiscipline — services already at full footprint
always get offers (launch/maintenance); reservation growth is limited
to a sticky set of at most N services, so a burst of new services
deploys N-at-a-time instead of thrashing the fleet.
"""

from __future__ import annotations

from typing import List, Set


class AnyFootprintDiscipline:
    """No limit (reference: OfferDiscipline.Any)."""

    def select(self, growing: List[str]) -> Set[str]:
        return set(growing)


class ParallelFootprintDiscipline:
    def __init__(self, max_concurrent: int = 1):
        if max_concurrent < 1:
            raise ValueError("max_concurrent must be >= 1")
        self._max = max_concurrent
        self._selected: Set[str] = set()

    def select(self, growing: List[str]) -> Set[str]:
        """Sticky selection: a service keeps its slot until it stops
        growing; freed slots go to the longest-waiting services."""
        self._selected &= set(growing)
        for name in growing:
            if len(self._selected) >= self._max:
                break
            self._selected.add(name)
        return set(self._selected)
