"""Contiguous sub-slice search over the ICI torus.

The TPU-first replacement for NCCL-ring/host-affinity placement
(BASELINE.json north star): a gang pod requesting topology (tx, ty)
must land on a *contiguous axis-aligned rectangle* of hosts inside one
physical slice, so that the XLA mesh's collectives ride ICI links.
Contiguity on the torus also makes ring-attention neighbors
ICI-adjacent (SURVEY.md section 5.7).

Search: per slice, enumerate anchor positions row-major and take the
first fully-eligible rectangle (corner-first packing keeps large holes
open - simple and explainable, which matters more here than optimal
bin packing; the outcome tracker reports every rejected anchor).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from dcos_commons_tpu.offer.inventory import ResourceSnapshot
from dcos_commons_tpu.offer.outcome import EvaluationOutcome


class TorusPlacement:
    def __init__(self, snapshots: List[ResourceSnapshot], outcome: EvaluationOutcome):
        self.snapshots = snapshots          # row-major, instance order
        self.outcome = outcome


def find_subslice(
    snapshots: List[ResourceSnapshot],
    topology: Tuple[int, ...],
    chips_per_host: int,
    eligible: Callable[[ResourceSnapshot], EvaluationOutcome],
) -> TorusPlacement:
    """Find hosts forming a contiguous ``topology`` chip rectangle.

    ``eligible`` runs placement rules + scalar checks per host; its
    failures are recorded in the returned outcome tree.
    """
    if len(topology) == 3 and topology[2] == 1:
        topology = topology[:2]
    if len(topology) == 1:
        topology = (topology[0], 1)
    if len(topology) != 2:
        return TorusPlacement(
            [],
            EvaluationOutcome.fail(
                "torus",
                f"only 2D topologies supported this generation: {topology}",
            ),
        )
    tx, ty = topology

    outcome = EvaluationOutcome.ok("torus", f"searching {tx}x{ty}")
    by_slice: Dict[str, List[ResourceSnapshot]] = {}
    for snap in snapshots:
        if snap.host.generation:
            by_slice.setdefault(snap.host.slice_id, []).append(snap)

    if not by_slice:
        outcome.passed = False
        outcome.reason = "no TPU hosts in inventory"
        return TorusPlacement([], outcome)

    for slice_id, slice_snaps in sorted(by_slice.items()):
        placement = _search_slice(slice_id, slice_snaps, tx, ty, eligible, outcome)
        if placement is not None:
            return TorusPlacement(placement, outcome)

    outcome.passed = False
    outcome.reason = f"no contiguous {tx}x{ty} sub-slice available"
    return TorusPlacement([], outcome)


def _search_slice(
    slice_id: str,
    snaps: List[ResourceSnapshot],
    tx: int,
    ty: int,
    eligible: Callable[[ResourceSnapshot], EvaluationOutcome],
    outcome: EvaluationOutcome,
) -> Optional[List[ResourceSnapshot]]:
    blocks = {s.host.chip_block for s in snaps}
    if len(blocks) != 1:
        outcome.children.append(
            EvaluationOutcome.fail(
                f"slice:{slice_id}", f"mixed chip blocks {sorted(blocks)}"
            )
        )
        return None
    bw, bh = blocks.pop()
    if bw == 0 or tx % bw or ty % bh:
        outcome.children.append(
            EvaluationOutcome.fail(
                f"slice:{slice_id}",
                f"topology {tx}x{ty} not tileable by host block {bw}x{bh}",
            )
        )
        return None
    need_x, need_y = tx // bw, ty // bh

    grid: Dict[Tuple[int, int], ResourceSnapshot] = {
        s.host.grid: s for s in snaps
    }
    max_x = max(g[0] for g in grid) + 1
    max_y = max(g[1] for g in grid) + 1
    if need_x > max_x or need_y > max_y:
        outcome.children.append(
            EvaluationOutcome.fail(
                f"slice:{slice_id}",
                f"slice host grid {max_x}x{max_y} smaller than "
                f"required {need_x}x{need_y}",
            )
        )
        return None

    # cache per-host eligibility so each host is checked once per search
    cache: Dict[Tuple[int, int], EvaluationOutcome] = {}

    def check(pos: Tuple[int, int]) -> Optional[EvaluationOutcome]:
        snap = grid.get(pos)
        if snap is None:
            return None
        if pos not in cache:
            child = eligible(snap)
            if child.passed and len(snap.free_chips) < snap.host.chips_per_host:
                child = EvaluationOutcome.fail(
                    f"host:{snap.host.host_id}",
                    f"only {len(snap.free_chips)}/{snap.host.chips_per_host} "
                    "chips free (partially reserved)",
                )
            cache[pos] = child
        return cache[pos]

    # wrap-around: a slice whose ICI closes into a torus on an axis
    # (full-pod axes on v4/v5p, 16-wide v5e slices) admits rectangles
    # that cross the edge.  Opt-in per slice via host attributes:
    # ``ici_wrap`` in {x, y, both} plus the PHYSICAL ring
    # circumference ``ring_x``/``ring_y`` — the modulo must come from
    # the hardware ring, never the observed extent of up hosts (a down
    # edge host would shrink it and join non-adjacent hosts).
    attrs = next(iter(snaps)).host.attributes
    wrap_attr = attrs.get("ici_wrap", "")

    def _ring(key: str) -> int:
        # attributes are free-form operator strings: a typo must not
        # crash the offer cycle — it just disables wrap on that axis
        try:
            return int(attrs.get(key, 0) or 0)
        except (TypeError, ValueError):
            return 0

    ring_x = _ring("ring_x")
    ring_y = _ring("ring_y")
    wrap_x = wrap_attr in ("x", "both") and ring_x >= max_x and \
        need_x < ring_x
    wrap_y = wrap_attr in ("y", "both") and ring_y >= max_y and \
        need_y < ring_y
    mod_x = ring_x if wrap_x else max(max_x, need_x)
    mod_y = ring_y if wrap_y else max(max_y, need_y)
    anchors_x = range(ring_x if wrap_x else max_x - need_x + 1)
    anchors_y = range(ring_y if wrap_y else max_y - need_y + 1)
    for ay in anchors_y:
        for ax in anchors_x:
            rect = [
                ((ax + dx) % mod_x, (ay + dy) % mod_y)
                for dy in range(need_y)
                for dx in range(need_x)
            ]
            failures = []
            for pos in rect:
                child = check(pos)
                if child is None:
                    failures.append(
                        EvaluationOutcome.fail(
                            f"slice:{slice_id}", f"no host at grid {pos}"
                        )
                    )
                    break
                if not child.passed:
                    failures.append(child)
                    break
            if not failures:
                outcome.children.append(
                    EvaluationOutcome.ok(
                        f"slice:{slice_id}",
                        f"anchor {ax},{ay}: {need_x}x{need_y} hosts",
                    )
                )
                return [grid[pos] for pos in rect]
            outcome.children.append(
                EvaluationOutcome(
                    False,
                    f"slice:{slice_id}@{ax},{ay}",
                    "anchor rejected",
                    failures,
                )
            )
    return None
