"""Fleet-scale offer cycle tests (ISSUE 9 tentpole).

Four properties are load-bearing:

1. EQUIVALENCE: the incremental/indexed evaluator (dirty-host
   snapshot sync + candidate pre-filtering + requirement memo) must
   produce IDENTICAL evaluation outcomes to the full-rebuild path —
   same winner hosts, same failing_requirement reasons — under
   randomized interleavings of reservations, host add/remove/up/down,
   and pod relaunches.
2. Dirty-host sync: an unchanged fleet costs an O(1) token compare;
   a single commit re-synthesizes exactly the touched host; caches
   are PER VIEW, so alternating views never thrash each other.
3. Copy-on-write: shared snapshots raise on mutation; copies consume
   freely.
4. Suppress/revive: a multi-service scheduler skips services with no
   pending work and revives them on status arrival and on HTTP-verb
   nudges — a suppressed service never misses work.
"""

import pytest

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.offer import (
    OfferEvaluator,
    Reservation,
    ReservationLedger,
    SliceInventory,
    TpuHost,
)
from dcos_commons_tpu.offer.inventory import make_test_fleet
from dcos_commons_tpu.offer.ledger import new_reservation_id
from dcos_commons_tpu.plan.step import PodInstanceRequirement, RecoveryType
from dcos_commons_tpu.specification import from_yaml
from dcos_commons_tpu.state import StateStore
from dcos_commons_tpu.storage import MemPersister

# -- equivalence: incremental/indexed == full rebuild -----------------

FLEET_YAML = """
name: fleet
pods:
  app:
    count: 6
    placement: '{placement}'
    tasks:
      server:
        cmd: "serve"
        cpus: 1.0
        memory: 1024
  tpuapp:
    count: 2
    placement: '{placement}'
    tpu:
      generation: v5e
      chips-per-host: 4
    tasks:
      worker:
        cmd: "python train.py"
        cpus: 1.0
        memory: 1024
  gangpod:
    count: 4
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
    tasks:
      worker:
        goal: FINISH
        cmd: "python train.py"
        cpus: 1.0
        memory: 1024
"""

PLACEMENTS = [
    "",
    "max-per-host:1",
    "max-per-zone:3",
    "group-by:zone",
    "round-robin:zone",
    "zone:exact:zone-0,zone-1",
    "hostname:regex:pod-0-.*",
    "task-type:avoid:app",
    "task-type:colocate:app",
    "generation:v5e",
    "same-slice",
    "max-per-host:1 && zone:exact:zone-0 || group-by:zone",
]


def build_world(placement=""):
    spec = from_yaml(FLEET_YAML.replace("{placement}", placement))
    persister = MemPersister()
    store = StateStore(persister)
    ledger = ReservationLedger(persister)
    hosts = make_test_fleet(
        slice_id="pod-0", host_grid=(4, 2), chip_block=(2, 2), cpus=16.0
    ) + make_test_fleet(
        slice_id="pod-1", host_grid=(4, 2), chip_block=(2, 2), cpus=16.0
    ) + [TpuHost(host_id=f"cpu-{i}", zone=f"zone-{i % 2}") for i in range(4)]
    inv = SliceInventory(hosts)
    ev = OfferEvaluator(store, ledger, spec.name, "cfg-1")
    return spec, store, ledger, ev, inv, hosts


def oracle_result(spec, store, ledger, hosts, down, requirement):
    """Full-rebuild evaluation of the same state: fresh inventory
    (empty caches), fast path disabled — the PR-1 behavior."""
    oracle_inv = SliceInventory(hosts)
    for host_id in down:
        oracle_inv.mark_down(host_id)
    oracle_ev = OfferEvaluator(store, ledger, spec.name, "cfg-1")
    oracle_ev.fast_path = False
    return oracle_ev.evaluate(requirement, oracle_inv)


def outcome_signature(result):
    """What must be identical between the two paths: pass/fail, the
    chosen hosts (in worker order), and the failing reason."""
    return (
        result.passed,
        [r.host_id for r in result.reservations],
        [t.agent_id for t in result.task_infos],
        result.outcome.reason or result.outcome.source,
    )


def test_equivalence_randomized_interleavings():
    """Deterministic randomized sweep (runs without hypothesis): the
    incremental evaluator tracks the full-rebuild oracle through
    reservation churn, host up/down/add/remove, and relaunches."""
    import random

    rng = random.Random(20260803)
    for placement in PLACEMENTS:
        spec, store, ledger, ev, inv, hosts = build_world(placement)
        hosts = list(hosts)
        down = set()
        for step in range(40):
            op = rng.random()
            if op < 0.35:
                host = rng.choice(hosts)
                chips = host.chip_ids()
                ledger.commit([Reservation(
                    reservation_id=new_reservation_id(),
                    host_id=host.host_id,
                    task_name=f"app-{rng.randrange(6)}-server",
                    cpus=rng.choice([0.5, 2.0]),
                    memory_mb=rng.choice([256, 2048]),
                    chip_ids=(
                        rng.sample(chips, rng.randrange(len(chips) + 1))
                        if chips else []
                    ),
                )])
            elif op < 0.55:
                live = ledger.all()
                if live:
                    ledger.release(rng.choice(live).reservation_id)
            elif op < 0.7:
                host = rng.choice(hosts)
                inv.mark_down(host.host_id)
                down.add(host.host_id)
            elif op < 0.85:
                if down:
                    host_id = down.pop()
                    inv.mark_up(host_id)
            else:
                new_host = TpuHost(
                    host_id=f"extra-{step}", zone=f"zone-{step % 2}"
                )
                hosts.append(new_host)
                inv.add_host(new_host)
            pod_name = rng.choice(["app", "tpuapp", "gangpod"])
            pod = spec.pod(pod_name)
            instances = (
                list(range(pod.count)) if pod.gang
                else [rng.randrange(pod.count)]
            )
            recovery = rng.choice(
                [RecoveryType.NONE, RecoveryType.TRANSIENT,
                 RecoveryType.PERMANENT]
            )
            requirement = PodInstanceRequirement(
                pod=pod, instances=instances, recovery_type=recovery
            )
            fast = ev.evaluate(requirement, inv)
            slow = oracle_result(spec, store, ledger, hosts, down, requirement)
            assert outcome_signature(fast) == outcome_signature(slow), (
                f"diverged at step {step} placement={placement!r} "
                f"pod={pod_name} recovery={recovery}"
            )
        assert ev.fast_path  # the sweep exercised the indexed path


def test_equivalence_property_hypothesis():
    """Hypothesis-driven version: arbitrary op sequences, any
    placement rule, same-winner/same-reason equivalence."""
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    ops = st.lists(
        st.tuples(
            st.sampled_from(
                ["commit", "release", "down", "up", "evaluate"]
            ),
            st.integers(min_value=0, max_value=10 ** 6),
        ),
        min_size=1, max_size=25,
    )

    @settings(max_examples=40, deadline=None)
    @given(
        placement=st.sampled_from(PLACEMENTS),
        sequence=ops,
        seed=st.integers(min_value=0, max_value=2 ** 20),
    )
    def run(placement, sequence, seed):
        import random

        rng = random.Random(seed)
        spec, store, ledger, ev, inv, hosts = build_world(placement)
        down = set()
        for op, arg in sequence:
            if op == "commit":
                host = hosts[arg % len(hosts)]
                chips = host.chip_ids()
                ledger.commit([Reservation(
                    reservation_id=new_reservation_id(),
                    host_id=host.host_id,
                    task_name=f"app-{arg % 6}-server",
                    cpus=0.5 + (arg % 4),
                    memory_mb=256,
                    chip_ids=chips[: arg % (len(chips) + 1)] if chips else [],
                )])
            elif op == "release":
                live = ledger.all()
                if live:
                    ledger.release(live[arg % len(live)].reservation_id)
            elif op == "down":
                host_id = hosts[arg % len(hosts)].host_id
                inv.mark_down(host_id)
                down.add(host_id)
            elif op == "up":
                if down:
                    host_id = sorted(down)[arg % len(down)]
                    down.discard(host_id)
                    inv.mark_up(host_id)
            else:
                pod = spec.pod(
                    ["app", "tpuapp", "gangpod"][arg % 3]
                )
                instances = (
                    list(range(pod.count)) if pod.gang
                    else [arg % pod.count]
                )
                requirement = PodInstanceRequirement(
                    pod=pod, instances=instances,
                    recovery_type=(
                        [RecoveryType.NONE, RecoveryType.TRANSIENT,
                         RecoveryType.PERMANENT][arg % 3]
                    ),
                )
                fast = ev.evaluate(requirement, inv)
                slow = oracle_result(
                    spec, store, ledger, hosts, down, requirement
                )
                assert outcome_signature(fast) == outcome_signature(slow)

    run()


# -- incremental sync mechanics ---------------------------------------


def test_idle_sync_is_token_compare_only():
    """An unchanged fleet re-syncs with zero rebuilds; one commit
    dirties exactly the touched host."""
    ledger = ReservationLedger(MemPersister())
    inv = SliceInventory(make_test_fleet(host_grid=(4, 4)))
    inv.snapshots(ledger)
    assert inv.cache_misses == 16
    inv.snapshots(ledger)
    assert inv.cache_misses == 16 and inv.last_dirty_hosts == 0
    target = inv.hosts()[5]
    ledger.commit([Reservation(
        reservation_id=new_reservation_id(),
        host_id=target.host_id, task_name="t-0-x", cpus=1.0,
    )])
    inv.snapshots(ledger)
    assert inv.last_dirty_hosts == 1
    assert inv.cache_misses == 17  # exactly one rebuild


def test_gang_prefilter_includes_sliceless_hosts():
    """Regression (review): TPU hosts registered WITHOUT a slice id
    group under slice "" in find_subslice and can host a gang; the
    torus pre-filter skipping the "" bucket made the indexed path
    fail a gang the full scan places whenever some NAMED slice passed
    the fully-free count filter but lost the actual search."""
    yaml_text = """
name: fleet
pods:
  gang:
    count: 4
    gang: true
    placement: 'zone:exact:good'
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
    tasks:
      worker:
        goal: FINISH
        cmd: "python train.py"
        cpus: 1.0
        memory: 1024
"""
    spec = from_yaml(yaml_text)
    persister = MemPersister()
    store = StateStore(persister)
    ledger = ReservationLedger(persister)
    # named slice: enough fully-free hosts to pass the count filter,
    # but the zone rule rejects every one of them
    hosts = make_test_fleet(
        slice_id="pod-a", host_grid=(2, 2), chip_block=(2, 2),
        zone_of=lambda gx, gy: "bad",
    ) + [
        TpuHost(
            host_id=f"adhoc-{gx}-{gy}", slice_id="", generation="v5e",
            grid=(gx, gy), chip_block=(2, 2), zone="good",
        )
        for gx in range(2) for gy in range(2)
    ]
    inv = SliceInventory(hosts)
    ev = OfferEvaluator(store, ledger, spec.name, "cfg-1")
    requirement = PodInstanceRequirement(
        pod=spec.pod("gang"), instances=[0, 1, 2, 3]
    )
    fast = ev.evaluate(requirement, inv)
    slow = oracle_result(spec, store, ledger, hosts, set(), requirement)
    assert outcome_signature(fast) == outcome_signature(slow)
    assert fast.passed, fast.outcome.flatten()
    assert all(r.host_id.startswith("adhoc-") for r in fast.reservations)


def test_ledger_host_gen_journal_compacts_and_stays_sound():
    """Months of host churn (every replaced host once held a claim)
    must not grow the ledger's per-host stamp journal without bound
    (review: the inventory journal got this, the ledger's did not) —
    and compaction must never let a pre-compaction token miss a
    pruned host's release: such tokens fall back to a full resync."""
    ledger = ReservationLedger(MemPersister())
    pre_token = ledger.generation_token()
    # churn: claim + release a long parade of one-shot hosts
    for i in range(200):
        rid = new_reservation_id()
        ledger.commit([Reservation(
            reservation_id=rid, host_id=f"ephemeral-{i}",
            task_name="t-0-x", cpus=1.0,
        )])
        ledger.release(rid)
    # a few live claims remain
    for i in range(3):
        ledger.commit([Reservation(
            reservation_id=new_reservation_id(), host_id=f"live-{i}",
            task_name="t-0-y", cpus=1.0,
        )])
    assert len(ledger._host_gen) <= max(16, 2 * 3) + 1, \
        len(ledger._host_gen)
    # the stale token cannot be answered incrementally (pruned stamps
    # postdate it) — None = caller rebuilds everything, missing nothing
    assert ledger.changed_hosts_since(pre_token) is None
    # post-compaction tokens keep the O(dirty) incremental contract
    token = ledger.generation_token()
    assert ledger.changed_hosts_since(token) == set()
    ledger.commit([Reservation(
        reservation_id=new_reservation_id(), host_id="live-0",
        task_name="t-0-z", cpus=1.0,
    )])
    assert ledger.changed_hosts_since(token) == {"live-0"}
    # and the full snapshot sync over a compacted ledger stays exact
    inv = SliceInventory(
        [TpuHost(host_id=f"live-{i}") for i in range(3)]
    )
    snaps = {s.host.host_id: s for s in inv.snapshots(ledger)}
    # live-0 carries two 1.0-cpu claims, live-1 one: the compacted
    # journal still yields exact per-host accounting
    assert snaps["live-1"].cpus - snaps["live-0"].cpus == 1.0
    inv.snapshots(ledger)
    assert inv.last_dirty_hosts == 0


def test_noop_topology_mutations_do_not_dirty():
    """mark_up of an up host, mark_down of a down host, remove of an
    unknown host: all no-ops — no generation bump, no fleet-wide
    invalidation (satellite fix)."""
    inv = SliceInventory(make_test_fleet())
    gen = inv.topology_generation
    inv.mark_up(inv.hosts()[0].host_id)       # already up
    inv.mark_up("never-heard-of-it")          # unknown
    inv.remove_host("never-heard-of-it")      # unknown
    assert inv.topology_generation == gen
    inv.mark_down(inv.hosts()[0].host_id)
    assert inv.topology_generation == gen + 1
    inv.mark_down(inv.hosts()[0].host_id)     # already down
    assert inv.topology_generation == gen + 1
    inv.mark_up(inv.hosts()[0].host_id)
    assert inv.topology_generation == gen + 2


def test_per_view_caches_do_not_thrash():
    """Two ledger views alternating against one inventory each keep
    their own cache (satellite fix: the old single-view cache was
    cleared wholesale on every alternation)."""
    persister = MemPersister()
    ledger_a = ReservationLedger(persister, "svc-a")
    ledger_b = ReservationLedger(persister, "svc-b")
    inv = SliceInventory(make_test_fleet(host_grid=(2, 2)))
    inv.snapshots(ledger_a)
    inv.snapshots(ledger_b)
    misses_after_warmup = inv.cache_misses
    for _ in range(5):
        inv.snapshots(ledger_a)
        inv.snapshots(ledger_b)
    assert inv.cache_misses == misses_after_warmup
    assert inv.cache_hits >= 40  # 5 alternations x 2 views x 4 hosts


def test_shared_snapshots_copy_on_write():
    """offer_view hands out shared masters: mutators raise until
    copy(); the copy consumes freely and the master is unharmed."""
    ledger = ReservationLedger(MemPersister())
    inv = SliceInventory(make_test_fleet(host_grid=(1, 1)))
    index = inv.offer_view(ledger)
    [snap] = index.ordered_snapshots()
    assert snap.shared
    with pytest.raises(RuntimeError, match="copy"):
        snap.try_consume_scalar(1.0, 1, 0)
    with pytest.raises(RuntimeError, match="copy"):
        snap.try_consume_chips(1)
    with pytest.raises(RuntimeError, match="copy"):
        snap.allocate_port()
    work = snap.copy()
    assert work.try_consume_scalar(1.0, 1, 0)
    assert work.try_consume_chips(1)
    again = inv.offer_view(ledger).ordered_snapshots()[0]
    assert again.cpus == snap.cpus and len(again.free_chips) == 4


def test_requirement_memo_short_circuits_and_invalidates():
    """A failing requirement against an unchanged fleet short-circuits
    (no re-scan); any ledger change invalidates the memo."""
    from dcos_commons_tpu.metrics.registry import Metrics

    spec, store, ledger, ev, inv, hosts = build_world("zone:exact:nowhere")
    ev.metrics = Metrics()
    requirement = PodInstanceRequirement(pod=spec.pod("app"), instances=[0])
    first = ev.evaluate(requirement, inv)
    assert not first.passed
    again = ev.evaluate(requirement, inv)
    assert outcome_signature(again) == outcome_signature(first)
    counters = ev.metrics.counters()
    assert counters.get("offers.eval.shortcircuit", 0) == 1
    # a commit anywhere voids the memo
    ledger.commit([Reservation(
        reservation_id=new_reservation_id(),
        host_id=hosts[0].host_id, task_name="t-0-x", cpus=0.5,
    )])
    third = ev.evaluate(requirement, inv)
    assert not third.passed
    assert ev.metrics.counters().get("offers.eval.shortcircuit", 0) == 1


def test_multi_instance_requirement_counts_recorded_instances():
    """Regression (review r9): a multi-instance requirement evaluated
    in ONE call must count its earlier instances for max-per rules on
    the later ones — the indexed path once filtered the just-placed
    tasks through the requirement's own excluded names, letting two
    instances land on one host."""
    yaml_text = """
name: spread
pods:
  app:
    count: 2
    gang: true
    placement: 'max-per-host:1'
    tasks:
      main:
        goal: RUNNING
        cmd: sleep 1000
        cpus: 0.5
        memory: 256
"""
    spec = from_yaml(yaml_text)
    persister = MemPersister()
    store = StateStore(persister)
    ledger = ReservationLedger(persister)
    hosts = [TpuHost(host_id=f"h{i}") for i in range(4)]
    inv = SliceInventory(hosts)
    ev = OfferEvaluator(store, ledger, spec.name, "cfg-1")
    requirement = PodInstanceRequirement(
        pod=spec.pod("app"), instances=[0, 1]
    )
    result = ev.evaluate(requirement, inv)
    assert result.passed, result.outcome.flatten()
    placed_hosts = [t.agent_id for t in result.task_infos]
    assert len(set(placed_hosts)) == 2, (
        f"max-per-host:1 violated within one requirement: {placed_hosts}"
    )
    # and it still matches the full-rebuild oracle
    slow = oracle_result(spec, store, ledger, hosts, set(), requirement)
    assert outcome_signature(result) == outcome_signature(slow)


def test_ledger_rebuild_invalidates_view_cache():
    """Regression (review r9): a rebuilt ledger (service upgrade /
    restart re-loads the same persisted tree) restarts its generation
    counter.  A LONG-LIVED view over a swappable ledger — the
    multi-service merged view's shape — must fully resync, not trust
    the rebased generations (which can numerically collide with the
    stale token)."""

    class SwappableView:
        def __init__(self, ledger):
            self.ledger = ledger

        def reserved_on(self, host_id):
            return self.ledger.reserved_on(host_id)

        def host_generation(self, host_id):
            return (self.ledger.epoch, self.ledger.host_generation(host_id))

        def generation_token(self):
            return self.ledger.generation_token()

        def changed_hosts_since(self, token):
            return self.ledger.changed_hosts_since(token)

    persister = MemPersister()
    hosts = make_test_fleet(host_grid=(2, 2))
    inv = SliceInventory(hosts)
    view = SwappableView(ReservationLedger(persister))
    inv.snapshots(view)
    # a commit the cache never observes before the rebuild...
    view.ledger.commit([Reservation(
        reservation_id=new_reservation_id(),
        host_id=hosts[0].host_id, task_name="t-0-x", cpus=10.0,
    )])
    old_token = view.ledger.generation_token()
    # ...then the rebuild: same persisted tree, fresh counters.  The
    # new generation (1 load + 1 commit land at 2 = the stale token's)
    # would alias without the epoch.
    view.ledger = ReservationLedger(persister)
    assert view.ledger.changed_hosts_since(old_token) is None
    snaps = {s.host.host_id: s for s in inv.snapshots(view)}
    assert snaps[hosts[0].host_id].cpus == hosts[0].cpus - 10.0, (
        "stale snapshot served after ledger rebuild"
    )


def test_gang_prefilter_uses_host_blocks_not_declared_chips():
    """Regression (review r9): the torus slice pre-filter must size
    per-slice host need from the HOSTS' chip blocks — a spec that
    mis-declares chips-per-host must not make the fast path skip a
    slice the full search would place in."""
    yaml_text = """
name: jax
pods:
  trainer:
    count: 2
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
    tasks:
      worker:
        goal: FINISH
        cmd: "python train.py"
        cpus: 1.0
        memory: 1024
"""
    spec = from_yaml(yaml_text)
    persister = MemPersister()
    store = StateStore(persister)
    ledger = ReservationLedger(persister)
    # slice-a hosts own 2x4 = 8-chip blocks: a 4x4 topology needs TWO
    # fully free hosts there, not the declared-chips-derived four.
    # slice-b is the decoy: four 4-chip hosts (passes the BUGGY
    # declared-chips count) in a 4x1 grid that can never tile 4x4 —
    # without it the empty-eligible fallback would mask the bug.
    hosts = make_test_fleet(
        slice_id="pod-a", host_grid=(2, 1), chip_block=(2, 4), cpus=16.0
    ) + make_test_fleet(
        slice_id="pod-b", host_grid=(4, 1), chip_block=(2, 2), cpus=16.0
    )
    inv = SliceInventory(hosts)
    ev = OfferEvaluator(store, ledger, spec.name, "cfg-1")
    requirement = PodInstanceRequirement(
        pod=spec.pod("trainer"), instances=[0, 1]
    )
    fast = ev.evaluate(requirement, inv)
    slow = oracle_result(spec, store, ledger, hosts, set(), requirement)
    assert outcome_signature(fast) == outcome_signature(slow)
    assert fast.passed, fast.outcome.flatten()


def test_view_cache_bounded_under_view_churn():
    """Regression (review r9): superseded view objects (live options
    updates rebuild the ledger) must not pin fleet-sized snapshot
    caches forever."""
    persister = MemPersister()
    inv = SliceInventory(make_test_fleet(host_grid=(2, 2)))
    for _ in range(40):
        inv.snapshots(ReservationLedger(persister))
    assert len(inv._view_caches) <= inv._MAX_VIEW_CACHES


def test_admission_feasibility_is_per_host_not_composite():
    """Regression (review r9): a fleet with a 16-cpu/low-mem host and
    an 8-cpu/high-mem host must REJECT a pod needing 12 cpus AND high
    memory — no single host fits, even though the per-dimension maxima
    would."""
    from dcos_commons_tpu.multi.admission import validate_service_yaml

    inv = SliceInventory([
        TpuHost(host_id="cpuheavy", cpus=16.0, memory_mb=4096),
        TpuHost(host_id="memheavy", cpus=8.0, memory_mb=262144),
    ])
    yaml_text = """
name: fatpod
pods:
  app:
    count: 1
    tasks:
      main:
        goal: RUNNING
        cmd: sleep 1000
        cpus: 12
        memory: 131072
"""
    spec, findings = validate_service_yaml(yaml_text, "fatpod", inventory=inv)
    assert any(f.rule == "spec-resources" for f in findings), findings
    # and each shape alone still admits what fits it
    fits = yaml_text.replace("cpus: 12", "cpus: 4")
    spec, findings = validate_service_yaml(fits, "fatpod", inventory=inv)
    assert not [f for f in findings if f.rule == "spec-resources"], findings
    # the rejection's remediation hint is the admission one, not the
    # CI walker's CLI flags (which do not exist for a PUT)
    spec, findings = validate_service_yaml(yaml_text, "fatpod", inventory=inv)
    msg = next(f for f in findings if f.rule == "spec-resources").message
    assert "--host-cpus" not in msg and "add larger hosts" in msg, msg


def test_admission_skips_feasibility_when_no_hosts_up():
    """A spec sized for the real fleet must be admitted while zero
    hosts are up (scheduler bootstrap, transient outage): judging it
    against the CI default shape would gate service registration on
    fleet availability — the deploy plan just waits for hosts."""
    from dcos_commons_tpu.multi.admission import validate_service_yaml

    yaml_text = """
name: bigpod
pods:
  app:
    count: 1
    tasks:
      main:
        goal: RUNNING
        cmd: sleep 1000
        cpus: 64
        memory: 524288
"""
    for inv in (None, SliceInventory([])):
        spec, findings = validate_service_yaml(
            yaml_text, "bigpod", inventory=inv
        )
        assert spec is not None and not findings, (inv, findings)
    # a fleet whose hosts are all DOWN is an unknown fleet too
    inv = SliceInventory([TpuHost(host_id="h0", cpus=128.0,
                                  memory_mb=1048576)])
    inv.mark_down("h0")
    spec, findings = validate_service_yaml(yaml_text, "bigpod", inventory=inv)
    assert spec is not None and not findings, findings
    # ...but an up host that cannot fit the pod still rejects
    inv.mark_up("h0")
    too_fat = yaml_text.replace("cpus: 64", "cpus: 256")
    spec, findings = validate_service_yaml(too_fat, "bigpod", inventory=inv)
    assert any(f.rule == "spec-resources" for f in findings), findings


# -- suppress / revive ------------------------------------------------

MULTI_SVC_YAML = """
name: {name}
pods:
  app:
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: sleep 1000
        cpus: 0.5
        memory: 256
"""


def build_multi():
    from dcos_commons_tpu.multi.scheduler import MultiServiceScheduler
    from dcos_commons_tpu.scheduler.config import SchedulerConfig
    from dcos_commons_tpu.testing import FakeAgent

    agent = FakeAgent()
    inv = SliceInventory([TpuHost(host_id=f"h{i}") for i in range(4)])
    multi = MultiServiceScheduler(
        MemPersister(), inv, agent,
        scheduler_config=SchedulerConfig(backoff_enabled=False),
    )
    return multi, agent


def deploy_all(multi, agent, cycles=10):
    for _ in range(cycles):
        multi.run_cycle()
        for info in list(agent.launched):
            agent.send(TaskStatus(
                task_id=info.task_id, state=TaskState.RUNNING,
                ready=True, agent_id=info.agent_id,
            ))
    multi.run_cycle()


def test_idle_services_suppress_and_status_revives():
    multi, agent = build_multi()
    multi.add_service(from_yaml(MULTI_SVC_YAML.format(name="svc-a")))
    multi.add_service(from_yaml(MULTI_SVC_YAML.format(name="svc-b")))
    deploy_all(multi, agent)
    for name in ("svc-a", "svc-b"):
        plan = multi.get_service(name).deploy_manager.get_plan()
        assert plan.is_complete, f"{name} did not deploy"
    multi.run_cycle()
    state = multi.suppress_state()
    assert state["suppressed_services"] == ["svc-a", "svc-b"]
    # the gauge rides every service's metrics snapshot
    svc = multi.get_service("svc-a")
    assert svc.metrics.snapshot()["cycle.suppressed_services"] == 2.0
    # a suppressed service's cycle count stays flat
    before = svc.metrics.snapshot().get("cycle.process.count", 0)
    for _ in range(3):
        multi.run_cycle()
    assert svc.metrics.snapshot().get("cycle.process.count", 0) == before
    # a status about its own task revives it (and only it)
    info = agent.task_info_of("app-0-server")
    assert info is not None
    agent.send(TaskStatus(
        task_id=info.task_id, state=TaskState.FAILED, agent_id=info.agent_id,
    ))
    multi.run_cycle()
    assert "svc-a" in multi.suppress_state()["suppressed_services"] or \
        "svc-b" in multi.suppress_state()["suppressed_services"]
    # the owner woke and scheduled recovery work; drive it to done
    deploy_all(multi, agent)
    owner = "svc-a" if multi.get_service("svc-a").state_store.fetch_task(
        "app-0-server"
    ) and multi.get_service("svc-a").state_store.fetch_task(
        "app-0-server"
    ).task_id == info.task_id else "svc-b"
    recovery = multi.get_service(owner).plan("recovery")
    assert recovery is None or not multi.get_service(owner).work_pending()


def test_http_mutation_revives_suppressed_service():
    """An operator verb (pod restart -> nudge) on a suppressed
    service revives it on the next merged cycle — it never misses
    the work its own mutation created."""
    multi, agent = build_multi()
    multi.add_service(from_yaml(MULTI_SVC_YAML.format(name="svc-a")))
    deploy_all(multi, agent)
    multi.run_cycle()
    assert multi.suppress_state()["suppressed_services"] == ["svc-a"]
    svc = multi.get_service("svc-a")
    old_id = agent.task_id_of("app-0-server")
    svc.restart_pod("app", 0)  # kills + nudges, as the HTTP route does
    deploy_all(multi, agent)
    assert not svc.work_pending()
    new_id = agent.task_id_of("app-0-server")
    assert new_id is not None and new_id != old_id, \
        "suppressed service missed its own restart work"
    multi.run_cycle()
    assert multi.suppress_state()["suppressed_services"] == ["svc-a"]


def test_failed_cycle_leaves_revived_service_runnable():
    """A revived service whose cycle raises must not stay suppressed:
    its nudge was already consumed, so staying in the suppress set
    would skip it forever — the operator verb silently dropped and the
    consecutive-failure wedge detection unreachable."""
    multi, agent = build_multi()
    multi.add_service(from_yaml(MULTI_SVC_YAML.format(name="svc-a")))
    deploy_all(multi, agent)
    multi.run_cycle()
    assert multi.suppress_state()["suppressed_services"] == ["svc-a"]
    svc = multi.get_service("svc-a")
    real_cycle = svc.run_cycle

    def exploding_cycle(*a, **kw):
        raise RuntimeError("transient store blip")

    svc.run_cycle = exploding_cycle
    svc.nudge()  # operator verb revives it...
    multi.run_cycle()  # ...and the revived cycle fails
    assert "svc-a" not in multi.suppress_state()["suppressed_services"], \
        "failed cycle left the service suppressed with its nudge consumed"
    # next cycle retries without any new trigger, and recovery resumes
    svc.run_cycle = real_cycle
    multi.run_cycle()
    assert multi._cycle_failures["svc-a"] == 0


# -- admission control ------------------------------------------------

VALID_ADD_YAML = """
name: added
pods:
  app:
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: sleep 1000
        cpus: 0.5
        memory: 256
"""

# plan names a pod that does not exist + a fixed-port conflict
INVALID_ADD_YAML = """
name: added
pods:
  app:
    count: 2
    tasks:
      server:
        goal: RUNNING
        cmd: sleep 1000
        cpus: 0.5
        memory: 256
        ports:
          web: {port: 8080}
plans:
  deploy:
    strategy: serial
    phases:
      main:
        pod: nosuchpod
"""


def test_admission_rejects_invalid_spec_with_422_and_findings():
    import json
    import urllib.request

    from dcos_commons_tpu.http.server import ApiServer

    multi, agent = build_multi()
    server = ApiServer(multi=multi, port=0).start()
    try:
        def put(body):
            req = urllib.request.Request(
                f"{server.url}/v1/multi/added", data=body.encode(),
                method="PUT",
            )
            try:
                with urllib.request.urlopen(req) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, body = put(INVALID_ADD_YAML)
        assert code == 422, body
        rules = {f["rule"] for f in body["findings"]}
        assert "spec-plan" in rules
        assert "spec-ports" in rules
        # line-anchored: findings point into the submitted YAML
        assert all(f["line"] >= 1 for f in body["findings"])
        assert all(f["file"] == "added.yml" for f in body["findings"])
        # nothing persisted
        assert "added" not in multi.service_names()

        code, body = put(VALID_ADD_YAML)
        assert code == 200, body
        assert "added" in multi.service_names()
        # accepted unchanged: the stored spec round-trips the YAML
        entry = multi.service_store.fetch("added")
        assert entry["spec"]["name"] == "added"
    finally:
        server.stop()


def test_multi_events_route_and_reserved_name():
    """GET /v1/multi/events serves the fleet journal (admission
    rejections land there), and the 'events' service name is reserved
    at the PUT boundary — a service deployed under it would have its
    bare-name GET shadowed by the journal route."""
    import json
    import urllib.error
    import urllib.request

    from dcos_commons_tpu.http.server import ApiServer

    multi, agent = build_multi()
    server = ApiServer(multi=multi, port=0).start()
    try:
        def request(path, body=None, method="GET"):
            req = urllib.request.Request(
                f"{server.url}{path}",
                data=body.encode() if body else None,
                method=method,
            )
            try:
                with urllib.request.urlopen(req) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, body = request(
            "/v1/multi/events", INVALID_ADD_YAML, method="PUT"
        )
        assert code == 400 and "reserved" in body["message"]
        # a rejected spec journals an admission event at fleet level
        code, _ = request("/v1/multi/added", INVALID_ADD_YAML,
                          method="PUT")
        assert code == 422
        code, body = request("/v1/multi/events")
        assert code == 200
        kinds = {e["kind"] for e in body["events"]}
        assert "admission" in kinds, body
        # cursor drains
        code, tail = request(f"/v1/multi/events?since={body['seq']}")
        assert code == 200 and tail["events"] == []
    finally:
        server.stop()


def test_admission_ignores_suppression_comments_in_payload():
    """Suppression comments are a CI affordance; in the admission
    path they live in the operator-submitted body, so honoring them
    would let any payload waive its own rejection."""
    from dcos_commons_tpu.multi.admission import validate_service_yaml

    suppressed_invalid = "# sdklint: disable-file=all\n" + INVALID_ADD_YAML
    spec, findings = validate_service_yaml(suppressed_invalid, "added")
    assert {f.rule for f in findings} >= {"spec-plan", "spec-ports"}, findings

    # an unparseable body whose render finding is "suppressed" must
    # still reject (spec=None can never be admitted)
    spec, findings = validate_service_yaml(
        "# sdklint: disable-file=all\n:not yaml: [", "added"
    )
    assert spec is None
    assert findings, "render failure admitted with zero findings"


def test_admission_mesh_derivation_for_jax_workloads():
    """A jax-targeting spec whose topology cannot lay a host-aligned
    mesh is rejected with the shard-mesh rule, line-anchored at the
    pod; a derivable one is admitted."""
    from dcos_commons_tpu.multi.admission import validate_service_yaml

    bad = """
name: jaxsvc
pods:
  trainer:
    count: 2
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 3
      topology: 4x4
    tasks:
      worker:
        goal: FINISH
        cmd: "python train_worker.py"
        cpus: 1.0
        memory: 1024
"""
    spec, findings = validate_service_yaml(bad, "jaxsvc")
    assert any(f.rule == "shard-mesh" for f in findings), findings
    anchored = [f for f in findings if f.rule == "shard-mesh"]
    assert all(f.line > 1 for f in anchored)  # at the pod line, not 1

    good = bad.replace("chips-per-host: 3", "chips-per-host: 4")
    spec, findings = validate_service_yaml(good, "jaxsvc")
    assert spec is not None
    assert not [f for f in findings if f.rule == "shard-mesh"], findings


def test_admission_mesh_uses_profile_mesh_not_bare_derive():
    """Admission must reach the same verdict CI shardcheck does: the
    serve profiles pin their own meshes (serve_worker = single chip),
    so a 4-chip reservation for serve_worker.py is 'reserved chips
    sit idle' even though derive(env) would happily lay dp=4."""
    from dcos_commons_tpu.multi.admission import validate_service_yaml

    idle_chips = """
name: servesvc
pods:
  server:
    count: 1
    tpu:
      generation: v5e
      chips-per-host: 4
    tasks:
      serve:
        goal: RUNNING
        cmd: "python serve_worker.py"
        cpus: 1.0
        memory: 1024
"""
    spec, findings = validate_service_yaml(idle_chips, "servesvc")
    mesh = [f for f in findings if f.rule == "shard-mesh"]
    assert mesh and "sit idle" in mesh[0].message, findings


# -- static candidate memo (ISSUE 15 satellite: the PR 9 remainder) --


def test_static_candidate_memo_equivalence_and_invalidation():
    """Static rules (field matches and their and/or algebra) memoize
    their candidate sets per topology generation through the
    inventory: repeat queries are one dict hit, membership is
    IDENTICAL to a fresh computation, and any topology mutation
    (host down/up/add) invalidates by stamping."""
    from dcos_commons_tpu.offer.placement import (
        AndRule,
        FieldMatchRule,
        MaxPerRule,
        OrRule,
    )

    hosts = [
        TpuHost(host_id=f"h{i}", zone=("z1" if i % 2 else "z2"))
        for i in range(8)
    ]
    inv = SliceInventory(hosts)
    ledger = ReservationLedger(MemPersister())
    index = inv.offer_view(ledger)

    rule = FieldMatchRule("zone", ["z1"], invert=True)
    fresh = rule.candidate_host_ids(None, index)
    first = index.rule_candidates(rule, None)
    assert set(first) == set(fresh) == {f"h{i}" for i in range(0, 8, 2)}
    hits0 = inv.static_cand_hits
    again = index.rule_candidates(rule, None)
    assert inv.static_cand_hits == hits0 + 1
    assert again == first
    # an EQUIVALENT rule object shares the entry (key is structural)
    clone = FieldMatchRule("zone", ["z1"], invert=True)
    assert index.rule_candidates(clone, None) == first
    assert inv.static_cand_hits == hits0 + 2

    # topology mutation: the memo must see the new world
    inv.mark_down("h0")
    index2 = inv.offer_view(ledger)
    assert "h0" not in index2.rule_candidates(rule, None)
    inv.mark_up("h0")
    index3 = inv.offer_view(ledger)
    assert "h0" in index3.rule_candidates(rule, None)

    # composition: and/or of static rules is static; anything with a
    # count-dependent child is dynamic (no key, no memo entry)
    z1 = FieldMatchRule("zone", ["z1"])
    z2 = FieldMatchRule("zone", ["z2"])
    assert AndRule([z1, z2]).candidate_key() is not None
    assert OrRule([z1, z2]).candidate_key() is not None
    assert MaxPerRule("hostname", 1).candidate_key() is None
    assert AndRule([z1, MaxPerRule("hostname", 1)]).candidate_key() \
        is None
    assert OrRule([z1, MaxPerRule("hostname", 1)]).candidate_key() \
        is None
    misses0 = inv.static_cand_misses
    assert set(index3.rule_candidates(OrRule([z1, z2]), None)) == {
        h.host_id for h in hosts
    }
    assert inv.static_cand_misses == misses0 + 1


def test_deploy_reuses_candidates_across_instances():
    """A multi-instance deploy with a static placement rule pays the
    candidate-set algebra once, not once per instance — and places
    exactly as before (the existing randomized equivalence sweeps
    pin the winners; this pins the cost shape)."""
    yaml_text = """
name: fleet
pods:
  app:
    count: 6
    placement: 'zone:exact:good'
    tasks:
      server:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.5
        memory: 64
"""
    from dcos_commons_tpu.testing import ServiceTestRunner

    hosts = [
        TpuHost(host_id=f"g{i}", zone="good", cpus=8.0)
        for i in range(4)
    ] + [
        TpuHost(host_id=f"b{i}", zone="bad", cpus=8.0)
        for i in range(4)
    ]
    runner = ServiceTestRunner(yaml_text, hosts=hosts)
    world = runner.build()
    acked = set()
    for _ in range(10):
        world.scheduler.run_cycle()
        for info in list(world.agent.launched):
            if info.task_id not in acked:
                acked.add(info.task_id)
                world.agent.send(TaskStatus(
                    task_id=info.task_id, state=TaskState.RUNNING,
                    ready=True, agent_id=info.agent_id,
                ))
    assert world.scheduler.deploy_manager.get_plan().is_complete
    inv = world.inventory
    placed = {i.agent_id for i in world.agent.launched}
    assert placed and placed <= {f"g{i}" for i in range(4)}
    # the zone rule's set was computed once and then served from the
    # memo for every further instance/cycle
    assert inv.static_cand_misses >= 1
    assert inv.static_cand_hits >= inv.static_cand_misses
