"""Failure diagnostics bundles: capture everything an operator needs.

Reference: testing/sdk_diag.py (568 LoC) — on integration-test failure
the harness harvests plans, pod statuses, task logs and scheduler
state into a per-test bundle directory.  Same shape here: one call
pulls every observable surface of a served scheduler over HTTP plus
process/sandbox logs into a directory of JSON + text files.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, Optional

# every GET surface worth capturing, bundled file name -> path
_SURFACES = {
    "health.json": "/v1/health",
    "plans.json": "/v1/plans",
    "pod_status.json": "/v1/pod/status",
    "debug_offers.json": "/v1/debug/offers",
    "debug_reservations.json": "/v1/debug/reservations",
    "debug_plans.json": "/v1/debug/plans",
    "metrics.json": "/v1/metrics",
    "configs.json": "/v1/configs",
    "endpoints.json": "/v1/endpoints",
}


def dump_bundle(
    url: str,
    out_dir: str,
    scheduler_log: str = "",
    agent_logs: Optional[Dict[str, str]] = None,
    sandbox_roots: Optional[Iterable[str]] = None,
    log_tail_lines: int = 200,
) -> Dict[str, str]:
    """Harvest a served scheduler into ``out_dir``.

    Every surface is captured independently — one broken endpoint (or
    a dead scheduler) never voids the rest of the bundle; failures are
    recorded in the bundle itself.  Returns {bundle file: status}.
    """
    import urllib.request

    os.makedirs(out_dir, exist_ok=True)
    results: Dict[str, str] = {}

    def write(name: str, content: str) -> None:
        with open(os.path.join(out_dir, name), "w") as f:
            f.write(content)

    for name, path in _SURFACES.items():
        try:
            with urllib.request.urlopen(
                url.rstrip("/") + path, timeout=5
            ) as resp:
                body = json.loads(resp.read().decode("utf-8"))
            write(name, json.dumps(body, indent=2, default=str))
            results[name] = "ok"
        except Exception as e:  # capture-everything tool: record + move on
            write(name, json.dumps({"bundle_error": repr(e)}))
            results[name] = f"error: {e}"

    # per-plan detail, reusing the plan list already captured above;
    # each plan fetch fails independently so one wedged plan endpoint
    # never voids the others
    detail = {}
    try:
        with open(os.path.join(out_dir, "plans.json")) as f:
            plan_names = json.load(f)
        assert isinstance(plan_names, list)
    except Exception as e:
        plan_names = []
        detail["_list_error"] = repr(e)
    for plan in plan_names:
        try:
            with urllib.request.urlopen(
                url.rstrip("/") + f"/v1/plans/{plan}", timeout=5
            ) as resp:
                detail[plan] = json.loads(resp.read().decode("utf-8"))
        except Exception as e:
            detail[plan] = {"bundle_error": repr(e)}
    write("plan_trees.json", json.dumps(detail, indent=2, default=str))
    if "_list_error" in detail:
        results["plan_trees.json"] = f"error: {detail['_list_error']}"
    elif any("bundle_error" in str(v) for v in detail.values()):
        results["plan_trees.json"] = f"error: partial {sorted(detail)}"
    else:
        results["plan_trees.json"] = "ok"

    def capture_log(name: str, path: str) -> None:
        try:
            with open(path, errors="replace") as f:
                write(
                    name,
                    "\n".join(f.read().splitlines()[-log_tail_lines:]),
                )
            results[name] = "ok"
        except OSError as e:
            write(name, f"<unreadable: {e}>")
            results[name] = f"error: {e}"

    if scheduler_log:
        capture_log("scheduler.log", scheduler_log)
    for host_id, path in (agent_logs or {}).items():
        capture_log(f"agent-{host_id}.log", path)

    # task sandbox stdout/stderr tails
    for root in sandbox_roots or ():
        try:
            names = os.listdir(root)
        except OSError:
            continue
        for task_name in names:
            for stream in ("stdout", "stderr"):
                path = os.path.join(root, task_name, stream)
                if os.path.isfile(path):
                    capture_log(f"task-{task_name}.{stream}", path)
    write("MANIFEST.json", json.dumps(results, indent=2))
    return results
