"""Simulation test harness: full scheduler stack, no processes, no fleet.

Rebuild of the reference's `sdk/testing/` simulation harness
(reference: sdk/testing/.../ServiceTestRunner.java:38,
SimulationTick.java:6, Expect.java:47-631): boot the *entire* scheduler
(builder -> config update -> plans -> offer evaluation -> launch WAL)
against a MemPersister and a scripted FakeAgent, then drive it with
tick sequences -- `Send*` mutations followed by one scheduler cycle,
`Expect*` assertions over the observable state.  Scheduler restarts
are simulated by rebuilding the runner over the same persister, just
as the reference rebuilds ServiceTestRunner over one MemPersister
(ServiceTest.java:57-77).
"""

from dcos_commons_tpu.testing.fake_agent import FakeAgent


def drive_until(scheduler, predicate, timeout_s: float = 30.0,
                interval_s: float = 0.05) -> bool:
    """Run real scheduler cycles until ``predicate()`` is truthy.

    The shared poll loop for tests that drive a scheduler against a
    REAL agent (process launches) rather than scripted ticks."""
    import time as _time

    deadline = _time.monotonic() + timeout_s
    while _time.monotonic() < deadline:
        scheduler.run_cycle()
        if predicate():
            return True
        _time.sleep(interval_s)
    return False
from dcos_commons_tpu.testing.runner import (
    ServiceTestRunner,
    SimulationWorld,
    cosmos_render,
)
from dcos_commons_tpu.testing.ticks import (
    AddHost,
    AdvanceCycles,
    Expect,
    ExpectAllPlansComplete,
    ExpectDeclined,
    ExpectDeploymentComplete,
    ExpectDistinctHosts,
    ExpectLaunchedTasks,
    ExpectNoLaunches,
    ExpectPlanStatus,
    ExpectRecoveryStep,
    ExpectReservationCount,
    ExpectSameHost,
    ExpectStepStatus,
    ExpectTaskEnv,
    ExpectTaskKilled,
    ExpectTaskNotKilled,
    ExpectTaskStateStored,
    DrainHost,
    HostUp,
    MarkHostDown,
    MarkHostUp,
    PreemptHost,
    PlanContinue,
    PlanForceComplete,
    PlanInterrupt,
    PlanRestart,
    PlanStart,
    RemoveHost,
    Send,
    SendStatus,
    SendTaskFailed,
    SendTaskFinished,
    SendTaskRunning,
    SimulationTick,
)

__all__ = [
    "FakeAgent",
    "drive_until",
    "ServiceTestRunner",
    "cosmos_render",
    "SimulationWorld",
    "SimulationTick",
    "Send",
    "Expect",
    "SendStatus",
    "SendTaskRunning",
    "SendTaskFinished",
    "SendTaskFailed",
    "AddHost",
    "RemoveHost",
    "DrainHost",
    "HostUp",
    "MarkHostDown",
    "MarkHostUp",
    "PreemptHost",
    "AdvanceCycles",
    "PlanInterrupt",
    "PlanContinue",
    "PlanRestart",
    "PlanStart",
    "PlanForceComplete",
    "ExpectLaunchedTasks",
    "ExpectNoLaunches",
    "ExpectTaskKilled",
    "ExpectTaskNotKilled",
    "ExpectPlanStatus",
    "ExpectStepStatus",
    "ExpectDeploymentComplete",
    "ExpectAllPlansComplete",
    "ExpectRecoveryStep",
    "ExpectTaskEnv",
    "ExpectTaskStateStored",
    "ExpectReservationCount",
    "ExpectDistinctHosts",
    "ExpectSameHost",
    "ExpectDeclined",
]
