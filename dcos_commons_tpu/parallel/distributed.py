"""Worker-side distributed bootstrap: the rendezvous shim.

The moral equivalent of the reference's sdk/bootstrap DNS-wait
(sdk/bootstrap/main.go:218-289): instead of each task resolving its
own DNS record, workers read the scheduler-issued env contract
(offer/evaluate.py) and call jax.distributed.initialize against the
coordinator address the scheduler allocated on worker 0's host.  The
scheduler persisted that address in the FrameworkStore, so restarts
rendezvous at the same point.
"""

from __future__ import annotations

import logging
import os
from typing import Mapping, Optional

LOG = logging.getLogger(__name__)


def initialize_from_env(
    env: Optional[Mapping[str, str]] = None, timeout_s: int = 300
) -> dict:
    """Initialize jax.distributed from the scheduler env contract.

    Returns the parsed contract.  Single-worker pods (no
    COORDINATOR_ADDRESS) skip initialization — jax runs locally.
    """
    env = env if env is not None else os.environ
    # per-slice coordinator addressing (multi-slice gangs, ISSUE 20):
    # TPU_SLICE_COORDS lists each slice's rendezvous anchor
    # slice-major; a worker's own slice anchor is slice_coords[
    # slice_index].  The GLOBAL jax.distributed rendezvous stays the
    # single COORDINATOR_ADDRESS — one process group spanning every
    # slice, dcn collectives riding DCN — while the slice anchors give
    # slice-local tooling (per-slice barriers, dcn ring debugging) a
    # stable address without re-deriving placement.
    slice_coords = [
        a for a in env.get("TPU_SLICE_COORDS", "").split(",") if a
    ]
    num_slices = int(env.get("TPU_NUM_SLICES", "1") or 1)
    slice_index = int(env.get("TPU_SLICE_INDEX", "0") or 0)
    contract = {
        "coordinator": env.get("COORDINATOR_ADDRESS", ""),
        "worker_id": int(env.get("TPU_WORKER_ID", "0") or 0),
        "worker_count": int(env.get("TPU_WORKER_COUNT", "1") or 1),
        # 0 is the "probe the local runtime" sentinel, not a chip
        # count; options.json's 4 only applies to rendered deploys
        # sdklint: disable=config-default-drift — autodetect sentinel
        "chips_per_host": int(env.get("TPU_CHIPS_PER_HOST", "0") or 0),
        "topology": env.get("TPU_TOPOLOGY", ""),
        "generation": env.get("TPU_GENERATION", ""),
        "num_slices": num_slices,
        "slice_index": slice_index,
        "hosts_per_slice": int(env.get("TPU_HOSTS_PER_SLICE", "0") or 0),
        "slice_coords": slice_coords,
        "slice_coordinator": (
            slice_coords[slice_index]
            if 0 <= slice_index < len(slice_coords) else ""
        ),
    }
    if contract["worker_count"] > 1 and contract["coordinator"]:
        import jax

        if num_slices > 1:
            LOG.info(
                "multi-slice gang: slice %d/%d, slice coords %s",
                slice_index, num_slices, ",".join(slice_coords) or "n/a",
            )
        LOG.info(
            "jax.distributed.initialize(%s, %d/%d)",
            contract["coordinator"],
            contract["worker_id"],
            contract["worker_count"],
        )
        jax.distributed.initialize(
            coordinator_address=contract["coordinator"],
            num_processes=contract["worker_count"],
            process_id=contract["worker_id"],
            initialization_timeout=timeout_s,
        )
    return contract
