"""Decommission (scale-down) and uninstall (full teardown) plans.

Reference: scheduler/decommission/DecommissionPlanFactory.java (kill ->
unreserve -> erase per surplus instance), scheduler/uninstall/
UninstallScheduler.java (kill -> unreserve -> deregister, state wipe,
skeleton on restart).
"""

from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.scheduler import SchedulerConfig
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    SendTaskRunning,
    ServiceTestRunner,
)

THREE_POD_YAML = """
name: shrink-svc
pods:
  web:
    count: 3
    allow-decommission: true
    tasks:
      srv:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
"""


def deploy_three():
    runner = ServiceTestRunner(THREE_POD_YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("web-0-srv"),
        AdvanceCycles(1),
        SendTaskRunning("web-1-srv"),
        AdvanceCycles(1),
        SendTaskRunning("web-2-srv"),
        ExpectDeploymentComplete(),
    ])
    return runner


def test_scale_down_builds_decommission_plan():
    runner = deploy_three()
    assert len(runner.world.scheduler.ledger.all()) == 3

    shrunk = ServiceTestRunner(
        THREE_POD_YAML.replace("count: 3", "count: 2"),
        persister=runner.persister,
        hosts=runner.hosts,
    )
    shrunk.agent = runner.agent
    shrunk.inventory = runner.inventory
    world = shrunk.build()
    plan = world.scheduler.plan("decommission")
    assert plan is not None
    assert [p.name for p in plan.phases] == ["decommission-web-2"]

    # the count change is a config update: the surviving pods roll
    # to the new target config while web-2 decommissions
    shrunk.run([
        AdvanceCycles(2),
        SendTaskRunning("web-0-srv"),
        AdvanceCycles(2),
        SendTaskRunning("web-1-srv"),
        AdvanceCycles(4),
    ])
    # task killed (FakeAgent auto-acks), state erased, footprint freed
    assert plan.is_complete, [
        (s.name, s.get_status().value) for p in plan.phases for s in p.steps
    ]
    assert "web-2-srv" in shrunk.agent.killed_names()
    assert world.state_store.fetch_task("web-2-srv") is None
    assert len(world.scheduler.ledger.all()) == 2
    # surviving pods untouched, deploy (update plan) stays complete
    assert world.state_store.fetch_task("web-0-srv") is not None
    assert world.scheduler.deploy_manager.get_plan().is_complete


def test_removed_pod_type_decommissions_all_instances():
    runner = deploy_three()
    no_web = """
name: shrink-svc
pods:
  other:
    count: 1
    tasks:
      one:
        goal: RUNNING
        cmd: "run"
        cpus: 0.1
        memory: 32
"""
    replaced = ServiceTestRunner(
        no_web, persister=runner.persister, hosts=runner.hosts
    )
    replaced.agent = runner.agent
    replaced.inventory = runner.inventory
    world = replaced.build()
    plan = world.scheduler.plan("decommission")
    assert [p.name for p in plan.phases] == [
        "decommission-web-2", "decommission-web-1", "decommission-web-0",
    ]
    replaced.run([
        AdvanceCycles(14),
        SendTaskRunning("other-0-one"),
    ])
    assert plan.is_complete
    assert world.scheduler.ledger.for_task("web-0-srv") == []
    assert world.state_store.fetch_task("other-0-one") is not None


def test_uninstall_tears_everything_down():
    runner = deploy_three()
    config = SchedulerConfig(backoff_enabled=False, uninstall=True)
    uninstaller = ServiceTestRunner(
        THREE_POD_YAML,
        persister=runner.persister,
        hosts=runner.hosts,
        scheduler_config=config,
    )
    uninstaller.agent = runner.agent
    uninstaller.inventory = runner.inventory
    world = uninstaller.build()
    plan = world.scheduler.plan("uninstall")
    assert plan is not None and not plan.is_complete

    uninstaller.run([AdvanceCycles(4)])
    assert world.scheduler.is_complete, [
        (s.name, s.get_status().value) for p in plan.phases for s in p.steps
    ]
    # tasks killed, reservations gone, framework id cleared, state wiped
    assert set(runner.agent.killed_names()) == {
        "web-0-srv", "web-1-srv", "web-2-srv"
    }
    assert world.scheduler.ledger.all() == []
    assert world.scheduler.framework_store.fetch_framework_id() is None
    assert runner.persister.get_children_or_empty("/") == []
    # the uninstall plan serves as "deploy" for package-manager polling
    assert world.scheduler.plan("deploy").is_complete


def test_skeleton_scheduler_after_wipe():
    """Restarting an uninstalled service yields an immediately-complete
    uninstall plan (reference: skeleton scheduler)."""
    runner = deploy_three()
    config = SchedulerConfig(backoff_enabled=False, uninstall=True)
    first = ServiceTestRunner(
        THREE_POD_YAML, persister=runner.persister, hosts=runner.hosts,
        scheduler_config=config,
    )
    first.agent = runner.agent
    first.inventory = runner.inventory
    first.build()
    first.run([AdvanceCycles(4)])
    assert first.world.scheduler.is_complete

    second = first.restart()
    world = second.build()
    second.run([AdvanceCycles(3)])
    assert world.scheduler.is_complete
    assert world.scheduler.plan("deploy").get_status() is Status.COMPLETE


DASHED_TASK_YAML = """
name: dash-svc
pods:
  web:
    count: 2
    allow-decommission: true
    tasks:
      main-server:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
        kill-grace-period: 17
"""


def test_decommission_grace_honored_for_dashed_task_names():
    """Regression: grace lookup must key by FULL task name — suffix
    parsing of 'web-1-main-server' would yield 'server' and silently
    fall back to an immediate kill."""
    runner = ServiceTestRunner(DASHED_TASK_YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("web-0-main-server"),
        AdvanceCycles(1),
        SendTaskRunning("web-1-main-server"),
        ExpectDeploymentComplete(),
    ])
    shrunk = ServiceTestRunner(
        DASHED_TASK_YAML.replace("count: 2", "count: 1"),
        persister=runner.persister,
        hosts=runner.hosts,
    )
    shrunk.agent = runner.agent
    shrunk.inventory = runner.inventory
    world = shrunk.build()
    doomed_id = runner.agent.task_id_of("web-1-main-server")
    for _ in range(4):
        world.scheduler.run_cycle()
    assert doomed_id in shrunk.agent.kills
    assert shrunk.agent.kill_graces[doomed_id] == 17.0
