"""X3: debug introspection — the "explain why" trackers.

Reference: sdk/scheduler/.../debug/ — OfferOutcomeTrackerV2 (ring
buffer of per-offer per-stage pass/fail reasons, fed from
OfferEvaluator.java:193-241, served at /v1/debug/offers),
PlansTracker, TaskStatusesTracker, TaskReservationsTracker.
SURVEY.md section 5.1 calls this the single most operator-loved
feature; it is first-class here.
"""

from dcos_commons_tpu.debug.trackers import (
    OfferOutcomeTracker,
    PlansTracker,
    TaskReservationsTracker,
    TaskStatusesTracker,
)

__all__ = [
    "OfferOutcomeTracker",
    "PlansTracker",
    "TaskReservationsTracker",
    "TaskStatusesTracker",
]
