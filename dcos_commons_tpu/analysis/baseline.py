"""Baseline file: tracked pre-existing debt, not hidden debt.

Reference: the checkstyle/findbugs suppression-file idiom — a gate
adopted by an existing codebase records current violations in a
reviewed file so (a) the gate can land green immediately, (b) NEW
violations still fail, and (c) the debt is visible and burned down
deliberately.  Entries key on ``file::rule`` with a count, so line
drift from unrelated edits never resurfaces a baselined finding,
while adding one MORE violation of the same rule in the same file
does fail the gate.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Tuple

from dcos_commons_tpu.analysis.linter import Finding

BASELINE_NAME = ".sdklint-baseline.json"


def baseline_path(root: str) -> str:
    return os.path.join(root, BASELINE_NAME)


def load_baseline(path: str) -> Dict[str, int]:
    """{fingerprint: allowed count}; a missing file is an empty one."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        raw = json.load(f)
    entries = raw.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}


def save_baseline(
    path: str,
    findings: Iterable[Finding],
    retain: Dict[str, int] = None,
) -> Dict[str, int]:
    """Write the baseline from ``findings``; ``retain`` carries
    fingerprint counts that must survive the rewrite verbatim — the
    entries of an analyzer that did NOT run this invocation (lint and
    spmd share this file, and `--lint --update-baseline` must not
    erase the spmd debt it never recomputed)."""
    counts = Counter(f.fingerprint for f in findings)
    for fingerprint, count in (retain or {}).items():
        counts[fingerprint] = max(counts[fingerprint], count)
    doc = {
        "comment": (
            "sdklint baseline: pre-existing violations tracked, not "
            "hidden.  Regenerate with `python -m dcos_commons_tpu."
            "analysis --lint --update-baseline` after deliberate "
            "triage; shrink it, don't grow it."
        ),
        "entries": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return dict(counts)


def apply_baseline(
    findings: List[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], List[Finding]]:
    """-> (new findings that fail the gate, baselined findings).

    Per fingerprint, up to the baselined count is absorbed; anything
    beyond it is new debt and fails.
    """
    budget = dict(baseline)
    fresh: List[Finding] = []
    absorbed: List[Finding] = []
    for finding in findings:
        if budget.get(finding.fingerprint, 0) > 0:
            budget[finding.fingerprint] -= 1
            absorbed.append(finding)
        else:
            fresh.append(finding)
    return fresh, absorbed
