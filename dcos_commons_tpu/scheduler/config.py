"""SchedulerConfig: process-level configuration from env.

Reference: scheduler/SchedulerConfig.java (666 LoC, ~45 env vars) +
framework/EnvStore.java.  The same plane-(a) config surface
(SURVEY.md section 5.6): process env -> typed config; service YAML and
per-task env are the other two planes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Mapping, Optional


@dataclass
class SchedulerConfig:
    api_port: int = 8080
    state_dir: str = "./state"
    # networked persistence: when set, scheduler state lives on the
    # cluster state server (reference: ZK via CuratorPersister) and the
    # instance lock is a TTL lease there (CuratorLocker) — the
    # scheduler process becomes host-agnostic and failover is real
    state_url: str = ""
    state_lease_ttl_s: float = 15.0
    # HA leader election (dcos_commons_tpu/ha/): with remote state,
    # `serve --ha` (or SDK_HA=1) makes extra scheduler processes hot
    # STANDBYS — they candidate for the leader lease instead of
    # exiting, and every store mutation is fenced by the lease epoch
    ha_enabled: bool = False
    # secrets provider root (reference: DC/OS secrets service; here an
    # operator-managed directory tree read by FileSecretsProvider)
    secrets_dir: str = ""
    service_namespace: str = ""
    uninstall: bool = False              # reference: SDK_UNINSTALL
    state_cache_enabled: bool = True     # reference: DISABLE_STATE_CACHE
    # launch backoff (reference: ExponentialBackoff env knobs)
    backoff_enabled: bool = True
    backoff_initial_s: float = 1.0
    backoff_factor: float = 1.15
    backoff_max_s: float = 300.0
    # recovery escalation (overridden by ServiceSpec's policy)
    permanent_failure_timeout_s: float = 1200.0
    # revive throttling (reference: ReviveManager token bucket)
    revive_capacity: int = 4
    revive_refill_s: float = 5.0
    # agent sandbox root
    sandbox_root: str = "./sandboxes"
    # traceview flight recorder: span ring-buffer capacity per
    # scheduler (0 disables tracing; drop-oldest above the cap, with
    # evictions counted in the `trace.dropped` metric)
    trace_capacity: int = 2048
    # coordinator port range for pjit rendezvous
    coordinator_port_base: int = 8476
    # fleet health plane (dcos_commons_tpu/health/): durable event
    # journal capacity (0 disables the whole plane — detectors AND
    # journal, the bench_health_overhead disabled arm), sandbox/wire
    # telemetry fan-in cadence, metric-history sampling cadence, and
    # the straggler detector's median-ratio threshold/window.  Serving
    # SLO thresholds default off here; per-task env (the options.json
    # serving.*_slo knobs ride the task env contract) overrides.
    health_enabled: bool = True
    health_journal_capacity: int = 512
    health_telemetry_interval_s: float = 5.0
    health_history_interval_s: float = 1.0
    health_straggler_ratio: float = 2.0
    health_straggler_window: int = 32
    # health -> action seam (ISSUE 13 satellite), DEFAULT OFF: a
    # confirmed straggler episode on a host carrying a gang member
    # triggers at most one automated pod replace (riding the gang
    # recovery plan) per episode.  Opt-in — automated eviction must
    # be an operator decision.
    health_auto_replace: bool = False
    # the CLOSED health->action loop (health/actions.py, ISSUE 15):
    # SLO-breach scale-out + quiet-pod scale-in for non-gang serve
    # pods, and general straggler remediation.  Both families default
    # OFF — automated resizing/eviction is an operator decision.  The
    # hysteresis/cooldown/drain knobs feed the ActionPolicy verbatim.
    health_autoscale: bool = False
    health_remediation: bool = False
    autoscale_max_instances: int = 4
    autoscale_breach_hold_s: float = 10.0
    autoscale_quiet_hold_s: float = 60.0
    autoscale_quiet_factor: float = 0.25
    autoscale_cooldown_out_s: float = 60.0
    autoscale_cooldown_in_s: float = 300.0
    autoscale_drain_grace_s: float = 5.0
    health_ttft_p95_slo_s: float = 0.0
    health_queue_depth_slo: float = 0.0
    health_kv_occupancy_slo: float = 0.0
    health_kv_pages_free_slo: float = 0.0
    # control-plane credentials (security/auth.py): one cluster bearer
    # token shared by scheduler API, agent daemons, and state server;
    # TLS material for serving HTTPS / verifying peers
    auth_token: str = ""
    tls_ca_file: str = ""
    tls_cert_file: str = ""
    tls_key_file: str = ""

    @property
    def api_tls(self):
        """(cert, key) for the scheduler's own HTTPS, or None.
        Raises ValueError on half a pair (no silent plaintext)."""
        from dcos_commons_tpu.security.auth import tls_pair

        return tls_pair(self.tls_cert_file, self.tls_key_file)

    @staticmethod
    def from_env(env: Optional[Mapping[str, str]] = None) -> "SchedulerConfig":
        env = env if env is not None else os.environ
        return SchedulerConfig(
            api_port=int(env.get("PORT_API", "8080")),
            state_dir=env.get("STATE_DIR", "./state"),
            state_url=env.get("STATE_URL", ""),
            state_lease_ttl_s=float(env.get("STATE_LEASE_TTL_S", "15")),
            ha_enabled=env.get("SDK_HA", "") not in ("", "0", "false"),
            secrets_dir=env.get("SECRETS_DIR", ""),
            service_namespace=env.get("SERVICE_NAMESPACE", ""),
            uninstall=env.get("SDK_UNINSTALL", "") not in ("", "0", "false"),
            state_cache_enabled=env.get("DISABLE_STATE_CACHE", "")
            in ("", "0", "false"),
            backoff_enabled=env.get("ENABLE_BACKOFF", "true")
            not in ("0", "false"),
            backoff_initial_s=float(env.get("BACKOFF_INITIAL_S", "1.0")),
            backoff_factor=float(env.get("BACKOFF_FACTOR", "1.15")),
            backoff_max_s=float(env.get("BACKOFF_MAX_S", "300")),
            permanent_failure_timeout_s=float(
                env.get("PERMANENT_FAILURE_TIMEOUT_S", "1200")
            ),
            revive_capacity=int(env.get("REVIVE_CAPACITY", "4")),
            revive_refill_s=float(env.get("REVIVE_REFILL_S", "5.0")),
            sandbox_root=env.get("SANDBOX_ROOT", "./sandboxes"),
            trace_capacity=int(env.get("TRACE_CAPACITY", "2048")),
            coordinator_port_base=int(env.get("COORDINATOR_PORT_BASE", "8476")),
            health_enabled=env.get("HEALTH_ENABLED", "true")
            not in ("0", "false"),
            health_journal_capacity=int(
                env.get("HEALTH_JOURNAL_CAPACITY", "512")
            ),
            health_telemetry_interval_s=float(
                env.get("HEALTH_TELEMETRY_INTERVAL_S", "5.0")
            ),
            health_history_interval_s=float(
                env.get("HEALTH_HISTORY_INTERVAL_S", "1.0")
            ),
            health_straggler_ratio=float(
                env.get("HEALTH_STRAGGLER_RATIO", "2.0")
            ),
            health_straggler_window=int(
                env.get("HEALTH_STRAGGLER_WINDOW", "32")
            ),
            health_auto_replace=env.get("HEALTH_AUTO_REPLACE", "")
            not in ("", "0", "false"),
            health_autoscale=env.get("HEALTH_AUTOSCALE", "")
            not in ("", "0", "false"),
            health_remediation=env.get("HEALTH_REMEDIATION", "")
            not in ("", "0", "false"),
            autoscale_max_instances=int(
                env.get("AUTOSCALE_MAX_INSTANCES", "4")
            ),
            autoscale_breach_hold_s=float(
                env.get("AUTOSCALE_BREACH_HOLD_S", "10")
            ),
            autoscale_quiet_hold_s=float(
                env.get("AUTOSCALE_QUIET_HOLD_S", "60")
            ),
            autoscale_quiet_factor=float(
                env.get("AUTOSCALE_QUIET_FACTOR", "0.25")
            ),
            autoscale_cooldown_out_s=float(
                env.get("AUTOSCALE_COOLDOWN_OUT_S", "60")
            ),
            autoscale_cooldown_in_s=float(
                env.get("AUTOSCALE_COOLDOWN_IN_S", "300")
            ),
            autoscale_drain_grace_s=float(
                env.get("AUTOSCALE_DRAIN_GRACE_S", "5")
            ),
            health_ttft_p95_slo_s=float(env.get("SERVE_TTFT_SLO_S", "0")),
            health_queue_depth_slo=float(
                env.get("SERVE_QUEUE_DEPTH_SLO", "0")
            ),
            health_kv_occupancy_slo=float(
                env.get("SERVE_KV_OCCUPANCY_SLO", "0")
            ),
            health_kv_pages_free_slo=float(
                env.get("SERVE_KV_PAGES_FREE_SLO", "0")
            ),
            auth_token=_load_token(env),
            tls_ca_file=env.get("TLS_CA_FILE", ""),
            tls_cert_file=env.get("TLS_CERT_FILE", ""),
            tls_key_file=env.get("TLS_KEY_FILE", ""),
        )


def _load_token(env: Mapping[str, str]) -> str:
    from dcos_commons_tpu.security.auth import load_token

    return load_token(env=env)
