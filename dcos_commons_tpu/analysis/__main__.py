"""sdklint CLI: ``python -m dcos_commons_tpu.analysis <command>``.

Commands (also reachable as ``python -m dcos_commons_tpu analyze``):

    lint     framework lint (AST rules + baseline)
    specs    ahead-of-time spec analyzer (frameworks/*)
    spmd     SPMD collective-safety analyzer (cross-host divergence)
    plan     plan state-machine model checker (exhaustive BFS)
    shard    static sharding / HBM-footprint / collective-cost analyzer
    race     thread-ownership / happens-before race analyzer (static
             half; the dynamic half runs under SDKLINT_RACECHECK=1)
    config   env/config contract analyzer (options.json ⇄ YAML
             templates ⇄ task env ⇄ worker/SDK reads)
    dur      crash-consistency / durability-ordering analyzer
             (WAL-before-effect, replay parity, fence coverage,
             atomic pairs, file discipline + the persistence-point
             map the chaos harness auto-derives kill points from)
    all      everything — the CI gate; default when no command given

Flag spelling (``--lint``/.../``--race``/``--all``) is accepted too,
composably: ``--lint --spmd`` runs exactly those two.

Options:
    --json              one machine-readable JSON document on stdout
                        (findings per analyzer, plancheck.states_explored,
                        shard.footprint / shard.cost per analyzed pod,
                        config.env_vars / config.flows / config.per_rule)
    --docs              render the config flow graph to
                        docs/config-reference.md (implies config)
    --points            dump the durcheck persistence-point map as a
                        JSON document and exit (for the chaos harness
                        and /v1/debug/health consumers)
    --update-baseline   rewrite the baseline from current
                        lint+spmd+shard findings
    --catalog           print the rule catalogs and exit
    --root DIR          repo root (default: auto-detect from this file)
    --plan-max-states N cap per plancheck configuration (default 200000)
    --hbm-mb N          per-chip HBM budget override (0 = generation table)
    --giant-mb N        replicated-param finding threshold (default 256)
    --steplog PATH      compare a worker steplog.jsonl against each
                        train workload's shard.cost wire-time model
                        (predicted-vs-measured step time; a regression
                        past --step-slack fails the run)
    --step-floor-us N   calibrated compute floor added to the wire model
    --step-slack F      allowed measured-over-floor headroom (default 0.25)
    --verbose/-v        also list suppressed and baselined findings

Exit code 0 = no non-baselined findings and no plan violations;
1 = findings; 2 = bad usage.  The gate test (tests/test_lint_gate.py)
runs the same entry points in-process.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

_COMMANDS = (
    "lint", "specs", "spmd", "plan", "shard", "race", "config", "dur",
    "all",
)


def _default_root() -> str:
    """The repo root: the directory holding the ``dcos_commons_tpu``
    package this module was imported from."""
    package_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(package_dir)


def main(argv: List[str] = None) -> int:
    from dcos_commons_tpu.analysis import baseline as baseline_mod
    from dcos_commons_tpu.analysis import (
        configcheck,
        durcheck,
        plancheck,
        racecheck,
        shardcheck,
        speccheck,
        spmdcheck,
    )
    from dcos_commons_tpu.analysis.configcheck import config_rule_catalog
    from dcos_commons_tpu.analysis.durcheck import dur_rule_catalog
    from dcos_commons_tpu.analysis.linter import lint_tree
    from dcos_commons_tpu.analysis.racecheck import race_rule_catalog
    from dcos_commons_tpu.analysis.rules import rule_catalog
    from dcos_commons_tpu.analysis.shardcheck import shard_rule_catalog
    from dcos_commons_tpu.analysis.spmdcheck import spmd_rule_catalog

    argv = list(sys.argv[1:] if argv is None else argv)
    # subcommand spelling -> the equivalent mode flag
    if argv and argv[0] in _COMMANDS:
        argv[0] = f"--{argv[0]}"

    parser = argparse.ArgumentParser(
        prog="python -m dcos_commons_tpu.analysis",
        description=__doc__.splitlines()[0],
    )
    parser.add_argument("--lint", action="store_true")
    parser.add_argument("--specs", action="store_true")
    parser.add_argument("--spmd", action="store_true")
    parser.add_argument("--plan", action="store_true")
    parser.add_argument("--shard", action="store_true")
    parser.add_argument("--race", action="store_true")
    parser.add_argument("--config", action="store_true")
    parser.add_argument("--dur", action="store_true")
    parser.add_argument("--all", action="store_true")
    parser.add_argument(
        "--docs", action="store_true",
        help="render the config flow graph to docs/config-reference.md "
             "(implies --config)",
    )
    parser.add_argument(
        "--points", action="store_true",
        help="dump the durcheck persistence-point map as JSON and exit",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument("--update-baseline", action="store_true")
    parser.add_argument("--catalog", action="store_true")
    parser.add_argument("--root", default=_default_root())
    parser.add_argument("--baseline", default="")
    parser.add_argument("--plan-max-states", type=int, default=200_000)
    parser.add_argument("--hbm-mb", type=int, default=0)
    parser.add_argument("--giant-mb", type=float, default=256.0)
    parser.add_argument(
        "--steplog", default="",
        help="worker steplog.jsonl: compare measured step time against "
             "each train workload's shard.cost wire-time model",
    )
    parser.add_argument(
        "--step-floor-us", type=float, default=0.0,
        help="calibrated compute floor (us) added to the wire model; "
             "0 leaves the comparison ungated on collective-free meshes",
    )
    parser.add_argument(
        "--step-slack", type=float, default=0.25,
        help="allowed measured-over-floor headroom before the steplog "
             "comparison counts as a regression (0.25 = +25%%)",
    )
    parser.add_argument("--host-cpus", type=float, default=8.0)
    parser.add_argument("--host-mem", type=int, default=16384)
    parser.add_argument("--host-disk", type=int, default=102400)
    parser.add_argument(
        "--verbose", "-v", action="store_true",
        help="also list suppressed and baselined findings",
    )
    args = parser.parse_args(argv)

    if args.catalog:
        print(rule_catalog())
        print()
        print(spmd_rule_catalog())
        print()
        print(shard_rule_catalog())
        print()
        print(race_rule_catalog())
        print()
        print(config_rule_catalog())
        print()
        print(dur_rule_catalog())
        return 0

    if args.points:
        # the machine contract: testing/chaos.py auto-derives its
        # crash-injection points from exactly this document, and the
        # /v1/debug/health handler links it for operators
        points = durcheck.persistence_point_map(os.path.abspath(args.root))
        per_kind: dict = {}
        for point in points:
            per_kind[point["kind"]] = per_kind.get(point["kind"], 0) + 1
        print(json.dumps(
            {"persistence_points": points, "per_kind": per_kind},
            indent=2, sort_keys=True,
        ))
        return 0

    any_mode = (args.lint or args.specs or args.spmd or args.plan
                or args.shard or args.race or args.config
                or args.dur or args.docs)
    run_lint = args.lint or args.all or not any_mode
    run_specs = args.specs or args.all or not any_mode
    run_spmd = args.spmd or args.all or not any_mode
    run_plan = args.plan or args.all or not any_mode
    run_shard = args.shard or args.all or not any_mode
    run_race = args.race or args.all or not any_mode
    run_config = args.config or args.docs or args.all or not any_mode
    run_dur = args.dur or args.all or not any_mode
    root = os.path.abspath(args.root)
    baseline_path = args.baseline or baseline_mod.baseline_path(root)
    known = baseline_mod.load_baseline(baseline_path)
    doc: dict = {}
    failed = False

    def emit(line: str) -> None:
        if not args.as_json:
            print(line)

    # lint + spmd share the baseline file; --update-baseline rewrites
    # it from BOTH result sets so neither clobbers the other's entries
    baseline_feed = []

    def run_findings_pass(name: str, result) -> None:
        nonlocal failed
        if args.update_baseline:
            baseline_feed.extend(result.findings)
            fresh, absorbed = [], result.findings
        else:
            fresh, absorbed = baseline_mod.apply_baseline(
                result.findings, known
            )
        for finding in fresh:
            emit(finding.render())
        if args.verbose:
            for finding in absorbed:
                emit(f"{finding.render()}  [baselined]")
            for finding in result.suppressed:
                emit(f"{finding.render()}  [suppressed]")
        emit(
            f"{name}: {result.files_checked} files, "
            f"{len(fresh)} new finding(s), {len(absorbed)} baselined, "
            f"{len(result.suppressed)} suppressed"
        )
        doc[name] = {
            "files_checked": result.files_checked,
            "findings": [f.to_dict() for f in fresh],
            "baselined": len(absorbed),
            "suppressed": len(result.suppressed),
        }
        failed |= bool(fresh)

    if run_lint:
        run_findings_pass("lint", lint_tree(root))

    if run_spmd:
        run_findings_pass("spmd", spmdcheck.analyze_tree(root))

    if run_race:
        race_result = racecheck.analyze_tree(root)
        run_findings_pass("race", race_result)
        # trend keys: how much shared state the thread model carries
        doc["race"]["shared_attrs"] = sum(
            len(attrs) for attrs in race_result.shared_attrs.values()
        )
        doc["race"]["roles"] = len({
            role
            for roles in race_result.roles.values()
            for role in roles
        })
        doc["race"]["classes"] = {
            cls: {
                "shared_attrs": race_result.shared_attrs.get(cls, []),
                "roles": race_result.roles.get(cls, []),
            }
            for cls in sorted(
                set(race_result.shared_attrs) | set(race_result.roles)
            )
        }

    if run_shard:
        shard_result = shardcheck.analyze_all(
            root, hbm_mb=args.hbm_mb, giant_mb=args.giant_mb
        )
        run_findings_pass("shard", shard_result)
        doc["shard"]["footprint"] = {
            r.key: dict(r.footprint, mesh=r.mesh, script=r.script)
            for r in shard_result.reports
        }
        doc["shard"]["cost"] = {
            r.key: r.cost
            for r in shard_result.reports if r.cost is not None
        }
        if args.steplog:
            # predicted-vs-measured step time (ISSUE 7): hold each
            # train workload's wire-time model against the worker's
            # steplog; an explicit comparison that regresses past the
            # slack fails the run — the operator asked for the gate
            # by passing --steplog
            from dcos_commons_tpu.trace.steplog import read_steplog

            records = read_steplog(args.steplog)
            doc["shard"]["stepcompare"] = {}
            for r in shard_result.reports:
                if r.cost is None:
                    continue
                comparison = shardcheck.stepcompare(
                    r.cost, records, floor_us=args.step_floor_us,
                    slack=args.step_slack,
                )
                doc["shard"]["stepcompare"][r.key] = comparison
                emit(
                    f"stepcompare {r.key}: measured p50 "
                    f"{comparison['measured_p50_us']}us vs floor "
                    f"{comparison['predicted_floor_us']}us "
                    f"(wire {comparison['predicted_wire_us']}us), "
                    f"regression={comparison['regression']}"
                )
                failed |= comparison["regression"] is True

    if run_config:
        config_result = configcheck.analyze_all(root)
        run_findings_pass("config", config_result)
        # trend keys: how much of the env surface the graph covers
        doc["config"]["env_vars"] = len(config_result.env_vars)
        doc["config"]["flows"] = len(config_result.flows)
        doc["config"]["per_rule"] = dict(config_result.per_rule)
        if args.docs:
            docs_path = configcheck.write_config_reference(
                root, config_result
            )
            emit(f"docs: wrote {docs_path}")
            doc["config"]["docs_path"] = docs_path

    if run_dur:
        dur_result = durcheck.analyze_tree(root)
        run_findings_pass("dur", dur_result)
        # trend keys: the durability surface the chaos matrix covers
        doc["dur"]["persistence_points"] = len(
            dur_result.persistence_points
        )
        per_kind: dict = {}
        for point in dur_result.persistence_points:
            per_kind[point.kind] = per_kind.get(point.kind, 0) + 1
        doc["dur"]["per_kind"] = per_kind
        doc["dur"]["per_rule"] = dict(dur_result.per_rule)

    if args.update_baseline:
        if not (run_lint or run_spmd or run_shard or run_race
                or run_config or run_dur):
            emit(
                "baseline: nothing to update — only lint, spmd, shard, "
                "race, config, and dur feed the baseline; run one of "
                "them"
            )
        else:
            # entries of a baseline-feeding pass that did NOT run
            # survive verbatim: `--lint --update-baseline` must not
            # erase triaged spmd/shard debt it never recomputed (and
            # vice versa)
            retain = {}
            for fp, count in known.items():
                rule = fp.rsplit("::", 1)[-1]
                if rule.startswith("spmd-"):
                    owner_ran = run_spmd
                elif rule.startswith("shard-"):
                    owner_ran = run_shard
                elif rule.startswith("race-"):
                    owner_ran = run_race
                elif rule.startswith("config-"):
                    owner_ran = run_config
                elif rule.startswith("dur-"):
                    owner_ran = run_dur
                else:
                    owner_ran = run_lint
                if not owner_ran:
                    retain[fp] = count
            counts = baseline_mod.save_baseline(
                baseline_path, baseline_feed, retain=retain
            )
            emit(
                f"baseline: {sum(counts.values())} finding(s) across "
                f"{len(counts)} file/rule pair(s) -> {baseline_path}"
            )

    if run_specs:
        host_model = speccheck.HostModel(
            cpus=args.host_cpus,
            memory_mb=args.host_mem,
            disk_mb=args.host_disk,
        )
        findings = speccheck.analyze_all(root, host_model)
        for finding in findings:
            emit(finding.render())
        emit(f"specs: {len(findings)} finding(s)")
        doc["specs"] = {
            "findings": [f.to_dict() for f in findings],
        }
        failed |= bool(findings)

    if run_plan:
        summary = plancheck.check_all(max_states=args.plan_max_states)
        emit(f"plan: {summary.states_explored} states explored")
        emit(summary.render())
        doc["plan"] = {
            "states_explored": summary.states_explored,
            "transitions": summary.transitions,
            "configs": {
                r.config: {
                    "states": r.states,
                    "transitions": r.transitions,
                    "complete_states": r.complete_states,
                    "truncated": r.truncated,
                    "livelock_checked": r.livelock_checked,
                    "violations": len(r.violations),
                }
                for r in summary.results
            },
            "violations": [
                {
                    "invariant": v.invariant,
                    "detail": v.detail,
                    "trace": list(v.trace),
                }
                for v in summary.violations
            ],
        }
        failed |= not summary.ok

    rc = 1 if failed else 0
    if args.as_json:
        doc["exit_code"] = rc
        print(json.dumps(doc, indent=2, sort_keys=True))
    return rc


if __name__ == "__main__":
    sys.exit(main())
