"""Provision-time compile-cache seeding (VERDICT r3 #8).

Run ONCE when a host is provisioned (agent `--provision-cmd`, or by
hand) with the fleet's shared `JAX_COMPILATION_CACHE_DIR`: it
compiles the framework's standard programs at their deployed shapes
into the persistent cache, so the FIRST deploy on a fresh host pays
disk-cache-hit time instead of a full XLA compile — cold deploy ~=
warm deploy.  Programs are compiled with `jax.jit(...).lower().
compile()` (no data, no training) and selected by WARM_TARGETS
(comma list; default: mnist).

The cache key covers the jaxpr + compile options + device kind, so a
seeded entry hits exactly when the real task would have compiled the
same program (utils/compile_cache.py).
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))


def warm_mnist() -> None:
    import jax
    import optax

    from dcos_commons_tpu.models import MlpConfig, mlp_init, mlp_train_step
    from dcos_commons_tpu.utils import synthetic_mnist

    config = MlpConfig()
    params = mlp_init(config, jax.random.key(0))
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)
    step_fn = mlp_train_step(optimizer)
    x, y = synthetic_mnist(jax.random.key(1), 256)
    # lower + compile ONLY: provisioning must not run a training step
    jax.jit(step_fn).lower(params, opt_state, x, y).compile()


def main() -> int:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from dcos_commons_tpu.utils import enable_compilation_cache

    if not enable_compilation_cache():
        print(
            "warm_cache: no JAX_COMPILATION_CACHE_DIR set — nothing "
            "to seed", file=sys.stderr,
        )
        return 1
    targets = os.environ.get("WARM_TARGETS", "mnist").split(",")
    for target in targets:
        target = target.strip()
        fn = globals().get(f"warm_{target}")
        if fn is None:
            print(f"warm_cache: unknown target {target!r}",
                  file=sys.stderr)
            return 1
        t0 = time.time()
        fn()
        print(f"warm_cache: seeded {target} in {time.time()-t0:.1f}s",
              flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
