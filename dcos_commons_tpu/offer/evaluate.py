"""The evaluation pipeline: requirement + snapshots -> operations.

Reference: offer/evaluate/OfferEvaluator.java:65,113 and its stage
pipeline (:250-310, new-pod :411-522): Placement -> per-task resources
(cpu/mem/ports/volumes) -> launch, each stage returning an
EvaluationOutcome; first fully-passing host wins (:137-248); existing
pods reuse prior reservation ids (TaskResourceMapper) so relaunches
keep their footprint.  PodInfoBuilder's TaskInfo assembly (env, ports,
readiness labels) lives in ``_build_task_info`` here.

TPU-first: gang requirements are evaluated atomically across hosts
via torus.find_subslice; the evaluator allocates the pjit rendezvous
point (worker-0 coordinator address) and injects the JAX distributed
env contract into every worker (the moral equivalent of the
reference's bootstrap DNS-wait, sdk/bootstrap/main.go:218-289).
"""

from __future__ import annotations

import contextlib
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dcos_commons_tpu.common import Label, TaskInfo, new_task_id
from dcos_commons_tpu.offer.inventory import ResourceSnapshot, SliceInventory
from dcos_commons_tpu.offer.ledger import (
    Reservation,
    ReservationLedger,
    new_reservation_id,
)
from dcos_commons_tpu.offer.multislice import (
    ENV_TPU_SLICE_COORDS,
    SLICE_COORDINATOR_PORT_NAME,
    eligible_slice_ids,
    place_slice_set,
)
from dcos_commons_tpu.offer.outcome import EvaluationOutcome
from dcos_commons_tpu.offer.placement import (
    PlacementContext,
    PlacementRule,
    parse_placement,
)
from dcos_commons_tpu.plan.step import PodInstanceRequirement, RecoveryType
from dcos_commons_tpu.specification.specs import (
    PodSpec,
    TaskSpec,
    task_full_name,
)
from dcos_commons_tpu.state.state_store import GoalStateOverride, StateStore
from dcos_commons_tpu.trace.recorder import NULL_TRACER

# env contract injected into every launched task (reference analogue:
# offer/taskdata/EnvConstants + PodInfoBuilder env assembly)
# idle command a PAUSED task runs instead of its real cmd (reference:
# the pause override sleep cmd in PodQueries/GoalStateOverride)
PAUSE_COMMAND = "sleep 1209600"

ENV_POD_INSTANCE_INDEX = "POD_INSTANCE_INDEX"
ENV_TASK_NAME = "TASK_NAME"
ENV_FRAMEWORK_NAME = "FRAMEWORK_NAME"
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_COUNT = "TPU_WORKER_COUNT"
ENV_TPU_CHIPS_PER_HOST = "TPU_CHIPS_PER_HOST"
ENV_TPU_TOPOLOGY = "TPU_TOPOLOGY"
ENV_TPU_GENERATION = "TPU_GENERATION"
# libtpu provisioning env (the reference bootstrap's task-side setup,
# sdk/bootstrap/main.go; here the scheduler computes it at placement):
# the chip ids this host contributes and the host's chip-grid bounds in
# the TPU_CHIPS_PER_HOST_BOUNDS x,y,z form libtpu expects
ENV_TPU_CHIP_IDS = "TPU_CHIP_IDS"
ENV_TPU_HOST_BOUNDS = "TPU_CHIPS_PER_HOST_BOUNDS"
ENV_COORDINATOR_ADDRESS = "COORDINATOR_ADDRESS"
ENV_TPU_SLICE_INDEX = "TPU_SLICE_INDEX"
ENV_TPU_NUM_SLICES = "TPU_NUM_SLICES"
COORDINATOR_PORT_NAME = "coordinator"


class EvaluationContext:
    """Shared per-cycle evaluation state (the offer-cycle fast path).

    ``run_cycle`` constructs ONE of these and threads it through every
    candidate evaluation, so the state-store task scan and the hosts
    dict are computed once per cycle instead of once per step.  Both
    are lazy — an idle cycle (no candidates) pays nothing.

    Correctness contract: the scheduler must call ``note_launched``
    after recording a launch, so the next candidate in the SAME cycle
    sees the just-launched tasks exactly as a fresh ``fetch_tasks``
    would (max-per/colocate rules count them).
    """

    def __init__(self, state_store: StateStore, inventory: SliceInventory):
        self._state_store = state_store
        self._inventory = inventory
        self._tasks: Optional[List[TaskInfo]] = None
        self._task_index: Optional[Dict[str, Dict[str, List[TaskInfo]]]] = None

    def tasks(self) -> List[TaskInfo]:
        if self._tasks is None:
            self._tasks = list(self._state_store.fetch_tasks())
        return self._tasks

    def hosts(self) -> Dict[str, object]:
        # cached on the inventory itself (keyed to its topology
        # generation) so every context of every cycle shares one dict
        # instead of rebuilding a fleet-sized map per cycle
        return self._inventory.hosts_by_id()

    def task_index(self) -> Dict[str, Dict[str, List[TaskInfo]]]:
        """pod_type -> instance key -> task infos, built once per
        cycle: PlacementContext counts come from this instead of a
        per-requirement scan over the whole task list."""
        if self._task_index is None:
            idx: Dict[str, Dict[str, List[TaskInfo]]] = {}
            for info in self.tasks():
                idx.setdefault(info.pod_type, {}).setdefault(
                    f"{info.pod_type}-{info.pod_index}", []
                ).append(info)
            self._task_index = idx
        return self._task_index

    def note_launched(self, infos: List[TaskInfo]) -> None:
        """Mirror ``StateStore.store_tasks`` semantics on the cached
        task list: a relaunch replaces the same-named entry."""
        if self._tasks is None or not infos:
            return
        names = {i.name for i in infos}
        self._tasks = [
            t for t in self._tasks if t.name not in names
        ] + list(infos)
        self._task_index = None

    def invalidate_tasks(self) -> None:
        """Drop the cached task scan after a mid-cycle state mutation
        this context cannot mirror (e.g. an ActionStep erasing tasks);
        the next evaluation re-fetches."""
        self._tasks = None
        self._task_index = None


@dataclass
class ReserveRecommendation:
    reservation: Reservation


@dataclass
class LaunchRecommendation:
    task_info: TaskInfo


@dataclass
class EvaluationResult:
    passed: bool
    outcome: EvaluationOutcome
    reservations: List[Reservation] = field(default_factory=list)
    task_infos: List[TaskInfo] = field(default_factory=list)

    @property
    def recommendations(self) -> List[object]:
        return [ReserveRecommendation(r) for r in self.reservations] + [
            LaunchRecommendation(t) for t in self.task_infos
        ]


class OfferEvaluator:
    def __init__(
        self,
        state_store: StateStore,
        ledger: ReservationLedger,
        service_name: str,
        target_config_id: str,
    ):
        self._state_store = state_store
        self._ledger = ledger
        self._service_name = service_name
        self._target_config_id = target_config_id
        # multi-service: free-capacity snapshots must subtract EVERY
        # service's claims, not just this service's namespaced ledger
        # (reference: one Mesos master arbitrates all frameworks; here
        # the merged ledger view is the arbiter)
        self._snapshot_view = ledger
        # set by the scheduler so snapshot synthesis shows up under
        # the cycle.* timers; None when wired by hand in tests
        self.metrics = None
        # traceview flight recorder (set by the scheduler alongside
        # metrics); hand-wired evaluators default to the no-op recorder
        self.tracer = None
        # fleet-scale fast path: shared copy-on-write snapshots,
        # indexed placement pre-filtering, and the per-requirement
        # failure memo.  False = the PR-1 behavior (per-host snapshot
        # copies, full candidate scans) — the reference oracle the
        # equivalence tests and bench_fleet_scale compare against.
        self.fast_path = True
        # requirement-name -> (change token, failed result, pod spec):
        # a requirement that failed against an unchanged fleet/ledger/
        # task set short-circuits without re-scanning.  The pod spec
        # object is held so identity comparison can never alias a
        # recycled id() from a superseded config.
        self._memo: Dict[tuple, tuple] = {}

    @property
    def target_config_id(self) -> str:
        """The config id launches are stamped with (read by the
        autoscale plan synthesis when no config store is wired)."""
        return self._target_config_id

    def set_target_config(self, config_id: str) -> None:
        self._target_config_id = config_id
        self._memo.clear()

    def set_snapshot_view(self, view) -> None:
        self._snapshot_view = view
        self._memo.clear()

    def invalidate_memo(self) -> None:
        """Drop memoized requirement outcomes after a state mutation
        the change tokens cannot see (e.g. an ActionStep erasing
        tasks mid-cycle)."""
        self._memo.clear()

    def _incr(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.incr(name)

    # ------------------------------------------------------------------

    def evaluate(
        self,
        requirement: PodInstanceRequirement,
        inventory: SliceInventory,
        context: Optional[EvaluationContext] = None,
        trace_parent=None,
    ) -> EvaluationResult:
        """Match one requirement against the current inventory.

        ``context`` shares the task scan and hosts dict across every
        candidate of one scheduler cycle; omitted (direct callers,
        tests), a private one is built — same results, less reuse.
        ``trace_parent`` is the offer-cycle span: the evaluation span
        and its per-pod outcome events inherit its correlation id."""
        if context is None:
            context = EvaluationContext(self._state_store, inventory)
        tracer = self.tracer or NULL_TRACER
        pod = requirement.pod
        with tracer.span(
            f"evaluate:{requirement.name}", parent=trace_parent,
            track="scheduler", pod=pod.type,
        ) as span:
            result = self._evaluate_requirement(
                requirement, inventory, context
            )
            span.set_attr("passed", str(result.passed).lower())
            reason = result.outcome.reason or result.outcome.source
            if not result.passed:
                span.set_attr("failing_requirement", reason)
            # per-pod outcome events: one lane per pod instance, the
            # failing requirement attached where evaluation refused
            for index in requirement.instances:
                attrs = {"outcome": "pass" if result.passed else "fail"}
                if not result.passed:
                    attrs["failing_requirement"] = reason
                tracer.event(
                    f"evaluate:{pod.type}-{index}", parent=span,
                    track=f"{pod.type}-{index}", **attrs,
                )
            return result

    def _memo_token(self, inventory: SliceInventory):
        """Change token guarding the requirement-failure memo: the
        snapshot view's whole-ledger token plus the topology
        generation.  None disables memoization (view has no token)."""
        token_fn = getattr(self._snapshot_view, "generation_token", None)
        view_token = token_fn() if token_fn is not None else None
        if view_token is None:
            return None
        return (view_token, inventory.topology_generation)

    def _evaluate_requirement(
        self,
        requirement: PodInstanceRequirement,
        inventory: SliceInventory,
        context: EvaluationContext,
    ) -> EvaluationResult:
        token = self._memo_token(inventory) if self.fast_path else None
        memo_key = None
        if token is not None:
            memo_key = (
                requirement.name,
                tuple(requirement.instances),
                tuple(requirement.tasks_to_launch),
                requirement.recovery_type,
            )
            hit = self._memo.get(memo_key)
            if hit is not None and hit[0] == token \
                    and hit[2] is requirement.pod:
                # prior outcome was computed against an unchanged
                # candidate set: short-circuit without re-scanning
                self._incr("offers.eval.shortcircuit")
                return hit[1]
        timer = (
            self.metrics.time("cycle.snapshot")
            if self.metrics is not None else contextlib.nullcontext()
        )
        index = None
        with timer:
            if self.fast_path:
                index = inventory.offer_view(self._snapshot_view)
                snapshots = index.ordered_snapshots()
            else:
                snapshots = inventory.snapshots(self._snapshot_view)
        excluded = set(requirement.task_names())
        if index is not None:
            ctx = PlacementContext(
                pod_type=requirement.pod.type,
                hosts=context.hosts(),
                task_index=context.task_index(),
                excluded_names=frozenset(excluded),
            )
        else:
            ctx = PlacementContext(
                pod_type=requirement.pod.type,
                existing_tasks=[
                    t
                    for t in context.tasks()
                    # tasks being relaunched must not block their own
                    # placement
                    if t.name not in excluded
                ],
                hosts=context.hosts(),
            )
        result = self._evaluate_placed(
            requirement, inventory, snapshots, ctx, index
        )
        if memo_key is not None and not result.passed:
            # only failures memoize: a pass consumes capacity and is
            # never legitimately replayed
            self._memo[memo_key] = (token, result, requirement.pod)
        return result

    def _evaluate_placed(
        self,
        requirement: PodInstanceRequirement,
        inventory: SliceInventory,
        snapshots,
        ctx: PlacementContext,
        index,
    ) -> EvaluationResult:

        # In-place relaunch: reuse committed reservations when they are
        # still valid (reference: existing-pod pipeline reusing prior
        # resource ids, OfferEvaluator.java:266-310).  PERMANENT
        # recovery skips this and re-places from scratch.
        if requirement.recovery_type is not RecoveryType.PERMANENT:
            reuse = self._try_reuse(requirement, inventory)
            if reuse is not None:
                return reuse
            # sidecar tasks (backup/bench plans) launch inside a pod
            # instance whose footprint already exists: same host, own
            # scalars, never the pod's chips
            colocate = self._try_colocate(requirement, inventory, snapshots)
            if colocate is not None:
                return colocate

        pod = requirement.pod
        rule = parse_placement(pod.placement)
        # pre-reserved capacity (reference: ResourceSpec preReservedRole
        # + PreReservationCannotChange): the fleet operator marks hosts
        # as carved out for a role via the reserved_role attribute.
        # BOTH directions are enforced — a pod declaring the role
        # places ONLY on those hosts, and an ordinary pod NEVER lands
        # on a carved-out host (otherwise first-fit would consume the
        # reservation); the outcome tracker records refusals like any
        # placement term.
        from dcos_commons_tpu.offer.placement import (
            AndRule,
            FieldMatchRule,
        )

        if pod.pre_reserved_role:
            rule = AndRule([
                FieldMatchRule("reserved_role", [pod.pre_reserved_role]),
                rule,
            ])
        else:
            rule = AndRule([
                FieldMatchRule("reserved_role", [""], invert=False),
                rule,
            ])
        # profile volumes constrain placement: the host must advertise
        # every storage profile the pod's volumes demand
        profiles = {
            p
            for task in pod.tasks
            for v in task.volumes
            for p in v.profiles
        }
        if profiles:
            from dcos_commons_tpu.offer.placement import VolumeProfilesRule

            rule = AndRule([VolumeProfilesRule(profiles), rule])
        if pod.gang and pod.tpu is not None and pod.tpu.topology:
            return self._evaluate_gang(
                requirement, snapshots, rule, ctx, index
            )
        return self._evaluate_instances(
            requirement, snapshots, rule, ctx, index
        )

    # -- reuse path ----------------------------------------------------

    def _try_reuse(
        self,
        requirement: PodInstanceRequirement,
        inventory: SliceInventory,
    ) -> Optional[EvaluationResult]:
        """Relaunch on existing reservations if every task of the
        requirement still has its full footprint on healthy hosts."""
        placements: List[Tuple[int, str, List[Reservation]]] = []
        for index in requirement.instances:
            host_ids = set()
            reservations: List[Reservation] = []
            for task_name in requirement.tasks_to_launch:
                full = task_full_name(requirement.pod.type, index, task_name)
                task_reservations = self._ledger.for_task(full)
                if not task_reservations:
                    return None
                reservations.extend(task_reservations)
                host_ids |= {r.host_id for r in task_reservations}
            if len(host_ids) != 1:
                return None
            host_id = host_ids.pop()
            if not inventory.is_up(host_id):
                return None  # host gone: fall through to fresh placement
            placements.append((index, host_id, reservations))

        coordinator = self._existing_coordinator(requirement, inventory)
        pod = requirement.pod
        if pod.gang and pod.tpu is not None and pod.tpu.topology \
                and not coordinator:
            # a gang relaunch without the rendezvous reservation would
            # launch workers that hang forever in
            # jax.distributed.initialize — fail loudly instead; the
            # operator escalates with `pod replace` (PERMANENT), which
            # re-places from scratch and mints a fresh coordinator
            return EvaluationResult(
                False,
                EvaluationOutcome.fail(
                    "reuse",
                    "no coordinator reservation found for gang "
                    "relaunch; refusing to launch a gang that cannot "
                    "rendezvous (escalate with pod replace)",
                ),
            )
        outcome = EvaluationOutcome.ok(
            "reuse", f"relaunching in place on {[p[1] for p in placements]}"
        )
        # multi-slice gangs carry a slice env contract
        # (TPU_SLICE_INDEX/TPU_NUM_SLICES, set at claim time in
        # _evaluate_gang); an in-place relaunch must restore it or the
        # mesh layer builds a dcn-less mesh.  Derived from the INSTANCE
        # index and pod.count: at claim time instances are [0..count-1]
        # slice-major, so worker_id == index — a subset relaunch (a
        # per-index deploy step) must not renumber from its enumerate
        # position.
        n_slices = pod.tpu.slices if pod.tpu is not None else 1
        hosts_per_slice = max(1, pod.count // max(1, n_slices))
        slice_coords: List[str] = []
        if n_slices > 1:
            slice_coords = self._existing_slice_coords(
                requirement, inventory, n_slices, hosts_per_slice
            )
        task_infos = []
        for index, host_id, reservations in placements:
            worker_id = index
            slice_env: Dict[str, str] = {}
            if n_slices > 1:
                slice_env = {
                    ENV_TPU_SLICE_INDEX: str(index // hosts_per_slice),
                    ENV_TPU_NUM_SLICES: str(n_slices),
                }
                if slice_coords:
                    slice_env[ENV_TPU_SLICE_COORDS] = ",".join(slice_coords)
            host = inventory.host(host_id)
            for task_name in requirement.tasks_to_launch:
                task_spec = requirement.pod.task(task_name)
                full = task_full_name(requirement.pod.type, index, task_name)
                task_res = [
                    r for r in reservations if r.task_name == full
                    and r.container_path not in (
                        COORDINATOR_PORT_NAME, SLICE_COORDINATOR_PORT_NAME
                    )
                ]
                # rebuild the PORT_* env contract from the reservation's
                # port list (appended in spec order at claim time)
                port_env: Dict[str, str] = {}
                if task_res:
                    for port_spec, port in zip(
                        task_spec.resources.ports, task_res[0].ports
                    ):
                        key = port_spec.env_key or f"PORT_{port_spec.name.upper()}"
                        port_env[key] = str(port)
                # chips follow the reservation holder (see claim path)
                task_chips = sorted({
                    c for r in task_res for c in r.chip_ids
                })
                task_infos.append(
                    self._build_task_info(
                        requirement, task_spec, index, host,
                        reservations=task_res,
                        chips=task_chips,
                        coordinator=coordinator,
                        worker_id=worker_id,
                        extra_env={**port_env, **slice_env},
                    )
                )
        return EvaluationResult(True, outcome, [], task_infos)

    def _try_colocate(
        self,
        requirement: PodInstanceRequirement,
        inventory: SliceInventory,
        snapshots: List[ResourceSnapshot],
    ) -> Optional[EvaluationResult]:
        """Place tasks into a pod instance whose footprint already
        exists: sibling tasks of the instance hold reservations, so the
        new tasks claim only their own cpu/mem/ports on that host.

        This is the sidecar-plan path (reference: cassandra backup
        plans run extra tasks inside the pod's existing executor
        footprint rather than re-negotiating resources).  The pod's
        chips are NOT re-reserved — they belong to its main tasks.
        """
        pod = requirement.pod
        sibling_names = {t.name for t in pod.tasks} - set(
            requirement.tasks_to_launch
        )
        if not sibling_names:
            return None
        placements: List[Tuple[int, str]] = []
        for index in requirement.instances:
            anchors: List[Reservation] = []
            for other in sibling_names:
                anchors.extend(
                    self._ledger.for_task(
                        task_full_name(pod.type, index, other)
                    )
                )
            host_ids = {r.host_id for r in anchors}
            if len(host_ids) != 1:
                return None  # no (or ambiguous) footprint: fresh placement
            host_id = host_ids.pop()
            if not inventory.is_up(host_id):
                return None
            placements.append((index, host_id))
        snap_by_host = {s.host.host_id: s for s in snapshots}
        outcome = EvaluationOutcome.ok(
            "colocate",
            f"sidecar tasks joining existing footprint on "
            f"{[h for _, h in placements]}",
        )
        reservations: List[Reservation] = []
        task_infos: List[TaskInfo] = []
        # a gang sidecar group (the collectives bench) rendezvous like
        # the main gang: instance 0's host carries a fresh coordinator
        # port for THIS task group — the trainer's port is in use
        gang_group = (
            pod.gang and pod.tpu is not None and len(placements) > 1
        )
        coordinator = ""
        if gang_group:
            coord_host = placements[0][1]
            coord_snap = snap_by_host.get(coord_host)
            if coord_snap is None:
                return None
            coord_port = coord_snap.copy().allocate_port()
            coordinator = _coordinator_address(coord_snap.host, coord_port)
        # instances sharing a host consume from ONE working snapshot so
        # capacity cannot be double-booked
        claimed: Dict[str, ResourceSnapshot] = {}
        for worker_id, (index, host_id) in enumerate(placements):
            work = claimed.get(host_id)
            if work is None:
                snap = snap_by_host.get(host_id)
                if snap is None:
                    return None
                work = snap.copy()
                claimed[host_id] = work
            res, infos = self._claim_instance(
                requirement, index, work, [], coordinator=coordinator,
                coordinator_here=(gang_group and worker_id == 0),
                worker_id=worker_id,
            )
            if res is None:
                return EvaluationResult(
                    False,
                    EvaluationOutcome.fail(
                        "colocate",
                        f"pod {pod.type}-{index} footprint host {host_id} "
                        "lacks cpu/mem for the sidecar task",
                    ),
                )
            reservations.extend(res)
            task_infos.extend(infos)
        return EvaluationResult(True, outcome, reservations, task_infos)

    def _existing_coordinator(
        self, requirement: PodInstanceRequirement, inventory
    ) -> str:
        # relaunches keep the original rendezvous point: reservations
        # for instance 0 carry the coordinator port
        for r in self._ledger.for_task(
            task_full_name(
                requirement.pod.type, 0, requirement.tasks_to_launch[0]
            )
        ):
            if r.container_path == COORDINATOR_PORT_NAME and r.ports:
                host = inventory.host(r.host_id)
                if host is not None:
                    return _coordinator_address(host, r.ports[0])
                # coordinator host gone from the inventory: there is
                # no dialable address — return nothing so the gang
                # reuse guard fails LOUDLY instead of launching
                # workers that hang in jax.distributed.initialize
                return ""
        return ""

    def _existing_slice_coords(
        self, requirement: PodInstanceRequirement, inventory,
        n_slices: int, hosts_per_slice: int,
    ) -> List[str]:
        """Rebuild the per-slice coordinator address list from the
        slice leaders' SLICE_COORDINATOR_PORT_NAME reservations (the
        multi-slice analogue of ``_existing_coordinator``).  An empty
        list means some leader's claim is gone — the caller omits
        TPU_SLICE_COORDS rather than advertise a partial set."""
        coords: List[str] = []
        for k in range(n_slices):
            leader = k * hosts_per_slice
            addr = ""
            for r in self._ledger.for_task(
                task_full_name(
                    requirement.pod.type, leader,
                    requirement.tasks_to_launch[0],
                )
            ):
                if r.container_path == SLICE_COORDINATOR_PORT_NAME \
                        and r.ports:
                    host = inventory.host(r.host_id)
                    if host is not None:
                        addr = _coordinator_address(host, r.ports[0])
                    break
            if not addr:
                return []
            coords.append(addr)
        return coords

    # -- fresh placement ----------------------------------------------

    def _evaluate_gang(
        self,
        requirement: PodInstanceRequirement,
        snapshots: List[ResourceSnapshot],
        rule: PlacementRule,
        ctx: PlacementContext,
        index=None,
    ) -> EvaluationResult:
        pod = requirement.pod
        scalar_needs = _pod_scalar_needs(pod, requirement.tasks_to_launch)

        def eligible(snap: ResourceSnapshot) -> EvaluationOutcome:
            rule_outcome = rule.filter(snap, ctx)
            if not rule_outcome.passed:
                return rule_outcome
            probe = snap.copy()
            if not probe.try_consume_scalar(*scalar_needs):
                return EvaluationOutcome.fail(
                    f"host:{snap.host.host_id}",
                    f"insufficient cpu/mem/disk for {scalar_needs}",
                )
            return EvaluationOutcome.ok(f"host:{snap.host.host_id}")

        if index is not None:
            # slice-set pre-filter (offer/multislice.py): slices that
            # cannot hold even one fully-free `topology` rectangle are
            # skipped before any anchor search
            total_chips = 1
            for d in pod.tpu.topology_dims():
                total_chips *= d
            eligible_slices = eligible_slice_ids(
                index, ctx.hosts, total_chips,
                generation=pod.tpu.generation,
            )
            if eligible_slices:
                slice_index = index.value_index("slice")
                candidate_ids: set = set()
                for s in eligible_slices:
                    candidate_ids |= slice_index.get(s, frozenset())
                self._incr("offers.index.hit")
                snapshots = index.snapshots_for(candidate_ids)
            else:
                # nothing can place: run the UNFILTERED search so the
                # outcome tree explains every slice's refusal (the
                # requirement memo keeps repeat failures O(1))
                self._incr("offers.index.scan")

        # multi-slice gangs (tpu: slices: N): N slice-local sub-gangs,
        # one contiguous `topology` rectangle in each of N DISTINCT
        # slices, all on one DCN pool (offer/multislice.py).  Workers
        # are numbered slice-major; every worker gets
        # TPU_SLICE_INDEX/TPU_NUM_SLICES so the mesh layer lays the dcn
        # (data-parallel-across-slices) axis over the slice boundary
        # and keeps tp/sp collectives on ICI (scaling-book recipe).
        n_slices = pod.tpu.slices
        placement = place_slice_set(snapshots, pod.tpu, eligible)
        outcome = placement.outcome
        if not placement.ok:
            return EvaluationResult(False, outcome)
        ordered = placement.snapshots
        if len(ordered) != len(requirement.instances):
            outcome.passed = False
            outcome.reason = (
                f"{n_slices} slice(s) of topology yield {len(ordered)} "
                f"hosts but pod count is {len(requirement.instances)}"
            )
            return EvaluationResult(False, outcome)

        # worker 0's host (slice 0) carries the jax.distributed
        # coordinator for the WHOLE multi-slice gang: one global
        # rendezvous, slice-local ICI + cross-slice DCN under one
        # mesh.  Each slice leader (worker k*hosts_per_slice)
        # additionally carries a slice-local rendezvous port; the full
        # slice-major address list is advertised to every worker as
        # TPU_SLICE_COORDS.  Ports are probed on snapshot COPIES here
        # and re-allocated identically at claim time — both walks
        # start from the same committed snapshot state, so the claim
        # is deterministic (the established coordinator idiom).
        coord_snap = ordered[0]
        probe = coord_snap.copy()
        coord_port = probe.allocate_port()
        coordinator = _coordinator_address(coord_snap.host, coord_port)
        hosts_per_slice = placement.hosts_per_slice
        slice_coords: List[str] = []
        if n_slices > 1:
            for k in range(n_slices):
                leader = ordered[k * hosts_per_slice]
                # slice 0's leader already allocated the global
                # coordinator port on `probe` — reuse that walk so the
                # second allocation cannot collide with the first
                leader_probe = probe if k == 0 else leader.copy()
                slice_port = leader_probe.allocate_port()
                slice_coords.append(
                    _coordinator_address(leader.host, slice_port)
                )

        reservations: List[Reservation] = []
        task_infos: List[TaskInfo] = []
        for worker_id, (index_i, snap) in enumerate(
            zip(requirement.instances, ordered)
        ):
            work = snap.copy()
            chips = work.try_consume_chips(snap.host.chips_per_host)
            if chips is None:  # cannot happen post-eligibility; guard anyway
                return EvaluationResult(
                    False,
                    EvaluationOutcome.fail(
                        "gang", f"chips vanished on {snap.host.host_id}"
                    ),
                )
            slice_env = {}
            slice_coordinator = ""
            if n_slices > 1:
                slice_env = {
                    ENV_TPU_SLICE_INDEX: str(worker_id // hosts_per_slice),
                    ENV_TPU_NUM_SLICES: str(n_slices),
                    ENV_TPU_SLICE_COORDS: ",".join(slice_coords),
                }
                if worker_id % hosts_per_slice == 0:
                    slice_coordinator = slice_coords[
                        worker_id // hosts_per_slice
                    ]
            res, infos = self._claim_instance(
                requirement, index_i, work, chips, coordinator,
                coordinator_here=(worker_id == 0), worker_id=worker_id,
                extra_env=slice_env, slice_coordinator=slice_coordinator,
            )
            if res is None:
                return EvaluationResult(
                    False,
                    EvaluationOutcome.fail(
                        "gang", f"resource claim failed on {snap.host.host_id}"
                    ),
                )
            reservations.extend(res)
            task_infos.extend(infos)
        return EvaluationResult(True, outcome, reservations, task_infos)

    def _evaluate_instances(
        self,
        requirement: PodInstanceRequirement,
        snapshots: List[ResourceSnapshot],
        rule: PlacementRule,
        ctx: PlacementContext,
        index=None,
    ) -> EvaluationResult:
        """Non-gang: place each instance independently, first host wins
        (reference: first fully-passing offer, OfferEvaluator.java:137-171).

        Indexed path: the rule emits a candidate host-id SET which is
        intersected with the free-chip-count bucket BEFORE any
        snapshot is touched; candidates iterate in scan-order so the
        winner is identical to a full scan.  Recomputed per instance —
        each placement updates the counts the rules consult."""
        pod = requirement.pod
        reservations: List[Reservation] = []
        task_infos: List[TaskInfo] = []
        root = EvaluationOutcome.ok("evaluate", pod.type)
        claimed_hosts: Dict[str, ResourceSnapshot] = {}
        # deploy-time candidate algebra (the PR 9 remainder): a rule
        # with a STATIC candidate key yields the same candidate set —
        # and the same chip-bucket intersection, which reads the
        # committed view, not the loop's local claims — for every
        # instance of this requirement, so the set algebra and the
        # scan-order sort run ONCE, not once per instance.  Dynamic
        # rules (count-dependent) recompute per placement as before.
        static_scan: Optional[List[ResourceSnapshot]] = None
        rule_is_static = False
        if index is not None:
            key_of = getattr(rule, "candidate_key", None)
            rule_is_static = callable(key_of) and key_of() is not None
            if rule_is_static:
                cand = index.rule_candidates(rule, ctx)
                if pod.tpu is not None:
                    chip_ok = index.hosts_with_free_chips(
                        pod.tpu.chips_per_host
                    )
                    cand = chip_ok if cand is None else cand & chip_ok
                if cand:
                    static_scan = index.snapshots_for(cand)
        for index_i in requirement.instances:
            scan = snapshots
            if index is not None and rule_is_static:
                if static_scan is not None:
                    self._incr("offers.index.hit")
                    scan = static_scan
                else:
                    self._incr("offers.index.scan")
            elif index is not None:
                cand = index.rule_candidates(rule, ctx)
                if pod.tpu is not None:
                    chip_ok = index.hosts_with_free_chips(
                        pod.tpu.chips_per_host
                    )
                    cand = chip_ok if cand is None else cand & chip_ok
                if cand:
                    self._incr("offers.index.hit")
                    scan = index.snapshots_for(cand)
                else:
                    # unbounded rule (None) — or an EMPTY candidate
                    # set, where the full scan runs so the outcome
                    # tree explains every host's refusal (the
                    # requirement memo keeps repeat failures O(1))
                    self._incr("offers.index.scan")
            placed = False
            for snap in scan:
                snap = claimed_hosts.get(snap.host.host_id, snap)
                rule_outcome = rule.filter(snap, ctx)
                if not rule_outcome.passed:
                    root.children.append(rule_outcome)
                    continue
                work = snap.copy()
                chips = None
                if pod.tpu is not None:
                    if not snap.host.generation:
                        root.children.append(EvaluationOutcome.fail(
                            f"host:{snap.host.host_id}", "not a TPU host"
                        ))
                        continue
                    chips = work.try_consume_chips(pod.tpu.chips_per_host)
                    if chips is None:
                        root.children.append(EvaluationOutcome.fail(
                            f"host:{snap.host.host_id}",
                            f"needs {pod.tpu.chips_per_host} chips, "
                            f"{len(snap.free_chips)} free",
                        ))
                        continue
                res, infos = self._claim_instance(
                    requirement, index_i, work, chips or [], coordinator="",
                    coordinator_here=False, worker_id=index_i,
                )
                if res is None:
                    root.children.append(EvaluationOutcome.fail(
                        f"host:{snap.host.host_id}", "insufficient cpu/mem/disk"
                    ))
                    continue
                reservations.extend(res)
                task_infos.extend(infos)
                claimed_hosts[snap.host.host_id] = work
                # placement context must see this instance for max-per
                # rules on subsequent instances in the same requirement
                ctx.record_tasks(infos)
                placed = True
                root.children.append(EvaluationOutcome.ok(
                    f"host:{snap.host.host_id}",
                    f"{pod.type}-{index_i} placed",
                ))
                break
            if not placed:
                root.passed = False
                root.reason = f"no host satisfies {pod.type}-{index_i}"
                return EvaluationResult(False, root)
        return EvaluationResult(True, root, reservations, task_infos)

    # -- claim + TaskInfo assembly ------------------------------------

    def _claim_instance(
        self,
        requirement: PodInstanceRequirement,
        index: int,
        work: ResourceSnapshot,
        chips: List[str],
        coordinator: str,
        coordinator_here: bool,
        worker_id: int,
        extra_env: Optional[Dict[str, str]] = None,
        slice_coordinator: str = "",
    ):
        """Consume scalars/ports on ``work`` and emit reservations +
        TaskInfos for every task of one pod instance.

        ``slice_coordinator`` (multi-slice gangs, slice leaders only)
        is this host's slice-local rendezvous address: its port is
        claimed here under SLICE_COORDINATOR_PORT_NAME, riding the
        first task's resource ids like the global coordinator port."""
        pod = requirement.pod
        reservations: List[Reservation] = []
        task_infos: List[TaskInfo] = []
        chips_assigned = False
        # volume keys shared across the tasks claimed in THIS call
        # (ledger lookups only see already-committed siblings)
        instance_volumes: Dict[str, str] = {}
        anchor_res: List[Reservation] = []
        if coordinator_here:
            coord_port = work.allocate_port(int(coordinator.rsplit(":", 1)[1]))
            if coord_port is None:
                coord_port = work.allocate_port()
                coordinator = _coordinator_address(work.host, coord_port)
            anchor_res.append(Reservation(
                reservation_id=new_reservation_id(),
                host_id=work.host.host_id,
                task_name=task_full_name(
                    pod.type, index, requirement.tasks_to_launch[0]
                ),
                cpus=0.0,
                ports=[coord_port],
                container_path=COORDINATOR_PORT_NAME,
            ))
        if slice_coordinator:
            slice_port = work.allocate_port(
                int(slice_coordinator.rsplit(":", 1)[1])
            )
            if slice_port is None:
                slice_port = work.allocate_port()
            anchor_res.append(Reservation(
                reservation_id=new_reservation_id(),
                host_id=work.host.host_id,
                task_name=task_full_name(
                    pod.type, index, requirement.tasks_to_launch[0]
                ),
                cpus=0.0,
                ports=[slice_port],
                container_path=SLICE_COORDINATOR_PORT_NAME,
            ))
        reservations.extend(anchor_res)
        disk_seen_paths: set = set()
        for task_name in requirement.tasks_to_launch:
            task_spec = pod.task(task_name)
            full = task_full_name(pod.type, index, task_name)
            task_disk = _task_disk_mb(task_spec, disk_seen_paths)
            if not work.try_consume_scalar(
                task_spec.resources.cpus,
                task_spec.resources.memory_mb,
                task_disk,
            ):
                return None, None
            ports: List[int] = []
            port_env: Dict[str, str] = {}
            for port_spec in task_spec.resources.ports:
                port = work.allocate_port(port_spec.port)
                if port is None:
                    return None, None
                ports.append(port)
                key = port_spec.env_key or f"PORT_{port_spec.name.upper()}"
                port_env[key] = str(port)
            task_chips = chips if not chips_assigned else []
            chips_assigned = chips_assigned or bool(chips)
            volumes = self._instance_volume_keys(
                requirement, pod, index, task_spec, instance_volumes
            )
            reservation = Reservation(
                reservation_id=new_reservation_id(),
                host_id=work.host.host_id,
                task_name=full,
                role=self._service_name,
                cpus=task_spec.resources.cpus,
                memory_mb=task_spec.resources.memory_mb,
                disk_mb=task_disk,
                chip_ids=list(task_chips),
                ports=ports,
                volume_id=(uuid.uuid4().hex if task_spec.volumes else ""),
                container_path=(
                    task_spec.volumes[0].container_path if task_spec.volumes else ""
                ),
                volumes=volumes,
            )
            reservations.append(reservation)
            # the coordinator-port claims (global and slice-local)
            # ride on the first task's resource ids so reservation GC
            # (which keeps every id referenced by a stored TaskInfo)
            # never reclaims them
            info_res = [reservation]
            if anchor_res and not task_infos:
                info_res.extend(anchor_res)
            info = self._build_task_info(
                requirement, task_spec, index, work.host,
                # chips follow the RESERVATION holder: only the task
                # whose reservation carries the chip ids receives the
                # libtpu provisioning env — a co-launched chip-less
                # sidecar must not double-bind the devices
                reservations=info_res, chips=list(task_chips),
                coordinator=coordinator, worker_id=worker_id,
                extra_env={**(extra_env or {}), **port_env},
            )
            task_infos.append(info)
        return reservations, task_infos

    def _instance_volume_keys(
        self,
        requirement,
        pod,
        index: int,
        task_spec,
        claimed_now: Optional[Dict[str, str]] = None,
    ) -> Dict[str, str]:
        """container_path -> durable volume key for one task.

        Sibling tasks of one pod instance that declare the SAME
        container path share one key, so the hdfs format-then-node
        choreography writes and reads one durable directory
        (reference: pods share their resource set's volumes).  A
        PERMANENT replace never reuses old keys — the replacement
        starts empty."""
        keys: Dict[str, str] = {}
        if not task_spec.volumes:
            return keys
        existing: Dict[str, str] = dict(claimed_now or {})
        if requirement.recovery_type is not RecoveryType.PERMANENT:
            for sibling in pod.tasks:
                full = task_full_name(pod.type, index, sibling.name)
                for res in self._ledger.for_task(full):
                    for path, key in (res.volumes or {}).items():
                        existing.setdefault(path, key)
        for v in task_spec.volumes:
            keys[v.container_path] = existing.get(
                v.container_path, uuid.uuid4().hex
            )
            existing[v.container_path] = keys[v.container_path]
        if claimed_now is not None:
            claimed_now.update(keys)
        return keys

    def _build_task_info(
        self,
        requirement: PodInstanceRequirement,
        task_spec: TaskSpec,
        index: int,
        host,
        reservations: List[Reservation],
        chips: List[str],
        coordinator: str,
        worker_id: int = 0,
        extra_env: Optional[Dict[str, str]] = None,
    ) -> TaskInfo:
        """Reference: PodInfoBuilder (offer/evaluate/PodInfoBuilder.java,
        831 LoC) — command, env, readiness label, discovery assembly."""
        pod = requirement.pod
        full = task_full_name(pod.type, index, task_spec.name)
        env = dict(task_spec.env)
        env.update(extra_env or {})
        # parameterized-plan env (PodInstanceRequirement.env_overrides)
        # beats the spec but never the system contract vars below
        env.update(requirement.env_overrides)
        env[ENV_POD_INSTANCE_INDEX] = str(index)
        env[ENV_TASK_NAME] = full
        env[ENV_FRAMEWORK_NAME] = self._service_name
        if pod.tpu is not None:
            env[ENV_TPU_WORKER_ID] = str(worker_id)
            # a gang's worker count is the GANG size, even when this
            # evaluation covers a subset (per-index relaunch step)
            env[ENV_TPU_WORKER_COUNT] = str(
                pod.count if pod.gang else len(requirement.instances)
            )
            # the mesh slice of the contract comes from the spec
            # itself (TpuSpec.mesh_env) — the same dict the static
            # sharding analyzer evaluates, so launch and analysis
            # cannot drift.  Claim-time slice vars (extra_env) agree
            # by construction when both set TPU_NUM_SLICES.
            env.update(pod.tpu.mesh_env())
            if chips:
                # callers pass THIS host's chips (claim consumes per
                # host; reuse gathers per instance); ';'-separated
                # because chip ids carry grid commas ("pod-0/2,3")
                env[ENV_TPU_CHIP_IDS] = ";".join(chips)
                bx, by = host.chip_block
                if bx and by and len(chips) == bx * by:
                    # bounds describe the task's visible chip grid:
                    # emitted only for full-host assignments (a partial
                    # allocation has no rectangular contract to claim,
                    # and a chip-less sidecar must get NEITHER var)
                    env[ENV_TPU_HOST_BOUNDS] = f"{bx},{by},1"
            if coordinator:
                env[ENV_COORDINATOR_ADDRESS] = coordinator
        labels = {
            Label.TARGET_CONFIG: self._target_config_id,
            Label.HOSTNAME: host.hostname,
            Label.ZONE: host.zone,
            Label.REGION: host.region,
            Label.GOAL_STATE: task_spec.goal.value,
        }
        if pod.networks:
            # virtual network membership (reference: CNI networks on
            # the ContainerInfo): recorded for the agent's container
            # runtime and surfaced to the task
            labels[Label.NETWORKS] = ",".join(pod.networks)
            env["TASK_NETWORKS"] = ",".join(pod.networks)
        if pod.share_pid_namespace:
            labels[Label.SHARE_PID_NAMESPACE] = "true"
        # pod pause: a PAUSED goal override swaps the real command for
        # an idle one, so the task occupies its reservations without
        # doing work (reference: GoalStateOverride.PAUSED launched with
        # a sleep override cmd, PodQueries.java:183-203)
        command = task_spec.cmd
        override, _progress = self._state_store.fetch_goal_override(full)
        if override is GoalStateOverride.PAUSED:
            command = PAUSE_COMMAND
            labels[Label.GOAL_STATE_OVERRIDE] = override.value
        volumes: Dict[str, str] = {}
        for r in reservations:
            volumes.update(r.volumes or {})
        return TaskInfo(
            name=full,
            task_id=new_task_id(full),
            agent_id=host.host_id,
            pod_type=pod.type,
            pod_index=index,
            command=command,
            env=env,
            resource_ids=[r.reservation_id for r in reservations],
            tpu_chip_ids=list(chips),
            volume_ids=[r.volume_id for r in reservations if r.volume_id],
            volumes=volumes,
            labels=labels,
        )


def _coordinator_address(host, port) -> str:
    """The jax.distributed rendezvous point workers DIAL — it must be
    a reachable address, so the topology's ``hostname`` (the DCN
    address of the host) wins over the logical host_id."""
    return f"{host.hostname or host.host_id}:{port}"


def _task_disk_mb(task_spec, seen_paths: set) -> int:
    """Disk demand of one task within a pod instance.  A volume path
    SHARED by sibling tasks (pod-level volumes are merged into every
    task's spec) is one durable directory — only the first sibling
    pays its size, or a 2-task pod would demand twice the disk the
    instance actually uses."""
    disk = task_spec.resources.disk_mb
    for v in task_spec.volumes:
        if v.container_path not in seen_paths:
            seen_paths.add(v.container_path)
            disk += v.size_mb
    return disk


def _pod_scalar_needs(pod: PodSpec, tasks_to_launch: List[str]) -> Tuple[float, int, int]:
    cpus, mem, disk = 0.0, 0, 0
    seen_paths: set = set()
    for name in tasks_to_launch:
        spec = pod.task(name)
        cpus += spec.resources.cpus
        mem += spec.resources.memory_mb
        disk += _task_disk_mb(spec, seen_paths)
    return cpus, mem, disk
