"""Worker-side step telemetry: append-only JSONL in the sandbox.

The scheduler's flight recorder sees the control plane; the worker's
pjit step loop is invisible to it.  ``StepLog`` closes that gap from
the task side: each training/serving step appends one JSON line
(step index, wall seconds, tokens, seconds blocked waiting for the
gang before the step's first collective) to ``steplog.jsonl`` in the
task sandbox.  The agent's sandbox plumbing (``LocalProcessAgent.
steplog_of``) surfaces the file and the scheduler's ``/v1/debug/trace``
exporters merge it into the same timeline — per-host step lanes make
gang skew directly visible (host 3's ``blocked_s`` IS the skew the
other hosts imposed on it).

Telemetry must never take a worker down: write failures are counted
(``errors``) and otherwise ignored.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

STEPLOG_NAME = "steplog.jsonl"


class StepLog:
    """Appends one JSON record per step; flushes per record so a gang
    worker killed mid-run leaves a readable log."""

    def __init__(self, path: Optional[str] = None):
        # the scheduler's env contract puts every task in a sandbox
        # ($SANDBOX, agent/local.py); outside one, log to cwd
        self.path = path or os.path.join(
            os.environ.get("SANDBOX", "."), STEPLOG_NAME
        )
        self.errors = 0
        self._fh = None

    def record(self, step: int, **fields) -> None:
        entry = {"step": int(step), "t": time.time()}
        entry.update(fields)
        try:
            if self._fh is None:
                self._fh = open(self.path, "a", encoding="utf-8")
            self._fh.write(json.dumps(entry) + "\n")
            self._fh.flush()
        except (OSError, ValueError, TypeError):
            # telemetry is best-effort: a full disk or closed handle
            # must not kill the training step that produced the record
            self.errors += 1

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                self.errors += 1
            self._fh = None


def read_steplog(path: str) -> List[dict]:
    """Parse a steplog file; malformed/truncated lines (a worker killed
    mid-write) are skipped, valid records around them survive."""
    out: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    continue
                if isinstance(record, dict):
                    out.append(record)
    except OSError:
        return []
    return out
