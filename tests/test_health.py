"""The fleet health plane: journal, detectors, monitor, end to end.

The acceptance scenario (ISSUE 10): a seeded slow host in a 4-host
gang deploy produces a straggler alert in the durable event journal
and a suspect-host score at GET /v1/debug/health; the suspect host is
demoted to the END of placement scan order (superset-sound — it still
places when it is the only fit); and the journal survives a scheduler
failover, replayed under the HA fenced store with its sequence
numbers continuing where the deposed leader stopped.
"""

import pytest

from dcos_commons_tpu.ha.election import FencedPersister, LeaderLease
from dcos_commons_tpu.health import (
    EventJournal,
    LeaseChurnWatcher,
    ServingSloWatcher,
    StatePropertyBackend,
    StragglerDetector,
    median_ratio_scores,
)
from dcos_commons_tpu.http.api import SchedulerApi
from dcos_commons_tpu.offer.inventory import (
    SliceInventory,
    TpuHost,
    make_test_fleet,
)
from dcos_commons_tpu.state.state_store import StateStore
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    SendTaskRunning,
    ServiceTestRunner,
)

@pytest.fixture(scope="module", autouse=True)
def _racecheck_probes():
    """Dynamic race probes (SDKLINT_RACECHECK=1): the monitor's
    background telemetry collector publishes snapshots the scoring
    thread consumes — watch HealthMonitor's shared-write set so any
    unordered publish/consume pair fails the run.  No-op in the fast
    tier."""
    from dcos_commons_tpu.health.monitor import HealthMonitor

    from conftest import racecheck_watch_guard

    yield from racecheck_watch_guard(HealthMonitor)


GANG_YAML = """
name: jax
pods:
  trainer:
    count: 4
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
    tasks:
      worker:
        goal: RUNNING
        cmd: "python train.py"
        cpus: 2.0
        memory: 4096
"""

WEB_YAML = """
name: web
pods:
  app:
    count: 1
    tasks:
      srv:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
"""


# -- journal ----------------------------------------------------------


def test_journal_append_bound_and_query():
    journal = EventJournal(backend=None, capacity=4)
    for i in range(6):
        journal.append("operator", verb=f"v{i}")
    events = journal.events()
    # capacity-bounded drop-oldest, monotonic seq preserved
    assert [e["seq"] for e in events] == [3, 4, 5, 6]
    assert journal.last_seq == 6
    assert [e["verb"] for e in journal.events(since=5)] == ["v5"]
    journal.append("alert", detector="slo")
    assert [e["kind"] for e in journal.events(kinds=("alert",))] == ["alert"]
    assert len(journal.events(limit=2)) == 2
    # no backend: flush is a no-op, never an error
    assert journal.flush() is False
    assert journal.describe()["events"] == 4


def test_journal_disabled_is_inert():
    journal = EventJournal(backend=None, capacity=0)
    assert journal.append("operator", verb="x") == {}
    assert journal.events() == []
    assert journal.last_seq == 0
    assert journal.flush() is False
    assert not journal.enabled


def test_journal_persists_and_reloads():
    store = StateStore(MemPersister())
    journal = EventJournal(StatePropertyBackend(store))
    journal.append("operator", verb="interrupt", plan="deploy")
    journal.append("plan", step="node-0")
    assert journal.flush() is True
    assert journal.flush() is False  # clean: no redundant write

    reloaded = EventJournal(StatePropertyBackend(store))
    events = reloaded.events()
    assert [e["kind"] for e in events] == ["operator", "plan"]
    assert reloaded.last_seq == 2
    # seq continues across incarnations — operator cursors survive
    event = reloaded.append("operator", verb="proceed")
    assert event["seq"] == 3


def test_journal_corrupt_or_missing_record_starts_empty():
    store = StateStore(MemPersister())
    store.store_property("health-journal", b"{not json")
    journal = EventJournal(StatePropertyBackend(store))
    assert journal.events() == []
    assert journal.append("operator", verb="x")["seq"] == 1


def test_journal_survives_failover_under_the_fenced_store():
    """The acceptance criterion's durability half: leader A journals
    through the fenced store, is deposed, and standby B replays the
    journal and continues the sequence; A's post-deposition flush is
    REJECTED by the fence (counted, not raced in) and never clobbers
    B's events."""
    mem = MemPersister()
    clock = [1000.0]
    lease_a = LeaderLease(mem, "svc", "sched-a", ttl_s=10.0,
                          clock=lambda: clock[0])
    assert lease_a.try_acquire()
    journal_a = EventJournal(StatePropertyBackend(
        StateStore(FencedPersister(mem, lease_a))
    ))
    journal_a.append("operator", verb="interrupt", plan="deploy")
    journal_a.append("alert", detector="straggler", host="h3")
    assert journal_a.flush()

    clock[0] += 11.0  # A stalls past its TTL; B takes over
    lease_b = LeaderLease(mem, "svc", "sched-b", ttl_s=10.0,
                          clock=lambda: clock[0])
    assert lease_b.try_acquire()
    journal_b = EventJournal(StatePropertyBackend(
        StateStore(FencedPersister(mem, lease_b))
    ))
    replayed = journal_b.events()
    assert [e["kind"] for e in replayed] == ["operator", "alert"]
    assert journal_b.append("election", event="promote")["seq"] == 3
    assert journal_b.flush()

    # the deposed leader's flush bounces off the fence
    journal_a.append("operator", verb="zombie-write")
    assert journal_a.flush() is False
    assert journal_a.write_errors == 1
    # ...and the store still carries B's journal, zombie-free
    final = EventJournal(StatePropertyBackend(StateStore(mem)))
    assert [e["seq"] for e in final.events()] == [1, 2, 3]
    assert not any(
        e.get("verb") == "zombie-write" for e in final.events()
    )


# -- detector units ---------------------------------------------------


def test_median_ratio_scorer_gates():
    # under 3 qualifying hosts: no scores (the fleet median would BE
    # the outlier)
    assert median_ratio_scores({"a": [1.0] * 3, "b": [9.0] * 3}) == {}
    # hosts below min_samples are skipped, not scored off one step
    scores = median_ratio_scores({
        "a": [1.0] * 3, "b": [1.0] * 3, "c": [1.0] * 3, "fresh": [9.0],
    })
    assert "fresh" not in scores and len(scores) == 3


def test_straggler_detector_alerts_once_and_clears():
    detector = StragglerDetector(threshold=2.0)

    def logs(slow_own):
        fleet = {}
        for i in range(3):
            fleet[f"h{i}"] = [
                {"wall_s": 1.0, "blocked_s": 0.9} for _ in range(4)
            ]
        fleet["h-slow"] = [
            {"wall_s": 1.0, "blocked_s": 1.0 - slow_own}
            for _ in range(4)
        ]
        return fleet

    events = detector.observe(logs(slow_own=1.0))  # 10x the fleet
    assert [e["host"] for e in events] == ["h-slow"]
    assert detector.suspects and "h-slow" in detector.suspects
    # steady breach: no repeat alert (episodes, not per-cycle spam)
    assert detector.observe(logs(slow_own=1.0)) == []
    # recovery: one clear event, suspect mark dropped
    cleared = detector.observe(logs(slow_own=0.1))
    assert len(cleared) == 1 and cleared[0].get("cleared")
    assert detector.suspects == {}


def test_straggler_window_applies_per_colocated_task_series():
    """Regression: a host running several tasks hands the detector one
    series PER TASK — with a flat pooled list, whichever task was
    appended last would evict the other's records from the trailing
    window and detection would depend on task iteration order."""
    detector = StragglerDetector(threshold=2.0, window=8)
    slow = [{"wall_s": 1.0, "blocked_s": 0.0}] * 8   # straggling task
    fast = [{"wall_s": 1.0, "blocked_s": 0.9}] * 8
    fleet = {
        # the colocated host lists its FAST task last: a flat pool
        # trimmed to window=8 would see only the fast series
        "h-shared": [slow, fast],
        "h1": [fast], "h2": [fast], "h3": [fast],
    }
    events = detector.observe(fleet)
    assert [e["host"] for e in events] == ["h-shared"], events


def test_journal_racing_flushes_persist_in_snapshot_order():
    """Regression: flush snapshots the payload then stores OUTSIDE the
    append lock — two racing flushes (cycle thread vs an operator
    verb's inline flush) must still land newest-last, or a crash in
    the window would lose the newer events and re-mint their seqs."""
    import threading

    stored = []
    release = threading.Event()

    class SlowBackend:
        def load(self):
            return None

        def store(self, raw):
            import json as _json

            stored.append(_json.loads(raw.decode())["seq"])
            if len(stored) == 1:
                release.wait(5.0)  # first store stalls mid-write

    journal = EventJournal(SlowBackend())
    journal.append("operator", verb="first")
    t = threading.Thread(target=journal.flush)
    t.start()
    while not stored:  # first flush is inside store()
        pass
    journal.append("operator", verb="second")  # the operator verb
    done = []
    t2 = threading.Thread(
        target=lambda: done.append(journal.flush())
    )
    t2.start()
    release.set()
    t.join(5.0)
    t2.join(5.0)
    # the racing flush waited for the stalled one, then persisted the
    # NEWER snapshot last — the store's final state carries seq 2
    assert stored == [1, 2], stored
    assert done == [True]


def test_straggler_silent_host_keeps_its_mark():
    detector = StragglerDetector(threshold=2.0)
    fleet = {
        f"h{i}": [{"wall_s": 1.0, "blocked_s": 0.9}] * 4 for i in range(3)
    }
    fleet["h-slow"] = [{"wall_s": 1.0, "blocked_s": 0.0}] * 4
    assert detector.observe(fleet)
    # the slow host stops reporting entirely: silence is not health
    del fleet["h-slow"]
    assert detector.observe(fleet) == []
    assert "h-slow" in detector.suspects


def test_slo_watcher_env_thresholds_and_episodes():
    watcher = ServingSloWatcher(ttft_p95_slo_s=1.0)
    stats = {"web-0-srv": {"ttft_p95_s": 2.5, "queue_depth": 100}}
    events = watcher.observe(stats)
    # queue_depth unchecked (no default, no env): only the TTFT fires
    assert [e["signal"] for e in events] == ["ttft_p95_s"]
    # steady breach: silent; recovery: one clear
    assert watcher.observe(stats) == []
    ok = {"web-0-srv": {"ttft_p95_s": 0.3, "queue_depth": 100}}
    cleared = watcher.observe(ok)
    assert len(cleared) == 1 and cleared[0].get("cleared")
    # per-task env overrides the scheduler default (options.json
    # serving.*_slo knobs ride the task env)
    env = {"web-0-srv": {"SERVE_QUEUE_DEPTH_SLO": "8"}}
    events = watcher.observe(ok, env)
    assert [e["signal"] for e in events] == ["queue_depth"]
    # a still-breaching signal keeps the CURRENT magnitude visible
    # (an operator must see the runaway value, not the first blip)
    worse = {"web-0-srv": {"ttft_p95_s": 0.3, "queue_depth": 400}}
    assert watcher.observe(worse, env) == []  # no repeat alert
    assert watcher.breaches[("web-0-srv", "queue_depth")] == 400
    # ONE missed collection (dropped RPC, idle window) is not a
    # recovery: the episode survives, and the returning still-breaching
    # sample does NOT re-alert
    assert watcher.observe({}, {}) == []
    assert ("web-0-srv", "queue_depth") in watcher.breaches
    assert watcher.observe(worse, env) == []
    # a task absent for RETIRE_AFTER_MISSES straight collections is
    # retired: episodes dropped silently (nothing was measured)
    for _ in range(ServingSloWatcher.RETIRE_AFTER_MISSES):
        assert watcher.observe({}, {}) == []
    assert watcher.breaches == {}


def test_slo_watcher_discards_stale_snapshots_unscored():
    """ISSUE 12: a wedged pod keeps mirroring its last-good gauges —
    the watcher must not score them (neither alert nor silently clear
    an open episode); staleness rides the engine's stats_age_s stamp
    or the snapshot's wall write stamp."""
    watcher = ServingSloWatcher(ttft_p95_slo_s=1.0, stale_stats_s=10.0)
    breaching = {"web-0-srv": {"ttft_p95_s": 2.5, "stats_age_s": 0.0}}
    assert [e["signal"] for e in watcher.observe(breaching)] == \
        ["ttft_p95_s"]
    # the pod wedges: gauges FREEZE at breach values, age grows — the
    # snapshot is discarded, the episode survives as a missed sample
    stale = {"web-0-srv": {"ttft_p95_s": 2.5, "stats_age_s": 60.0}}
    assert watcher.observe(stale) == []
    assert ("web-0-srv", "ttft_p95_s") in watcher.breaches
    assert watcher.stale_discards == 1
    # a stale LOOKS-HEALTHY snapshot must not clear the episode either
    stale_ok = {"web-0-srv": {"ttft_p95_s": 0.1, "stats_age_s": 60.0}}
    assert watcher.observe(stale_ok) == []
    assert ("web-0-srv", "ttft_p95_s") in watcher.breaches
    # wall-stamp staleness: a mirror file that stopped being
    # rewritten (worker gone, file survives) discards the same way —
    # and as the RETIRE_AFTER_MISSES-th consecutive miss it retires
    # the episode unmeasured, exactly like an absent task
    assert ServingSloWatcher.RETIRE_AFTER_MISSES == 3
    old_file = {"web-0-srv": {"ttft_p95_s": 2.5, "t": 100.0}}
    assert watcher.observe(old_file, now=200.0) == []
    assert watcher.stale_discards == 3
    assert watcher.breaches == {}
    # a FRESH recovery still clears normally (gate off the hot path)
    events = watcher.observe(breaching)
    assert len(events) == 1 and not events[0].get("cleared")
    fresh_ok = {"web-0-srv": {"ttft_p95_s": 0.1, "stats_age_s": 0.0}}
    assert [e.get("cleared") for e in watcher.observe(fresh_ok)] == \
        [True]
    # stale_stats_s=0 disables the gate (deterministic callers)
    ungated = ServingSloWatcher(ttft_p95_slo_s=1.0, stale_stats_s=0)
    assert ungated.observe(stale)  # scored despite the age


def test_lease_churn_watcher_flags_flapping_not_failover():
    watcher = LeaseChurnWatcher(churn_n=3, window_s=100.0)
    # one routine failover: no alert
    assert watcher.observe(1, t=0.0) == []
    assert watcher.observe(2, t=10.0) == []
    # flapping: three changes inside the window
    assert watcher.observe(3, t=20.0) == []
    events = watcher.observe(4, t=30.0)
    assert len(events) == 1 and events[0]["detector"] == "lease-churn"
    # steady flapping: one alert per episode
    assert watcher.observe(5, t=40.0) == []
    # churn drops under the threshold: one clear event, re-armed
    cleared = watcher.observe(5, t=200.0)
    assert len(cleared) == 1 and cleared[0].get("cleared")
    assert watcher.observe(6, t=300.0) == []  # 1 change < churn_n


def test_lease_churn_sub_threshold_drip_does_not_suppress():
    """Regression: episode end is churn dropping BELOW churn_n, not
    the window emptying — a routine failover every ~250s keeps the
    window non-empty forever, and the old empty-window re-arm would
    have suppressed every future flapping episode."""
    watcher = LeaseChurnWatcher(churn_n=3, window_s=300.0)
    epoch, t = 1, 0.0
    watcher.observe(epoch, t=t)  # baseline
    for _ in range(3):
        epoch, t = epoch + 1, t + 10.0
        watcher.observe(epoch, t=t)
    assert watcher._alerted  # first episode fired
    # months of sub-threshold drip: one change per 250s, the window
    # never empties but churn stays below churn_n
    for _ in range(10):
        epoch, t = epoch + 1, t + 250.0
        events = watcher.observe(epoch, t=t)
        assert all(e.get("cleared") for e in events)
    # genuine flapping resumes: the alert MUST fire again
    fired = []
    for _ in range(3):
        epoch, t = epoch + 1, t + 10.0
        fired += watcher.observe(epoch, t=t)
    assert any(
        e["detector"] == "lease-churn" and not e.get("cleared")
        for e in fired
    ), fired


# -- the suspect-host soft placement signal ---------------------------


def hosts3():
    return [TpuHost(host_id=f"host-{i}") for i in range(3)]


def deploy_web(hosts, suspects=()):
    runner = ServiceTestRunner(WEB_YAML, hosts=hosts)
    runner.build()
    runner.inventory.set_suspect_hosts(set(suspects))
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("app-0-srv"),
        ExpectDeploymentComplete(),
    ])
    return runner.world.state_store.fetch_task("app-0-srv").agent_id


def test_suspect_host_sorts_last_in_placement():
    # healthy fleet: first-fit lands on host-0 (registration order)
    assert deploy_web(hosts3()) == "host-0"
    # suspect host-0: demoted to the back, host-1 wins the tie
    assert deploy_web(hosts3(), suspects={"host-0"}) == "host-1"
    # superset-sound: a suspect host still places when it is the only
    # host — demotion orders, never excludes
    assert deploy_web([TpuHost(host_id="only")],
                      suspects={"only"}) == "only"


def test_suspect_set_change_resyncs_ordinals_not_snapshots():
    inventory = SliceInventory(hosts3())
    view_gen_before = inventory.topology_generation
    inventory.set_suspect_hosts({"host-1"})
    # ordering is not a topology change: snapshot caches stay valid
    assert inventory.topology_generation == view_gen_before
    assert inventory.suspect_hosts() == {"host-1"}
    ordinals = inventory._ordinals()
    assert ordinals["host-1"] == 2  # demoted behind host-0/host-2
    assert ordinals["host-0"] == 0
    # unchanged set: no-op (ordering caches keep their stamps)
    cache_before = inventory._scan_hosts()
    inventory.set_suspect_hosts({"host-1"})
    assert inventory._scan_hosts() is cache_before


def test_lease_churn_survives_incarnations_via_journal_seed():
    """Regression: a LeaderLease's in-memory epoch is constant for
    its process's lifetime (losing the lease restarts the process),
    so flapping is only visible ACROSS incarnations.  The monitor
    seeds the watcher from the journaled election events — which
    replay after failover — and then watches the PERSISTED record's
    epoch, so the third incarnation of a flapping fleet alerts even
    though its own watcher never saw an epoch change."""
    from dcos_commons_tpu.health.monitor import HealthMonitor

    journal = EventJournal(backend=None)
    # three prior incarnations journaled their promotions (the first
    # seeds the watcher's baseline epoch)
    journal.append("election", event="election.promote", epoch=1,
                   t=990.0)
    journal.append("election", event="election.promote", epoch=2,
                   t=1000.0)
    journal.append("election", event="election.promote", epoch=3,
                   t=1010.0)

    class FakeLease:
        epoch = 4

        def state(self):
            return self

    class FakeMetrics:
        def incr(self, name, value=1):
            pass

        def gauge(self, name, fn):
            pass

        def sample_history(self, t=None):
            pass

    class FakeScheduler:
        metrics = FakeMetrics()
        agent = object()
        ha_state = type("HA", (), {"lease": FakeLease()})()

        class state_store:
            @staticmethod
            def fetch_tasks():
                return []

        inventory = None
        spec = None

    monitor = HealthMonitor(journal=journal, telemetry_interval_s=0)
    events = monitor.observe(FakeScheduler(), now=1020.0)
    churn = [e for e in events if e.get("detector") == "lease-churn"]
    assert len(churn) == 1 and churn[0]["changes"] >= 3, events
    assert monitor.observe_errors == 0


def test_telemetry_collection_runs_off_the_cycle_thread():
    """With a non-zero telemetry interval the fan-in runs on a
    background thread (one slow daemon must not stall run_cycle);
    detectors score the completed snapshot on a later cycle."""
    import time as _time

    runner = gang_world()
    world = runner.world
    scheduler = world.scheduler
    seed_steplogs(world)
    scheduler.health.telemetry_interval_s = 0.001
    deadline = _time.monotonic() + 10
    while _time.monotonic() < deadline and \
            not scheduler.health.straggler.suspects:
        scheduler.run_cycle()
        _time.sleep(0.01)
    assert scheduler.health.straggler.suspects
    assert world.inventory.suspect_hosts()


def test_suspect_sources_union_on_shared_inventory():
    """Regression: on a multi-service fleet every service's monitor
    pushes only ITS OWN stragglers into the ONE shared inventory — a
    service with no stragglers pushing set() must not clear a host
    another service demoted, and per-source no-op pushes must not
    churn the ordering caches every cycle."""
    inventory = SliceInventory(hosts3())
    inventory.set_suspect_hosts({"host-1"}, source="svc-a")
    assert inventory.suspect_hosts() == {"host-1"}
    inventory.set_suspect_hosts(set(), source="svc-b")  # B: all healthy
    assert inventory.suspect_hosts() == {"host-1"}  # A's demotion holds
    # steady-state alternation (A re-pushes, B re-pushes): no resort
    cache = inventory._scan_hosts()
    inventory.set_suspect_hosts({"host-1"}, source="svc-a")
    inventory.set_suspect_hosts(set(), source="svc-b")
    assert inventory._scan_hosts() is cache
    # the union grows and shrinks per contributor
    inventory.set_suspect_hosts({"host-2"}, source="svc-b")
    assert inventory.suspect_hosts() == {"host-1", "host-2"}
    inventory.set_suspect_hosts(set(), source="svc-a")
    assert inventory.suspect_hosts() == {"host-2"}
    inventory.set_suspect_hosts(set(), source="svc-b")
    assert inventory.suspect_hosts() == set()


# -- end to end: the acceptance scenario ------------------------------


def gang_world():
    runner = ServiceTestRunner(
        GANG_YAML,
        hosts=make_test_fleet(host_grid=(2, 2), chip_block=(2, 2)),
    )
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("trainer-0-worker"),
        SendTaskRunning("trainer-1-worker"),
        SendTaskRunning("trainer-2-worker"),
        SendTaskRunning("trainer-3-worker"),
        ExpectDeploymentComplete(),
    ])
    return runner


def seed_steplogs(world, slow_task="trainer-3-worker"):
    """Give the sim agent the sandbox-steplog surface the real agents
    expose, with one host doing the gang's compute slowly: the slow
    host shows own time ~1.0s (never waits), the healthy three show
    own time ~0.1s and 0.9s of barrier blocking — exactly the shape a
    real gang-skew steplog has."""
    def steplog_of(name, agent_id=None):
        if not name.startswith("trainer-"):
            return []
        own = 1.0 if name == slow_task else 0.1
        return [
            {"step": i, "t": 100.0 + i, "wall_s": 1.0,
             "blocked_s": round(1.0 - own, 3), "tokens": 4096}
            for i in range(8)
        ]

    world.agent.steplog_of = steplog_of


def test_gang_straggler_lands_in_journal_and_health():
    runner = gang_world()
    world = runner.world
    scheduler = world.scheduler
    seed_steplogs(world)
    slow_host = world.state_store.fetch_task("trainer-3-worker").agent_id
    # deterministic cadence for the test: no time throttles
    scheduler.health.telemetry_interval_s = 0
    scheduler.health.history_interval_s = 0
    scheduler.run_cycle()

    # the alert is IN the journal (and survives the ring-buffered
    # flight recorder's eviction horizon by construction)
    alerts = scheduler.journal.events(kinds=("alert",))
    assert any(
        e.get("detector") == "straggler" and e.get("host") == slow_host
        for e in alerts
    ), alerts

    # ...and visible at GET /v1/debug/health with its score
    api = SchedulerApi(scheduler)
    code, body = api.debug_health()
    assert code == 200 and body["enabled"]
    assert body["status"] == "warn"
    assert slow_host in body["suspect_hosts"]
    assert body["suspect_hosts"][slow_host] >= 2.0
    assert body["straggler"]["scores"][slow_host] >= 2.0
    assert any(
        e.get("host") == slow_host for e in body["alerts_recent"]
    )

    # the soft placement signal reached the inventory
    assert world.inventory.suspect_hosts() == {slow_host}

    # metric history: the sampled rings answer "what was it recently"
    code, body = api.debug_health(metric="health.suspect_hosts")
    assert code == 200
    assert body["history"]["metric"] == "health.suspect_hosts"
    assert body["history"]["samples"]

    # /v1/debug/events serves the journal with a working cursor
    code, body = api.debug_events()
    assert code == 200 and body["seq"] >= 1
    cursor = body["seq"]
    assert api.debug_events(since=str(cursor))[1]["events"] == []
    assert api.debug_events(since="bogus")[0] == 400

    # recovery: the straggler gets healthy again -> clear event, mark
    # dropped, placement order restored
    seed_steplogs(world, slow_task="none")
    scheduler.run_cycle()
    assert world.inventory.suspect_hosts() == set()
    assert any(
        e.get("cleared") for e in
        scheduler.journal.events(kinds=("alert",))
    )


def test_journal_survives_scheduler_restart_in_the_sim():
    """Failover in the sim harness: a second scheduler built over the
    SAME persister (the ServiceTestRunner restart idiom) replays the
    journal — operator verbs and alerts from the first incarnation
    are visible to the second, and new events continue the seq."""
    runner = gang_world()
    world = runner.world
    scheduler = world.scheduler
    seed_steplogs(world)
    scheduler.health.telemetry_interval_s = 0
    scheduler.run_cycle()
    api = SchedulerApi(scheduler)
    assert api.plan_interrupt("deploy")[0] == 200
    seq_before = scheduler.journal.last_seq
    assert seq_before > 0
    kinds_before = {e["kind"] for e in scheduler.journal.events()}
    assert {"plan", "operator", "alert"} <= kinds_before

    second = ServiceTestRunner(
        GANG_YAML,
        hosts=make_test_fleet(host_grid=(2, 2), chip_block=(2, 2)),
        persister=runner.persister,
    )
    restarted = second.build().scheduler
    events = restarted.journal.events()
    assert {e["kind"] for e in events} >= {"operator", "alert"}
    assert restarted.journal.last_seq >= seq_before
    assert restarted.journal.append("operator", verb="post-failover")[
        "seq"
    ] > seq_before


def test_health_disabled_scheduler_reports_disabled():
    from dcos_commons_tpu.scheduler.config import SchedulerConfig

    runner = ServiceTestRunner(
        WEB_YAML,
        scheduler_config=SchedulerConfig(
            backoff_enabled=False, revive_capacity=1_000_000,
            health_enabled=False,
        ),
    )
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("app-0-srv"),
        ExpectDeploymentComplete(),
    ])
    scheduler = runner.world.scheduler
    assert not scheduler.journal.enabled
    assert scheduler.journal.events() == []  # transitions not recorded
    api = SchedulerApi(scheduler)
    assert api.debug_health()[1] == {"enabled": False}


def test_observe_never_kills_the_cycle():
    runner = gang_world()
    scheduler = runner.world.scheduler
    scheduler.health.telemetry_interval_s = 0

    def broken(_name, agent_id=None):
        raise RuntimeError("sandbox exploded")

    runner.world.agent.steplog_of = broken
    scheduler.run_cycle()  # must not raise
    assert scheduler.health.observe_errors >= 1
    assert scheduler.metrics.counters()["health.observe_errors"] >= 1
