"""Uninstall: full service teardown as a plan.

Reference: scheduler/uninstall/ — UninstallScheduler (291 LoC),
UninstallPlanFactory (phases: kill tasks -> unreserve resources ->
deregister), UninstallRecorder write-ahead of dereservations,
skeleton scheduler when already uninstalled
(framework/FrameworkRunner.java:99-115,214-238).
"""

from dcos_commons_tpu.uninstall.scheduler import (
    UNINSTALL_PLAN_NAME,
    UninstallPlanFactory,
    UninstallScheduler,
)

__all__ = [
    "UNINSTALL_PLAN_NAME",
    "UninstallPlanFactory",
    "UninstallScheduler",
]
