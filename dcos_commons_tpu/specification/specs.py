"""Typed service specification object model.

Reference: sdk/scheduler/.../specification/ — ServiceSpec, PodSpec,
TaskSpec, ResourceSpec/PortSpec/VolumeSpec, GoalState.java,
ReplacementFailurePolicy (DefaultServiceSpec.java).  Specs are pure
data: JSON-serializable, comparable, stored in the ConfigStore and
diffed on config update.

TPU-first: ResourceSpec has no ``gpus`` scalar (north-star requirement
in BASELINE.md); pods request TPU via :class:`TpuSpec`, whose topology
string ("2x2", "4x4", "2x2x4") names an ICI sub-slice shape.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


class SpecError(Exception):
    pass


class GoalState(enum.Enum):
    """Reference: specification/GoalState.java.

    RUNNING: stay up forever (restart on exit).
    FINISH: run to successful completion, re-run on config change.
    ONCE: run to successful completion exactly once ever.
    """

    RUNNING = "RUNNING"
    FINISH = "FINISH"
    ONCE = "ONCE"


@dataclass(frozen=True)
class TpuSpec:
    """A pod's TPU requirement — the heart of the rebuild.

    Replaces the reference's ``gpus:`` Mesos scalar resources.  A pod
    instance runs on one host and consumes ``chips_per_host`` chips
    there; ``topology`` names the ICI shape of the whole multi-host
    slice the pod's instances must form (e.g. "4x4" = 16 chips over 4
    hosts of 4).  The placement engine uses it to require torus
    adjacency between instances (SURVEY.md section 7 delta b).
    """

    generation: str = "v5e"          # v4 / v5e / v5p / v6e ...
    chips_per_host: int = 4
    topology: str = ""               # "" = no multi-host shape required
    # multi-slice gangs: the pod spans `slices` ICI slices, each
    # forming one `topology` sub-slice; slices talk over DCN (data
    # parallel across slices is the standard recipe — the dcn mesh
    # axis).  count must equal slices * hosts-per-slice.
    slices: int = 1
    # elastic re-slicing (ISSUE 13): a DP-sharded trainer gang that
    # cannot re-place at full size after preemption may restart on a
    # smaller mesh (a divisor of the gang size, never below
    # ``min_hosts``) instead of waiting for capacity that is not
    # coming back.  Opt-in — shrinking changes the effective batch
    # layout and the operator must have designed for it.
    elastic: bool = False
    min_hosts: int = 1

    def topology_dims(self) -> Tuple[int, ...]:
        if not self.topology:
            return ()
        try:
            dims = tuple(int(d) for d in self.topology.lower().split("x"))
        except ValueError:
            raise SpecError(f"bad topology {self.topology!r}")
        if not dims or any(d <= 0 for d in dims):
            raise SpecError(f"bad topology {self.topology!r}")
        return dims

    @property
    def total_chips(self) -> int:
        dims = self.topology_dims()
        total = 1
        for d in dims:
            total *= d
        return total if dims else self.chips_per_host

    def mesh_env(self) -> Dict[str, str]:
        """The mesh slice of the scheduler's task env contract.

        One source of truth consumed by BOTH the launch path
        (offer/evaluate.py task-env assembly) and the static sharding
        analyzer (analysis/shardcheck.py): the worker derives its mesh
        from exactly these variables (parallel/mesh.py ``derive``), so
        an analyzer that assembled them independently could approve a
        mesh the launched task never builds.  Multi-slice pods grow
        the dcn axis here (TPU_NUM_SLICES widens the declared shape)
        plus the static half of the per-slice coordinator addressing
        (TPU_HOSTS_PER_SLICE — slice-major worker numbering means
        ``worker_id // hosts_per_slice`` is the slice index); which
        HOST anchors each slice (TPU_SLICE_COORDS) and which slice a
        worker landed on (TPU_SLICE_INDEX) are claim-time facts and
        stay with the claim path (offer/evaluate.py).
        """
        env = {
            "TPU_CHIPS_PER_HOST": str(self.chips_per_host),
            "TPU_GENERATION": self.generation,
        }
        if self.topology:
            env["TPU_TOPOLOGY"] = self.topology
        if self.slices > 1:
            env["TPU_NUM_SLICES"] = str(self.slices)
            env["TPU_HOSTS_PER_SLICE"] = str(
                max(1, self.total_chips // max(1, self.chips_per_host))
            )
        return env


@dataclass(frozen=True)
class PortSpec:
    """Reference: specification/PortSpec.java + NamedVIPSpec.java."""

    name: str
    port: int = 0                    # 0 = dynamically assigned
    vip: str = ""                    # "name:port" service VIP
    env_key: str = ""                # env var to expose the port under
    # endpoints list the port the worker ACTUALLY bound (advertised
    # via its servestats snapshot) instead of the reserved one — for
    # HTTP servers that fall back to an ephemeral bind when the
    # assigned port is taken on a shared machine (ISSUE 12)
    advertise: bool = False


@dataclass(frozen=True)
class VolumeSpec:
    """Reference: specification/VolumeSpec.java (ROOT/MOUNT/profile)."""

    container_path: str
    size_mb: int
    type: str = "ROOT"               # ROOT (shared disk) | MOUNT (dedicated)
    profiles: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ResourceSpec:
    """Per-task scalar resources.  No ``gpus`` — TPU is per-pod TpuSpec."""

    cpus: float = 0.1
    memory_mb: int = 32
    disk_mb: int = 0
    ports: Tuple[PortSpec, ...] = ()


@dataclass(frozen=True)
class HealthCheckSpec:
    """Reference: specification/HealthCheckSpec.java."""

    cmd: str
    interval_s: float = 30.0
    grace_period_s: float = 30.0
    timeout_s: float = 20.0
    max_consecutive_failures: int = 3
    delay_s: float = 0.0


@dataclass(frozen=True)
class ReadinessCheckSpec:
    """Reference: specification/ReadinessCheckSpec.java — gates a step's
    STARTED->COMPLETE transition (stored as a task label in the
    reference, PodInfoBuilder.java:511-526)."""

    cmd: str
    interval_s: float = 5.0
    timeout_s: float = 10.0


@dataclass(frozen=True)
class SecretSpec:
    """One secret ref (reference: specification/DefaultSecretSpec +
    RawSecret {secret, env-key, file}).  ``secret`` is the provider
    path; the value lands as a 0600 sandbox ``file`` and/or an
    ``env_key`` env var.  With neither, the env key is derived from
    the ref path (reference behavior for bare refs)."""

    secret: str
    env_key: str = ""
    file: str = ""

    def effective_env_key(self) -> str:
        if self.env_key or self.file:
            return self.env_key
        import re as _re

        return _re.sub(r"[^A-Z0-9]", "_", self.secret.upper())


# "unlimited" sentinel for rlimit values (reference:
# RLimitSpec.RLIMIT_INFINITY)
RLIMIT_INFINITY = -1


def valid_rlimit_names() -> frozenset:
    """The rlimits this host can enforce (``man setrlimit(2)``).

    Derived from the stdlib ``resource`` module so the set matches
    what the agent can actually apply; a static POSIX core is the
    fallback for exotic platforms."""
    try:
        import resource

        return frozenset(
            n for n in dir(resource) if n.startswith("RLIMIT_")
        )
    except ImportError:  # pragma: no cover — non-POSIX dev box
        return frozenset({
            "RLIMIT_AS", "RLIMIT_CORE", "RLIMIT_CPU", "RLIMIT_DATA",
            "RLIMIT_FSIZE", "RLIMIT_MEMLOCK", "RLIMIT_NOFILE",
            "RLIMIT_NPROC", "RLIMIT_RSS", "RLIMIT_STACK",
        })


@dataclass(frozen=True)
class RLimitSpec:
    """One per-task resource limit (reference:
    specification/RLimitSpec.java — name plus optional soft/hard,
    both-or-neither, soft <= hard; enforced at task exec time by the
    agent via ``setrlimit(2)``).

    On a shared TPU-VM host this is a real isolation feature: an fd
    or nproc leak in one service's task must not take out the
    co-scheduled services on the same host.  ``-1`` means unlimited
    (RLIMIT_INFINITY)."""

    name: str
    soft: int = RLIMIT_INFINITY
    hard: int = RLIMIT_INFINITY

    def __post_init__(self) -> None:
        if self.name not in valid_rlimit_names():
            raise SpecError(
                f"{self.name!r} is not a valid rlimit; expected one of "
                f"{sorted(valid_rlimit_names())} (man setrlimit(2))"
            )
        soft_set = self.soft != RLIMIT_INFINITY
        hard_set = self.hard != RLIMIT_INFINITY
        if soft_set != hard_set:
            raise SpecError(
                f"rlimit {self.name}: soft and hard limits must be "
                "set together (or both left unlimited)"
            )
        if self.soft < RLIMIT_INFINITY or self.hard < RLIMIT_INFINITY:
            raise SpecError(
                f"rlimit {self.name}: limits must be >= 0 "
                f"(or -1 for unlimited)"
            )
        if soft_set and self.soft > self.hard:
            raise SpecError(
                f"rlimit {self.name}: soft limit {self.soft} exceeds "
                f"hard limit {self.hard}"
            )


@dataclass(frozen=True)
class TransportEncryptionSpec:
    """Reference: specification/TransportEncryptionSpec (tls.yml
    `transport-encryption:` entries).  ``type`` TLS emits
    <name>.crt/<name>.key/<name>.ca PEMs into the sandbox."""

    name: str
    type: str = "TLS"


@dataclass(frozen=True)
class UriSpec:
    """A sandbox artifact the agent downloads before launch.

    Reference: the ``uris:`` list in service YAML
    (frameworks/helloworld/src/main/dist/uri.yml:8,37), mapped at
    specification/yaml/YAMLToInternalMappers.java:397 and fetched by
    the Mesos fetcher before the task command runs.  TPU additions
    over the reference: ``sha256`` pins the artifact (a corpus or
    tokenizer staged per host must be the bytes the operator vetted,
    and pinning enables the per-host cache), ``extract`` unpacks
    tar archives, ``executable`` sets +x.
    """

    uri: str
    dest: str = ""            # sandbox-relative; default: URI basename
    sha256: str = ""          # hex digest pin; also the cache key
    extract: bool = False     # tar/tgz: unpack into dirname(dest)
    executable: bool = False

    def effective_dest(self) -> str:
        if self.dest:
            return self.dest
        name = self.uri.rstrip("/").rsplit("/", 1)[-1].split("?")[0]
        if not name:
            raise SpecError(f"cannot derive a dest from uri {self.uri!r}")
        return name


@dataclass(frozen=True)
class TaskSpec:
    """Reference: specification/TaskSpec.java."""

    name: str
    goal: GoalState = GoalState.RUNNING
    cmd: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    resources: ResourceSpec = field(default_factory=ResourceSpec)
    volumes: Tuple[VolumeSpec, ...] = ()
    health_check: Optional[HealthCheckSpec] = None
    readiness_check: Optional[ReadinessCheckSpec] = None
    config_templates: Tuple[Tuple[str, str], ...] = ()   # (template, dest)
    # default matches the Mesos KillPolicy default grace (3s);
    # an explicit 0 in YAML means kill immediately
    kill_grace_period_s: float = 3.0
    essential: bool = True           # reference: TaskSpec.isEssential
    transport_encryption: Tuple[TransportEncryptionSpec, ...] = ()
    # sandbox artifacts fetched before launch (pod-level uris merge in
    # here, task-level declarations winning on dest clashes)
    uris: Tuple[UriSpec, ...] = ()
    # custom discovery name prefix (reference: discovery.yml `discovery:
    # prefix:` -> DiscoveryInfo; tasks advertise as <prefix>-<index>
    # instead of <pod>-<index>-<task> in the endpoint/DNS listing)
    discovery_prefix: str = ""

    def __post_init__(self) -> None:
        if isinstance(self.goal, str):
            object.__setattr__(self, "goal", GoalState(self.goal))


@dataclass(frozen=True)
class PodSpec:
    """Reference: specification/PodSpec.java.

    ``gang=True`` is the TPU-first addition: all ``count`` instances
    form one scheduling unit (a pjit mesh), deployed and recovered
    together, with rolling updates at pod granularity.
    """

    type: str
    count: int = 1
    tasks: Tuple[TaskSpec, ...] = ()
    tpu: Optional[TpuSpec] = None
    gang: bool = False
    image: str = ""
    networks: Tuple[str, ...] = ()
    placement: str = ""              # placement DSL (offer/placement.py)
    volumes: Tuple[VolumeSpec, ...] = ()   # pod-level shared volumes
    uris: Tuple[UriSpec, ...] = ()   # pod-level artifacts (all tasks)
    pre_reserved_role: str = ""
    allow_decommission: bool = False
    share_pid_namespace: bool = False
    # pod-level secret refs applied to every task of the pod
    # (reference: RawPod secrets block, secrets.yml)
    secrets: Tuple[SecretSpec, ...] = ()
    # per-task resource limits applied to every task of the pod at
    # exec time (reference: RawPod rlimits block, svc.yml:9-13)
    rlimits: Tuple[RLimitSpec, ...] = ()

    def task(self, name: str) -> TaskSpec:
        for t in self.tasks:
            if t.name == name:
                return t
        raise SpecError(f"no task {name!r} in pod {self.type!r}")


@dataclass(frozen=True)
class ReplacementFailurePolicy:
    """Reference: DefaultServiceSpec ReplacementFailurePolicy — governs
    TRANSIENT->PERMANENT escalation (TimedFailureMonitor)."""

    permanent_failure_timeout_s: float = 1200.0
    min_replace_delay_s: float = 600.0


@dataclass(frozen=True)
class ServiceSpec:
    """Reference: specification/ServiceSpec.java."""

    name: str
    role: str = ""
    user: str = ""
    region: str = ""
    zone: str = ""
    web_url: str = ""
    # DNS suffix tasks advertise under in /v1/endpoints (reference:
    # custom_tld.yml + bootstrap's custom-TLD resolution; wiring the
    # names into a resolver is the fleet operator's job)
    service_tld: str = "fleet.local"
    pods: Tuple[PodSpec, ...] = ()
    replacement_failure_policy: Optional[ReplacementFailurePolicy] = None
    # raw plans section from YAML; compiled by plan.PlanGenerator
    plans: Dict[str, Any] = field(default_factory=dict)

    def pod(self, pod_type: str) -> PodSpec:
        for p in self.pods:
            if p.type == pod_type:
                return p
        raise SpecError(f"no pod {pod_type!r} in service {self.name!r}")

    # -- serde (ConfigStore stores dicts; reference stores Jackson JSON
    #    of DefaultServiceSpec) --------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        from dcos_commons_tpu.common import _to_jsonable

        return _to_jsonable(self)

    @staticmethod
    def from_dict(data: Dict[str, Any]) -> "ServiceSpec":
        return _decode_service(data)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, ServiceSpec):
            return NotImplemented
        return self.to_dict() == other.to_dict()

    def __hash__(self) -> int:
        return hash(json.dumps(self.to_dict(), sort_keys=True))


def _decode_service(data: Dict[str, Any]) -> ServiceSpec:
    pods = tuple(_decode_pod(p) for p in data.get("pods", []))
    rfp = data.get("replacement_failure_policy")
    return ServiceSpec(
        name=data["name"],
        role=data.get("role", ""),
        user=data.get("user", ""),
        region=data.get("region", ""),
        zone=data.get("zone", ""),
        web_url=data.get("web_url", ""),
        service_tld=data.get("service_tld", "fleet.local"),
        pods=pods,
        replacement_failure_policy=(
            ReplacementFailurePolicy(**rfp) if rfp else None
        ),
        plans=data.get("plans", {}),
    )


def merge_pod_volumes(tasks, pod_volumes):
    """Pod-level volumes are shared by every task of the pod
    (reference: pod volumes land in each task's resource set): copy
    them into each task's volume list, task-level declarations winning
    on path clashes.  Applied by BOTH the YAML mapper and from_dict so
    stored target configs written before the merge existed normalize
    to the same shape on load — keeping the builder's spec-equality
    check (and so the target-config pointer) stable across upgrades."""
    import dataclasses as _dc

    if not pod_volumes:
        return tuple(tasks)
    return tuple(
        _dc.replace(
            t,
            volumes=tuple(
                v for v in pod_volumes
                if v.container_path not in {
                    tv.container_path for tv in t.volumes
                }
            ) + t.volumes,
        )
        for t in tasks
    )


def merge_pod_uris(tasks, pod_uris):
    """Pod-level ``uris:`` apply to every task of the pod (reference:
    YAMLToInternalMappers.java:397 builder.uris(podUris)); task-level
    declarations win on dest clashes.  Applied by BOTH the YAML mapper
    and from_dict so stored configs normalize identically."""
    import dataclasses as _dc

    if not pod_uris:
        return tuple(tasks)
    return tuple(
        _dc.replace(
            t,
            uris=tuple(
                u for u in pod_uris
                if u.effective_dest() not in {
                    tu.effective_dest() for tu in t.uris
                }
            ) + t.uris,
        )
        for t in tasks
    )


def _decode_pod(data: Dict[str, Any]) -> PodSpec:
    tpu = data.get("tpu")
    pod_volumes = tuple(
        VolumeSpec(**_vol(v)) for v in data.get("volumes", [])
    )
    pod_uris = tuple(UriSpec(**u) for u in data.get("uris", []))
    return PodSpec(
        type=data["type"],
        count=data.get("count", 1),
        tasks=merge_pod_uris(
            merge_pod_volumes(
                tuple(_decode_task(t) for t in data.get("tasks", [])),
                pod_volumes,
            ),
            pod_uris,
        ),
        tpu=TpuSpec(**tpu) if tpu else None,
        gang=data.get("gang", False),
        image=data.get("image", ""),
        networks=tuple(data.get("networks", ())),
        placement=data.get("placement", ""),
        volumes=pod_volumes,
        uris=pod_uris,
        pre_reserved_role=data.get("pre_reserved_role", ""),
        allow_decommission=data.get("allow_decommission", False),
        share_pid_namespace=data.get("share_pid_namespace", False),
        secrets=tuple(SecretSpec(**s) for s in data.get("secrets", [])),
        rlimits=tuple(RLimitSpec(**r) for r in data.get("rlimits", [])),
    )


def _vol(v: Dict[str, Any]) -> Dict[str, Any]:
    v = dict(v)
    if "profiles" in v:
        v["profiles"] = tuple(v["profiles"])
    return v


def _decode_task(data: Dict[str, Any]) -> TaskSpec:
    res = data.get("resources") or {}
    ports = tuple(PortSpec(**p) for p in res.get("ports", []))
    hc = data.get("health_check")
    rc = data.get("readiness_check")
    return TaskSpec(
        name=data["name"],
        goal=GoalState(data.get("goal", "RUNNING")),
        cmd=data.get("cmd", ""),
        env=dict(data.get("env", {})),
        resources=ResourceSpec(
            cpus=res.get("cpus", 0.1),
            memory_mb=res.get("memory_mb", 32),
            disk_mb=res.get("disk_mb", 0),
            ports=ports,
        ),
        volumes=tuple(VolumeSpec(**_vol(v)) for v in data.get("volumes", [])),
        health_check=HealthCheckSpec(**hc) if hc else None,
        readiness_check=ReadinessCheckSpec(**rc) if rc else None,
        config_templates=tuple(
            (t[0], t[1]) for t in data.get("config_templates", [])
        ),
        kill_grace_period_s=data.get("kill_grace_period_s", 3.0),
        essential=data.get("essential", True),
        transport_encryption=tuple(
            TransportEncryptionSpec(**t)
            for t in data.get("transport_encryption", [])
        ),
        uris=tuple(UriSpec(**u) for u in data.get("uris", [])),
        discovery_prefix=data.get("discovery_prefix", ""),
    )


def pod_instance_name(pod_type: str, index: int) -> str:
    """"<pod>-<index>" (reference: PodInstance.getName())."""
    return f"{pod_type}-{index}"


def task_full_name(pod_type: str, index: int, task_name: str) -> str:
    """"<pod>-<index>-<task>" (reference: TaskSpec.getInstanceName())."""
    return f"{pod_type}-{index}-{task_name}"
