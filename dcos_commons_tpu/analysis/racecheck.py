"""racecheck: thread-ownership static analysis + happens-before race
detection — the concurrency half of the analysis suite.

The reference SDK's scheduler is a single-threaded offer loop; this
rebuild is deliberately not.  SlotEngine/PagedEngine loop threads,
HTTP verb threads, the async checkpoint writer, replication pullers,
the health monitor's telemetry collector, and router poll loops all
share mutable state, and the repo's worst latent bugs have been
cross-thread interleavings caught late.  racecheck finds them the way
plancheck finds plan-state bugs: statically, exhaustively, gated.

Two cooperating halves:

**Static thread-ownership analysis** (``analyze_tree``): an AST pass
that discovers thread-spawn sites (``threading.Thread(target=...)``,
``threading.Timer``, executor ``.submit``, HTTP ``do_*`` handlers,
``Thread`` subclass ``run``) and colors each class's methods by
thread role — the spawn's literal ``name=`` when given, the target
method name otherwise, plus the implicit ``caller`` role every public
method carries.  Roles propagate through the intra-class ``self.``
call graph (nested-closure thread targets become pseudo-methods).
Any attribute written from >= 2 roles must be (a) guarded by the same
lock in every write (``with self.<lock>:`` inference shared with
sdklint's lock-discipline rule, ``*_locked`` = "caller holds it"),
(b) handed off through a recognized channel (``queue.Queue``,
``collections.deque``), or (c) carry an explicit
``# racecheck: handoff=<reason>`` annotation — otherwise it is a
``race-unguarded-shared-write`` finding.  Reads are deliberately
exempt: lock-free reads of wholesale-swapped snapshots are this
codebase's idiom, and the swap itself is what the rule audits.
Writes inside non-spawned nested functions are not attributed (the
callback rule covers registrar-passed closures).

**Dynamic happens-before checker**: vector-clock instrumentation that
subsumes PR 2's lockcheck.  ``install()`` patches the
``threading.Lock``/``RLock``/``Condition`` factories (queue.Queue and
threading.Event resolve those at call time, so channels are
instrumented for free) and ``Thread.start``/``join``.  Lock release
publishes the holder's clock to the lock; acquire joins it; start and
join establish fork/join edges; ``Condition.wait`` flows through the
instrumented lock's ``_release_save``/``_acquire_restore``.  Writes
to watched attributes (``watch_type`` — fed by the static pass's
shared-write map) are probed: a write whose previous writer is
neither the same thread nor ordered before it by the clocks is a race,
reported with both stacks.  Lock-order cycle detection (the
``race-lock-cycle`` rule) is unchanged from lockcheck.  Enabled via
``SDKLINT_RACECHECK=1`` (``SDKLINT_LOCKCHECK=1`` stays an alias).
"""

from __future__ import annotations

import ast
import functools
import os
import re
import sys
import threading
import traceback
import weakref
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

from dcos_commons_tpu.analysis.linter import (
    Finding,
    LintContext,
    LintResult,
    Suppressions,
    _walk_py_files,
)
from dcos_commons_tpu.analysis.rules import _MUTATOR_METHODS, _is_self_attr

# -- rule ids ----------------------------------------------------------

RULE_UNGUARDED = "race-unguarded-shared-write"
RULE_CALLBACK = "race-callback-thread"
RULE_COLLECTIVE = "race-collective-offloop"
RULE_CHECK_THEN_ACT = "race-check-then-act"
RULE_LOCK_CYCLE = "race-lock-cycle"
RULE_UNORDERED = "race-unordered-write"

_RULE_DOCS = {
    RULE_UNGUARDED: (
        "shared attribute written from >= 2 thread roles unguarded",
        "An attribute written from two or more thread roles must hold "
        "one common lock at every write, be a queue/deque handoff "
        "channel, live in a `*_locked` method (caller holds the lock), "
        "or carry `# racecheck: handoff=<reason>` stating the ordering "
        "invariant.  Reads are exempt (snapshot-swap idiom).",
    ),
    RULE_CALLBACK: (
        "registered callback mutates owner-thread state unguarded",
        "A callback handed to a registrar (gauge/subscribe/"
        "add_listener/add_callback) in a thread-spawning class runs on "
        "whatever thread fires it; if it mutates self attributes "
        "without a lock, that is a write from an uncolored role.",
    ),
    RULE_COLLECTIVE: (
        "jax collective reachable from a non-main thread",
        "Collectives (psum/all_gather/broadcast_one_to_all/...) must "
        "run on the thread that owns the device order — a collective "
        "issued from a spawned thread can interleave with the main "
        "thread's program order and deadlock the mesh (the PR 7 "
        "hazard, generalized).",
    ),
    RULE_CHECK_THEN_ACT: (
        "lock released between a guarded read and its dependent write",
        "A local bound from self.<attr> inside one `with self.<lock>:` "
        "block and written back (or used to mutate the same attribute) "
        "inside a LATER guarded block is stale: the lock was released "
        "in between.  Re-read the attribute in the writing block or "
        "merge the critical sections.",
    ),
    RULE_LOCK_CYCLE: (
        "runtime lock-order cycle (latent deadlock) [dynamic]",
        "The instrumented run observed lock sites nesting in a cycle: "
        "thread A holds L1 wanting L2 while thread B can hold L2 "
        "wanting L1.  Reported by the SDKLINT_RACECHECK=1 fixtures; "
        "unchanged from lockcheck.",
    ),
    RULE_UNORDERED: (
        "concurrent unordered writes to one attribute [dynamic]",
        "The vector-clock probe saw two writes to the same attribute "
        "of the same object with no happens-before edge between them "
        "(no common lock, no queue handoff, no start/join ordering). "
        "Both stacks are reported.",
    ),
}


def race_rule_catalog() -> str:
    """Human-readable rule list for ``--catalog`` and the docs."""
    blocks = []
    for rid in sorted(_RULE_DOCS):
        short, doc = _RULE_DOCS[rid]
        blocks.append(f"{rid}: {short}\n    {' '.join(doc.split())}")
    return "\n\n".join(blocks)


# =====================================================================
# Static half: thread-ownership analysis
# =====================================================================

# handoff annotation grammar, on the write line or the line above:
#   # racecheck: handoff=<free-text reason naming the ordering edge>
_HANDOFF_RE = re.compile(r"#.*?\bracecheck:\s*handoff\s*=\s*\S")

_CHANNEL_FACTORIES = {
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "deque",
}
_CALLBACK_REGISTRARS = {
    "gauge", "subscribe", "add_listener", "add_callback",
    "register_callback", "add_done_callback",
}
_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_reduce", "all_to_all", "ppermute", "pshuffle",
    "broadcast_one_to_all", "process_allgather",
    "sync_global_devices", "reached_barrier",
}

CALLER_ROLE = "caller"
HTTP_ROLE = "http"


def _call_name(func: ast.AST) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _iter_spawns(node: ast.AST) -> Iterator[Tuple[ast.Call, ast.AST, str]]:
    """Yield (call, target_expr, role_hint) for every thread-spawn
    site under ``node``: threading.Thread/Timer and executor
    ``.submit`` calls."""
    for call in ast.walk(node):
        if not isinstance(call, ast.Call):
            continue
        func = call.func
        target: Optional[ast.AST] = None
        role = ""
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "threading"
            and func.attr in ("Thread", "Timer")
        ):
            if func.attr == "Thread":
                for kw in call.keywords:
                    if kw.arg == "target":
                        target = kw.value
                    elif kw.arg == "name" and isinstance(
                        kw.value, ast.Constant
                    ) and isinstance(kw.value.value, str):
                        role = kw.value.value
            else:  # Timer(interval, function)
                for kw in call.keywords:
                    if kw.arg == "function":
                        target = kw.value
                if target is None and len(call.args) >= 2:
                    target = call.args[1]
                role = role or "timer"
        elif isinstance(func, ast.Attribute) and func.attr == "submit":
            if call.args:
                target = call.args[0]
            role = "executor"
        if target is not None:
            yield call, target, role


@dataclass
class _Write:
    attr: str
    node: ast.AST
    guards: FrozenSet[str]
    wildcard: bool      # written in a *_locked method: caller holds it
    method: str


class _ClassModel:
    """One class's merged (module-local inheritance resolved) thread
    model: methods incl. spawned-closure pseudo-methods, lock/channel
    attrs, per-method roles, and the write map."""

    def __init__(self, ctx: LintContext, cls: ast.ClassDef,
                 by_name: Dict[str, ast.ClassDef]):
        self.ctx = ctx
        self.cls = cls
        self.name = cls.name
        self.methods: Dict[str, ast.AST] = self._merge_methods(cls, by_name)
        self.is_http_handler = self._is_http_handler(cls, by_name)
        self.is_thread_subclass = self._is_thread_subclass(cls, by_name)
        # pseudo-methods: nested defs spawned as thread targets, keyed
        # "<outer>.<name>"; their bodies are skipped when walking the
        # enclosing method
        self.spawned_nested: Set[int] = set()
        self.roles: Dict[str, Set[str]] = {}
        self._discover_spawns()
        self._seed_roles()
        self.lock_attrs = self._find_lock_attrs()
        self.channel_attrs = self._find_channel_attrs()
        self.calls: Dict[str, Set[str]] = {
            name: self._self_calls(node)
            for name, node in self.methods.items()
        }
        self._propagate_roles()
        self.writes: Dict[str, List[_Write]] = {}
        for name, node in self.methods.items():
            if name == "__init__" or name.endswith(".__init__"):
                continue  # pre-publication writes are single-threaded
            wildcard = name.rsplit(".", 1)[-1].endswith("_locked")
            for attr, sub, guards in self._walk_writes(node):
                self.writes.setdefault(attr, []).append(_Write(
                    attr, sub, frozenset(guards), wildcard, name,
                ))

    # -- structure ----------------------------------------------------

    @staticmethod
    def _merge_methods(cls, by_name) -> Dict[str, ast.AST]:
        chain: List[ast.ClassDef] = []

        def add(c: ast.ClassDef, seen: Set[str]) -> None:
            if c.name in seen:
                return
            seen.add(c.name)
            for b in c.bases:
                if isinstance(b, ast.Name) and b.id in by_name:
                    add(by_name[b.id], seen)
            chain.append(c)

        add(cls, set())
        methods: Dict[str, ast.AST] = {}
        for c in chain:  # base-first: derived overrides win
            for item in c.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods[item.name] = item
        return methods

    @staticmethod
    def _base_names(cls, by_name) -> Set[str]:
        out: Set[str] = set()

        def add(c: ast.ClassDef) -> None:
            for b in c.bases:
                name = _call_name(b) if not isinstance(b, ast.Name) else b.id
                if name and name not in out:
                    out.add(name)
                    if name in by_name:
                        add(by_name[name])

        add(cls)
        return out

    def _is_http_handler(self, cls, by_name) -> bool:
        return any(
            b.endswith("HTTPRequestHandler")
            for b in self._base_names(cls, by_name)
        )

    def _is_thread_subclass(self, cls, by_name) -> bool:
        return "Thread" in self._base_names(cls, by_name)

    def _discover_spawns(self) -> None:
        """Find spawn sites in every method; self.<m> targets color m,
        nested-closure targets become pseudo-methods."""
        for mname, mnode in list(self.methods.items()):
            nested = {
                item.name: item
                for item in ast.walk(mnode)
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                and item is not mnode
            }
            for _call, target, role in _iter_spawns(mnode):
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    tname = target.attr
                    self.roles.setdefault(tname, set()).add(
                        role or tname.lstrip("_")
                    )
                elif isinstance(target, ast.Name) and target.id in nested:
                    closure = nested[target.id]
                    pseudo = f"{mname}.{target.id}"
                    self.methods[pseudo] = closure
                    self.spawned_nested.add(id(closure))
                    self.roles.setdefault(pseudo, set()).add(
                        role or target.id
                    )

    def _seed_roles(self) -> None:
        if self.is_http_handler:
            # every handler method runs on a per-request HTTP thread;
            # nothing in a handler class runs on the caller thread, so
            # no caller seeding (instances are per-request anyway)
            for name in self.methods:
                if name.startswith("do_"):
                    self.roles.setdefault(name, set()).add(HTTP_ROLE)
            return
        if self.is_thread_subclass and "run" in self.methods:
            self.roles.setdefault("run", set()).add(f"run:{self.name}")
        for name in self.methods:
            if "." in name or name.startswith("_"):
                continue
            self.roles.setdefault(name, set()).add(CALLER_ROLE)

    def _find_lock_attrs(self) -> Set[str]:
        """Lock attrs: assigned a threading.Lock/RLock/Condition in any
        __init__ of the chain, or used as ``with self.<attr>:``
        anywhere (covers locks received as constructor parameters,
        e.g. StandbyTail's backend_lock)."""
        locks: Set[str] = set()
        for name, node in self.methods.items():
            if name.rsplit(".", 1)[-1] == "__init__":
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    value = sub.value
                    if (
                        isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Attribute)
                        and value.func.attr in ("Lock", "RLock", "Condition")
                        and isinstance(value.func.value, ast.Name)
                        and value.func.value.id == "threading"
                    ):
                        for target in sub.targets:
                            if (
                                isinstance(target, ast.Attribute)
                                and isinstance(target.value, ast.Name)
                                and target.value.id == "self"
                            ):
                                locks.add(target.attr)
            for sub in ast.walk(node):
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        expr = item.context_expr
                        if (
                            isinstance(expr, ast.Attribute)
                            and isinstance(expr.value, ast.Name)
                            and expr.value.id == "self"
                        ):
                            locks.add(expr.attr)
        return locks

    def _find_channel_attrs(self) -> Set[str]:
        chans: Set[str] = set()
        for name, node in self.methods.items():
            if name.rsplit(".", 1)[-1] != "__init__":
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                value = sub.value
                if (
                    isinstance(value, ast.Call)
                    and _call_name(value.func) in _CHANNEL_FACTORIES
                ):
                    for target in sub.targets:
                        if (
                            isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"
                        ):
                            chans.add(target.attr)
        return chans

    def _self_calls(self, node: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for sub in self._walk_skipping_nested(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == "self"
            ):
                out.add(sub.func.attr)
        return out

    def _walk_skipping_nested(self, root: ast.AST) -> Iterator[ast.AST]:
        """Pre-order walk that does not descend into nested function
        definitions (their execution time is unknown; spawned closures
        are analyzed as pseudo-methods instead)."""

        def rec(n: ast.AST) -> Iterator[ast.AST]:
            yield n
            for child in ast.iter_child_nodes(n):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                yield from rec(child)

        yield root
        body = root.body if hasattr(root, "body") else []
        for child in (body if isinstance(body, list) else []):
            yield from rec(child)

    def _propagate_roles(self) -> None:
        """Fixpoint: a method's roles flow to every self.<m> callee."""
        changed = True
        while changed:
            changed = False
            for name, roles in list(self.roles.items()):
                for callee in self.calls.get(name, ()):
                    if callee not in self.methods:
                        continue
                    have = self.roles.setdefault(callee, set())
                    add = roles - have
                    if add:
                        have |= add
                        changed = True

    # -- write walker ---------------------------------------------------

    def _walk_writes(
        self, method: ast.AST
    ) -> List[Tuple[str, ast.AST, FrozenSet[str]]]:
        """(attr, node, held_locks) for every self-attr write, with a
        set-valued with-lock tracker (same traversal discipline as
        sdklint's lock-discipline rule)."""
        writes: List[Tuple[str, ast.AST, FrozenSet[str]]] = []
        from dcos_commons_tpu.analysis.rules import _self_attr_writes

        def visit(node: ast.AST, held: FrozenSet[str]) -> None:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node is not method:
                return  # nested def: execution time unknown
            if isinstance(node, ast.With):
                locks_here = {
                    item.context_expr.attr
                    for item in node.items
                    if _is_self_attr(item.context_expr, self.lock_attrs)
                }
                held = held | frozenset(locks_here)
                for child in node.body:
                    visit(child, held)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                 ast.Delete, ast.Expr)):
                for attr, sub in _self_attr_writes(node):
                    writes.append((attr, sub, held))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.stmt, ast.excepthandler)):
                    visit(child, held)

        for stmt in method.body:
            visit(stmt, frozenset())
        return writes

    # -- summaries ------------------------------------------------------

    def attr_roles(self, attr: str) -> Set[str]:
        roles: Set[str] = set()
        for w in self.writes.get(attr, ()):
            roles |= self.roles.get(w.method, set())
        return roles

    def shared_attrs(self) -> Dict[str, Set[str]]:
        """attr -> writing roles, for attrs written from >= 2 roles
        (the dynamic probe set, guarded or not)."""
        out = {}
        for attr in self.writes:
            if attr in self.lock_attrs or attr in self.channel_attrs:
                continue
            roles = self.attr_roles(attr)
            if len(roles) >= 2:
                out[attr] = roles
        return out

    def thread_roles(self) -> Set[str]:
        return {
            r for roles in self.roles.values() for r in roles
            if r != CALLER_ROLE
        }


def _has_handoff(ctx: LintContext, line: int) -> bool:
    for lineno in (line, line - 1):
        if 1 <= lineno <= len(ctx.lines) and _HANDOFF_RE.search(
            ctx.lines[lineno - 1]
        ):
            return True
    return False


def _rhs_names(sub: ast.AST) -> Set[str]:
    """Locals referenced by a write's value side."""
    values: List[ast.AST] = []
    if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        if sub.value is not None:
            values.append(sub.value)
    elif isinstance(sub, ast.Call):
        values += list(sub.args)
        values += [kw.value for kw in sub.keywords]
    names: Set[str] = set()
    for value in values:
        for n in ast.walk(value):
            if isinstance(n, ast.Name):
                names.add(n.id)
    return names


def _self_reads(expr: ast.AST) -> Set[str]:
    return {
        n.attr
        for n in ast.walk(expr)
        if isinstance(n, ast.Attribute)
        and isinstance(n.value, ast.Name)
        and n.value.id == "self"
    }


def _ordered(node: ast.AST) -> Iterator[ast.AST]:
    yield node
    for child in ast.iter_child_nodes(node):
        yield from _ordered(child)


class _ClassChecker:
    """Runs the four static rules over one _ClassModel."""

    def __init__(self, model: _ClassModel):
        self.model = model
        self.ctx = model.ctx

    def check(self) -> Tuple[List[Finding], List[Finding]]:
        """-> (findings, handoff_exempted)."""
        findings: List[Finding] = []
        exempted: List[Finding] = []
        self._unguarded_shared_writes(findings, exempted)
        self._check_then_act(findings)
        self._collective_offloop(findings)
        self._callback_thread(findings)
        return findings, exempted

    def _unguarded_shared_writes(self, findings, exempted) -> None:
        m = self.model
        for attr, roles in sorted(m.shared_attrs().items()):
            recs = m.writes[attr]
            non_wild = [w for w in recs if not w.wildcard]
            ok = all(w.guards for w in non_wild)
            if ok and non_wild:
                common = set(non_wild[0].guards)
                for w in non_wild[1:]:
                    common &= set(w.guards)
                ok = bool(common)
            if ok:
                continue
            bad = next(
                (w for w in non_wild if not w.guards),
                recs[0] if recs else None,
            )
            if bad is None:
                continue
            guard_note = sorted({
                g for w in recs for g in w.guards
            })
            finding = self.ctx.finding(
                bad.node, RULE_UNGUARDED,
                f"{m.name}.{attr} is written from roles "
                f"{sorted(roles)} without one common lock"
                + (f" (locks seen: {guard_note})" if guard_note else "")
                + " — guard every write, hand off via a queue, or "
                  "annotate `# racecheck: handoff=<reason>`",
            )
            # the attr rides on the finding so analyze_paths can drop
            # declared-legal sharing from the dynamic probe set (an
            # annotated monotonic flip would otherwise be re-flagged
            # by the vector-clock checker as the exact benign race the
            # annotation blesses)
            finding._race_attr = attr
            if any(
                _has_handoff(self.ctx, w.node.lineno) for w in recs
            ):
                exempted.append(finding)
            else:
                findings.append(finding)

    def _check_then_act(self, findings) -> None:
        m = self.model
        if not m.thread_roles() or not m.lock_attrs:
            return
        for mname, mnode in m.methods.items():
            if mname.rsplit(".", 1)[-1] == "__init__":
                continue
            self._check_then_act_method(findings, mname, mnode)

    def _check_then_act_method(self, findings, mname, mnode) -> None:
        m = self.model
        regions: List[ast.With] = []

        def find_regions(n: ast.AST, held: bool) -> None:
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and n is not mnode:
                return
            if isinstance(n, ast.With):
                guarded = any(
                    _is_self_attr(item.context_expr, m.lock_attrs)
                    for item in n.items
                )
                if guarded and not held:
                    regions.append(n)
                    held = True
            for child in ast.iter_child_nodes(n):
                find_regions(child, held)

        for stmt in mnode.body:
            find_regions(stmt, False)
        if len(regions) < 2:
            return

        bound: Dict[str, Tuple[Set[str], int]] = {}
        for idx, region in enumerate(regions):
            for sub in _ordered(region):
                if (
                    isinstance(sub, ast.Assign)
                    and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                ):
                    attrs_read = _self_reads(sub.value)
                    if attrs_read:
                        bound[sub.targets[0].id] = (attrs_read, idx)
                write_attr = None
                if isinstance(sub, (ast.Assign, ast.AugAssign)):
                    targets = (
                        sub.targets if isinstance(sub, ast.Assign)
                        else [sub.target]
                    )
                    for t in targets:
                        base = t.value if isinstance(t, ast.Subscript) else t
                        if (
                            isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and base.value.id == "self"
                        ):
                            write_attr = base.attr
                elif (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _MUTATOR_METHODS
                    and isinstance(sub.func.value, ast.Attribute)
                    and isinstance(sub.func.value.value, ast.Name)
                    and sub.func.value.value.id == "self"
                ):
                    write_attr = sub.func.value.attr
                if write_attr is None:
                    continue
                for local in _rhs_names(sub):
                    if local not in bound:
                        continue
                    attrs_read, bidx = bound[local]
                    if write_attr in attrs_read and bidx < idx:
                        findings.append(self.ctx.finding(
                            sub, RULE_CHECK_THEN_ACT,
                            f"{m.name}.{mname}: `{local}` was read from "
                            f"self.{write_attr} in an earlier critical "
                            "section; the lock was released before this "
                            "guarded write derived from it — re-read "
                            "under the lock or merge the sections",
                        ))

    def _collective_offloop(self, findings) -> None:
        m = self.model
        for mname, mnode in m.methods.items():
            roles = m.roles.get(mname, set()) - {CALLER_ROLE}
            if not roles:
                continue
            for sub in m._walk_skipping_nested(mnode):
                if (
                    isinstance(sub, ast.Call)
                    and _call_name(sub.func) in _COLLECTIVES
                ):
                    findings.append(self.ctx.finding(
                        sub, RULE_COLLECTIVE,
                        f"{m.name}.{mname} (thread role(s) "
                        f"{sorted(roles)}) calls collective "
                        f"`{_call_name(sub.func)}` off the main "
                        "thread — collectives must follow one "
                        "thread's program order",
                    ))

    def _callback_thread(self, findings) -> None:
        m = self.model
        if not m.thread_roles():
            return
        unguarded_methods = {
            name for name, node in m.methods.items()
            if any(
                not w.guards and not w.wildcard
                for writes in (m.writes.values())
                for w in writes
                if w.method == name
            )
        }
        for mname, mnode in m.methods.items():
            for sub in m._walk_skipping_nested(mnode):
                if not (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _CALLBACK_REGISTRARS
                ):
                    continue
                for arg in list(sub.args) + [
                    kw.value for kw in sub.keywords
                ]:
                    attr = self._callback_mutation(arg, unguarded_methods)
                    if attr:
                        findings.append(self.ctx.finding(
                            sub, RULE_CALLBACK,
                            f"{m.name}.{mname} registers a callback "
                            f"via .{sub.func.attr}() that mutates "
                            f"{attr} unguarded — callbacks fire on "
                            "the registrar's thread, not the owner's",
                        ))

    def _callback_mutation(self, arg, unguarded_methods) -> str:
        if isinstance(arg, ast.Lambda):
            for sub in ast.walk(arg.body):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _MUTATOR_METHODS
                    and isinstance(sub.func.value, ast.Attribute)
                    and isinstance(sub.func.value.value, ast.Name)
                    and sub.func.value.value.id == "self"
                ):
                    owner = sub.func.value.attr
                    if owner not in self.model.lock_attrs:
                        return f"self.{owner}"
        if (
            isinstance(arg, ast.Attribute)
            and isinstance(arg.value, ast.Name)
            and arg.value.id == "self"
            and arg.attr in unguarded_methods
        ):
            return f"self.{arg.attr}() state"
        return ""


@dataclass
class RaceResult(LintResult):
    """LintResult + the thread model the dynamic half probes."""

    shared_attrs: Dict[str, List[str]] = field(default_factory=dict)
    roles: Dict[str, List[str]] = field(default_factory=dict)


def analyze_paths(paths: Sequence[str], root: str) -> RaceResult:
    result = RaceResult()
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        ctx = LintContext(path, os.path.relpath(path, root), source)
        result.files_checked += 1
        if ctx.tree is None:
            continue
        suppressions = Suppressions(ctx.lines)
        by_name = {
            node.name: node
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.ClassDef)
        }
        seen: Set[Tuple[str, int, str]] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            model = _ClassModel(ctx, node, by_name)
            findings, exempted = _ClassChecker(model).check()
            result.suppressed += exempted
            # sharing declared legal (handoff annotation) or triaged
            # with a rationale (sdklint suppression) leaves the
            # dynamic probe set — the stated invariant, not a lock,
            # is what orders those writes
            legal = {
                getattr(f, "_race_attr", None) for f in exempted
            }
            for finding in findings:
                key = (finding.file, finding.line, finding.rule)
                if key in seen:
                    continue  # inheritance merge re-visits base writes
                seen.add(key)
                if suppressions.covers(finding):
                    result.suppressed.append(finding)
                    legal.add(getattr(finding, "_race_attr", None))
                else:
                    result.findings.append(finding)
            shared = {
                attr: roles
                for attr, roles in model.shared_attrs().items()
                if attr not in legal
            }
            if shared:
                attrs = set(
                    result.shared_attrs.get(model.name, [])
                ) | set(shared)
                result.shared_attrs[model.name] = sorted(attrs)
            all_roles = {
                r for roles in model.roles.values() for r in roles
            }
            if all_roles - {CALLER_ROLE}:
                merged = set(
                    result.roles.get(model.name, [])
                ) | all_roles
                result.roles[model.name] = sorted(merged)
    result.findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return result


def analyze_tree(
    root: str,
    subdirs: Sequence[str] = ("dcos_commons_tpu", "frameworks"),
) -> RaceResult:
    return analyze_paths(_walk_py_files(root, subdirs), root)


@functools.lru_cache(maxsize=4)
def shared_write_map(root: str) -> Dict[str, Tuple[str, ...]]:
    """class name -> attrs written from >= 2 thread roles: the set the
    dynamic fixtures probe (``watch_type``).  Cached — the threaded
    test modules all ask for the same map."""
    result = analyze_tree(root)
    return {
        cls: tuple(attrs)
        for cls, attrs in sorted(result.shared_attrs.items())
    }


# =====================================================================
# Dynamic half: vector-clock happens-before instrumentation
# (subsumes PR 2's lockcheck; SDKLINT_LOCKCHECK stays an alias)
# =====================================================================

ENV_VAR = "SDKLINT_RACECHECK"
LEGACY_ENV_VAR = "SDKLINT_LOCKCHECK"

_state_lock = threading.Lock()  # guards the module-level maps below
_enabled = False
_originals: Optional[Tuple] = None
_thread_originals: Optional[Tuple] = None
_tls = threading.local()

# lock-order graph: (outer_site, inner_site) -> one sample acquiring
# stack (the first observed, enough to locate the nesting)
_edges: Dict[Tuple[str, str], str] = {}
# site -> set of thread names that ever acquired it
_threads_per_site: Dict[str, Set[str]] = {}
# (class_name, attr) -> {thread: ALL writes held a lock}
_watched_writes: Dict[Tuple[str, str], Dict[str, bool]] = {}
# vector clocks: (class, attr, id(obj)) -> last write record; the
# record keeps a strong ref to obj so an id() can't be reused while
# its entry is live (reset() drops them)
_last_write: Dict[Tuple[str, str, int], Tuple] = {}
_races: List["RaceRecord"] = []
_RACE_CAP = 64
_tid_counter = [0]
_final_vcs: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_watched_types: List[Tuple[type, Optional[object]]] = []


def _alloc_tid() -> int:
    with _state_lock:
        _tid_counter[0] += 1
        return _tid_counter[0]


def _thread_vc() -> Tuple[int, Dict[int, int]]:
    tid = getattr(_tls, "tid", None)
    if tid is None:
        tid = _tls.tid = _alloc_tid()
        _tls.vc = {tid: 1}
    return tid, _tls.vc


def _join_vc(vc: Dict[int, int], other: Dict[int, int]) -> None:
    for k, v in other.items():
        if v > vc.get(k, 0):
            vc[k] = v


def _held_stack() -> List["InstrumentedLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _enter_probe() -> bool:
    """Reentrancy guard for every recording path.  Recording calls
    ``threading.current_thread()``, which on a still-bootstrapping
    thread mints a ``_DummyThread`` whose own ``Event.set()`` walks
    back into the instrumented condition — without this flag that
    recursion never terminates.  Inside a probe, locks delegate
    without recording."""
    if getattr(_tls, "in_probe", False):
        return False
    _tls.in_probe = True
    return True


def _exit_probe() -> None:
    _tls.in_probe = False


def _creation_site() -> str:
    """file:line of the frame that called threading.Lock()/RLock(),
    relative to the repo so sites read like lint findings."""
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        if os.sep + "analysis" + os.sep + "racecheck" in frame.filename:
            continue
        if frame.filename.startswith("<"):
            continue
        name = frame.filename
        for marker in ("dcos_commons_tpu", "frameworks", "tests"):
            idx = name.find(os.sep + marker + os.sep)
            if idx >= 0:
                name = name[idx + 1:]
                break
        return f"{name.replace(os.sep, '/')}:{frame.lineno}"
    return "<unknown>"


def _short_stack(skip: int = 3, limit: int = 7) -> str:
    """Cheap frame walk (no traceback formatting) for per-write
    capture — racecheck probes hot loops."""
    try:
        frame = sys._getframe(skip)
    except ValueError:
        return "<no stack>"
    out = []
    while frame is not None and len(out) < limit:
        code = frame.f_code
        name = code.co_filename
        for marker in ("dcos_commons_tpu", "frameworks", "tests"):
            idx = name.find(os.sep + marker + os.sep)
            if idx >= 0:
                name = name[idx + 1:]
                break
        out.append(
            f"{name.replace(os.sep, '/')}:{frame.f_lineno} "
            f"in {code.co_name}"
        )
        frame = frame.f_back
    return "\n      ".join(out)


class InstrumentedLock:
    """Wraps one real Lock/RLock: records nesting edges on acquire and
    carries the vector clock releases publish / acquires join.  Also
    implements the private Condition protocol (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``) so ``threading.Condition``
    built on an instrumented lock keeps working — and cv-guarded state
    gets happens-before edges through wait/notify."""

    def __init__(self, inner, site: str, reentrant: bool):
        self._inner = inner
        self.site = site
        self._reentrant = reentrant
        self._vc: Dict[int, int] = {}

    # -- recording ----------------------------------------------------

    def _record_acquire(self) -> None:
        if not _enabled or not _enter_probe():
            return
        try:
            # the calling thread holds the inner lock here, so _vc
            # reads/writes are serialized by the lock itself
            tid, vc = _thread_vc()
            _join_vc(vc, self._vc)
            stack = _held_stack()
            if self._reentrant and any(h is self for h in stack):
                stack.append(self)  # reentry: no new edges
                return
            held_sites = {h.site for h in stack if h is not self}
            new_edges = [
                (outer, self.site) for outer in held_sites
                if outer != self.site and (outer, self.site) not in _edges
            ]
            if new_edges:
                # format the (expensive) sample stack only for a
                # first-seen edge; steady-state nested acquires just
                # re-confirm known edges
                sample = "".join(traceback.format_stack(limit=12)[:-2])
                with _state_lock:
                    for edge in new_edges:
                        _edges.setdefault(edge, sample)
            with _state_lock:
                _threads_per_site.setdefault(self.site, set()).add(
                    threading.current_thread().name
                )
            stack.append(self)
        except Exception:  # sdklint: disable=swallowed-exception — the checker must never break the code under test
            pass
        finally:
            _exit_probe()

    def _record_release(self, pop_all: bool = False) -> int:
        popped = 0
        if not _enabled or not _enter_probe():
            return popped
        try:
            tid, vc = _thread_vc()
            self._vc = dict(vc)
            vc[tid] = vc.get(tid, 0) + 1
            stack = _held_stack()
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is self:
                    del stack[i]
                    popped += 1
                    if not pop_all:
                        break
        except Exception:  # sdklint: disable=swallowed-exception — see _record_acquire
            pass
        finally:
            _exit_probe()
        return popped

    # -- the lock protocol -------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._record_acquire()
        return got

    def release(self) -> None:
        self._record_release()
        self._inner.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        if locked is not None:
            return locked()
        # RLock pre-3.12 has no locked(); _is_owned is close enough
        return bool(self._inner._is_owned())

    # -- Condition protocol ------------------------------------------

    def _release_save(self):
        """Condition.wait: drop ALL recursion levels before parking."""
        popped = self._record_release(pop_all=True)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return (popped, inner._release_save())
        inner.release()
        return (popped, None)

    def _acquire_restore(self, state) -> None:
        popped, saved = state
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(saved)
        else:
            inner.acquire()
        if _enabled and _enter_probe():
            try:
                tid, vc = _thread_vc()
                _join_vc(vc, self._vc)
                stack = _held_stack()
                for _ in range(popped):
                    stack.append(self)
            except Exception:  # sdklint: disable=swallowed-exception — see _record_acquire
                pass
            finally:
                _exit_probe()

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __enter__(self) -> bool:
        self.acquire()
        return True

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<InstrumentedLock {self.site} wrapping {self._inner!r}>"


def install() -> None:
    """Patch threading's lock factories and Thread start/join;
    idempotent."""
    global _enabled, _originals, _thread_originals
    with _state_lock:
        if _originals is None:
            real_lock, real_rlock = threading.Lock, threading.RLock
            real_condition = threading.Condition

            def make_lock():
                return InstrumentedLock(real_lock(), _creation_site(), False)

            def make_rlock():
                return InstrumentedLock(real_rlock(), _creation_site(), True)

            def make_condition(lock=None):
                # InstrumentedLock implements the private Condition
                # protocol, so the cv runs ON the wrapper and wait/
                # notify inherit its happens-before edges (queue.Queue
                # and threading.Event resolve these factories at call
                # time and come out instrumented for free)
                if lock is None:
                    lock = make_rlock()
                return real_condition(lock)

            threading.Lock = make_lock
            threading.RLock = make_rlock
            threading.Condition = make_condition
            _originals = (real_lock, real_rlock, real_condition)
        if _thread_originals is None:
            real_start = threading.Thread.start
            real_join = threading.Thread.join

            def patched_start(self):
                if _enabled:
                    try:
                        ptid, pvc = _thread_vc()
                        pvc[ptid] = pvc.get(ptid, 0) + 1
                        snapshot = dict(pvc)
                        orig_run = self.run

                        def run_shim():
                            tid, vc = _thread_vc()
                            _join_vc(vc, snapshot)
                            vc[tid] = vc.get(tid, 0) + 1
                            try:
                                orig_run()
                            finally:
                                try:
                                    with _state_lock:
                                        _final_vcs[self] = dict(vc)
                                except Exception:  # sdklint: disable=swallowed-exception — teardown must not mask the run's outcome
                                    pass

                        self.run = run_shim
                    except Exception:  # sdklint: disable=swallowed-exception — never break Thread.start
                        pass
                real_start(self)

            def patched_join(self, timeout=None):
                real_join(self, timeout)
                if _enabled and not self.is_alive():
                    try:
                        with _state_lock:
                            final = _final_vcs.get(self)
                        if final:
                            _tid, vc = _thread_vc()
                            _join_vc(vc, final)
                    except Exception:  # sdklint: disable=swallowed-exception — never break Thread.join
                        pass

            threading.Thread.start = patched_start
            threading.Thread.join = patched_join
            _thread_originals = (real_start, real_join)
        _enabled = True


def uninstall() -> None:
    """Restore the factories and stop recording.  Wrappers already
    handed out keep delegating to their inner locks."""
    global _enabled, _originals, _thread_originals
    with _state_lock:
        if _originals is not None:
            threading.Lock, threading.RLock, threading.Condition = _originals
            _originals = None
        if _thread_originals is not None:
            threading.Thread.start, threading.Thread.join = _thread_originals
            _thread_originals = None
        _enabled = False


def reset() -> None:
    with _state_lock:
        _edges.clear()
        _threads_per_site.clear()
        _watched_writes.clear()
        _last_write.clear()
        del _races[:]


def is_enabled() -> bool:
    return _enabled


def env_requested() -> bool:
    return any(
        os.environ.get(var, "") not in ("", "0", "false")
        for var in (ENV_VAR, LEGACY_ENV_VAR)
    )


# -- write probes ------------------------------------------------------


def _record_write(obj, attr: str) -> None:
    """One monitored attribute write: legacy guarded/unguarded
    bookkeeping + the vector-clock unordered-pair check."""
    if not _enter_probe():
        return
    try:
        _record_write_inner(obj, attr)
    finally:
        _exit_probe()


def _record_write_inner(obj, attr: str) -> None:
    held = bool(_held_stack())
    thread = threading.current_thread().name
    tid, vc = _thread_vc()
    vc[tid] = vc.get(tid, 0) + 1  # every write is its own event
    own = vc[tid]
    stack = _short_stack(skip=3)
    cls_name = type(obj).__name__
    for suffix in ("_sdklint",):
        if cls_name.endswith(suffix):
            cls_name = cls_name[: -len(suffix)]
    key = (cls_name, attr, id(obj))
    with _state_lock:
        by_thread = _watched_writes.setdefault((cls_name, attr), {})
        # AND across the thread's writes: one unguarded write taints
        # the thread forever — a guarded write later must never mask it
        by_thread[thread] = by_thread.get(thread, True) and held
        prev = _last_write.get(key)
        _last_write[key] = (tid, own, thread, stack, obj)
        if prev is not None:
            ptid, pown, pname, pstack, _obj = prev
            if ptid != tid and pown > vc.get(ptid, 0):
                if len(_races) < _RACE_CAP:
                    _races.append(RaceRecord(
                        cls_name, attr, pname, pstack, thread, stack,
                    ))


def watch(obj) -> None:
    """Instrument ONE object's attribute writes by swapping in a
    one-off recording subclass (legacy lockcheck API; requires a
    ``__dict__``-backed class)."""
    cls = type(obj)
    if getattr(cls, "_sdklint_watched", False):
        return
    base_name = cls.__name__

    def recording_setattr(self, name, value):
        if _enabled:
            try:
                _record_write(self, name)
            except Exception:  # sdklint: disable=swallowed-exception — never break the watched object
                pass
        super(watched, self).__setattr__(name, value)

    watched = type(
        f"{base_name}_sdklint",
        (cls,),
        {"__setattr__": recording_setattr, "_sdklint_watched": True},
    )
    obj.__class__ = watched


def watch_type(cls: type, attrs: Optional[Sequence[str]] = None) -> None:
    """Instrument EVERY instance of ``cls`` (works with ``__slots__``)
    by patching ``__setattr__`` class-wide.  ``attrs`` narrows the
    probe to the static pass's shared-write set; None records all.
    ``unwatch_types()`` restores."""
    resolved = getattr(cls, "__setattr__", None)
    if getattr(resolved, "_rc_recorder", False):
        return  # this class (or a base) is already recording
    own = cls.__dict__.get("__setattr__")
    allowed = frozenset(attrs) if attrs is not None else None

    def recording_setattr(self, name, value, _orig=resolved):
        if _enabled and (allowed is None or name in allowed):
            try:
                _record_write(self, name)
            except Exception:  # sdklint: disable=swallowed-exception — never break the watched type
                pass
        _orig(self, name, value)

    recording_setattr._rc_recorder = True
    cls.__setattr__ = recording_setattr
    with _state_lock:
        _watched_types.append((cls, own))


def unwatch_types() -> None:
    """Undo every ``watch_type`` patch (fixtures call on teardown)."""
    with _state_lock:
        pending = list(_watched_types)
        del _watched_types[:]
    for cls, own in reversed(pending):
        if own is not None:
            cls.__setattr__ = own
        else:
            try:
                del cls.__setattr__
            except AttributeError:
                pass


# -- report -----------------------------------------------------------


@dataclass
class RaceRecord:
    """One unordered write pair, with both stacks."""

    cls: str
    attr: str
    thread_a: str
    stack_a: str
    thread_b: str
    stack_b: str

    def describe(self) -> str:
        return (
            f"[{RULE_UNORDERED}] {self.cls}.{self.attr} written "
            f"concurrently by '{self.thread_a}' and '{self.thread_b}' "
            "with no happens-before edge\n"
            f"    '{self.thread_a}' wrote at:\n      {self.stack_a}\n"
            f"    '{self.thread_b}' wrote at:\n      {self.stack_b}"
        )


@dataclass
class RaceReport:
    """The dynamic run's verdict: lock-order graph + cycles (the
    race-lock-cycle rule), legacy unguarded-write summary, and the
    vector-clock unordered write pairs."""

    edges: Dict[Tuple[str, str], str] = field(default_factory=dict)
    cycles: List[List[str]] = field(default_factory=list)
    unguarded_writes: List[str] = field(default_factory=list)
    races: List[RaceRecord] = field(default_factory=list)

    def describe(self) -> str:
        lines = [
            f"lock-order edges: {len(self.edges)}, "
            f"cycles: {len(self.cycles)}, "
            f"cross-thread unguarded writes: {len(self.unguarded_writes)}, "
            f"unordered write pairs: {len(self.races)}"
        ]
        for cycle in self.cycles:
            lines.append(
                f"  [{RULE_LOCK_CYCLE}] DEADLOCK RISK: "
                + " -> ".join(cycle + cycle[:1])
            )
            first = (cycle[0], cycle[1 % len(cycle)])
            if first in self.edges:
                lines.append("  sample acquiring stack:\n" + self.edges[first])
        lines += [f"  UNGUARDED: {w}" for w in self.unguarded_writes]
        lines += ["  " + race.describe() for race in self.races]
        return "\n".join(lines)


# lockcheck's historical name for the report type
LockReport = RaceReport


def _find_cycles(adjacency: Dict[str, Set[str]]) -> List[List[str]]:
    """Simple elementary-cycle scan: DFS from each node, reporting
    each cycle once (canonicalized by its smallest rotation)."""
    seen_cycles: Set[Tuple[str, ...]] = set()
    cycles: List[List[str]] = []

    def canonical(path: List[str]) -> Tuple[str, ...]:
        pivot = min(range(len(path)), key=lambda i: path[i])
        return tuple(path[pivot:] + path[:pivot])

    def dfs(node: str, path: List[str], on_path: Set[str]) -> None:
        for nxt in sorted(adjacency.get(node, ())):
            if nxt in on_path:
                cycle = path[path.index(nxt):]
                key = canonical(cycle)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(list(key))
                continue
            if len(path) < 32:  # bound pathological graphs
                dfs(nxt, path + [nxt], on_path | {nxt})

    for start in sorted(adjacency):
        dfs(start, [start], {start})
    return cycles


def report() -> RaceReport:
    with _state_lock:
        edges = dict(_edges)
        watched = {k: dict(v) for k, v in _watched_writes.items()}
        races = list(_races)
    adjacency: Dict[str, Set[str]] = {}
    for outer, inner in edges:
        adjacency.setdefault(outer, set()).add(inner)
    unguarded = [
        f"{cls}.{attr} written by threads {sorted(by_thread)} "
        "with at least one write holding no lock"
        for (cls, attr), by_thread in sorted(watched.items())
        if len(by_thread) > 1 and not all(by_thread.values())
    ]
    return RaceReport(
        edges=edges,
        cycles=_find_cycles(adjacency),
        unguarded_writes=unguarded,
        races=races,
    )
