"""Networked persistence: a state server + remote Persister client.

Reference: curator/CuratorPersister.java:43-110 — the reference keeps
ALL scheduler state in ZooKeeper with atomic multi-op transactions so
a scheduler process is disposable: kill it anywhere, restart it
anywhere, and plans resume mid-step.  CuratorLocker (taken in
SchedulerRunner.run) guarantees one active scheduler per service.

This module is that pair for the TPU fleet, ZooKeeper replaced by a
small HTTP state server (one per cluster / control-plane host):

* ``StateServer`` — hierarchical KV over any local Persister
  (FileWalPersister for durability), every mutation under one lock so
  ``apply`` batches stay atomic, plus TTL leases for the scheduler
  instance lock.
* ``RemotePersister`` — the Persister contract over HTTP; network or
  server failures surface as PersisterError, which fails the scheduler
  cycle and (after the crash-to-restart threshold) the process —
  exactly how the reference treats a ZK outage.
* ``RemoteLocker`` — acquire/renew/release of a named TTL lease; the
  renewal thread keeps the lease while the process lives, and a dead
  scheduler's lease expires so a standby can take over (failover).

Protocol (JSON over HTTP):

    POST /v1/kv/get       {path}                -> {found, value?}
    POST /v1/kv/set       {path, value}
    POST /v1/kv/children  {path}                -> {found, children}
    POST /v1/kv/delete    {path}                -> {found}
    POST /v1/kv/apply     {ops: [{op, path, value?}]}   (atomic)
    POST /v1/lock/acquire {name, owner, ttl_s}  -> {acquired, owner}
    POST /v1/lock/release {name, owner}         -> {released}

Values travel base64-encoded.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, List, Optional, Tuple

from dcos_commons_tpu.storage.persister import (
    DeleteOp,
    MemPersister,
    Persister,
    PersisterError,
    SetOp,
    TransactionOp,
)


LEASE_PREFIX = "/__cluster__/leases"


class StateServer:
    """HTTP front end over one local Persister (the cluster's ZK).

    Leases are persisted through the backend (wall-clock expiry), so a
    state-server restart does NOT silently drop the scheduler instance
    lock — the reference's ZK ephemerals survive a ZK follower bounce
    the same way (CuratorLocker over a ZK ensemble)."""

    def __init__(
        self,
        backend: Optional[Persister] = None,
        port: int = 0,
        bind: str = "127.0.0.1",
        auth_token: str = "",
        tls=None,
        advertise_host: str = "",
    ):
        from dcos_commons_tpu.security import auth as _auth

        self._backend = backend or MemPersister()
        self._lock = threading.RLock()
        # lease name -> (owner, wall-clock expiry); mirrored to the
        # backend under LEASE_PREFIX on every mutation
        self._leases: Dict[str, Tuple[str, float]] = self._load_leases()
        self.advertise_host = advertise_host
        self._scheme = _auth.url_scheme(tls)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code: int, body: dict) -> None:
                payload = json.dumps(body).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                # ALL state routes are mutating or state-revealing:
                # with a token set there is no anonymous surface
                if not _auth.check_bearer(self.headers, auth_token):
                    self._reply(*_auth.UNAUTHORIZED)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    self._reply(200, server.handle(self.path, body))
                except PersisterError as e:
                    self._reply(409, {"error": str(e), "path": e.path})
                except Exception as e:
                    self._reply(500, {"error": repr(e)})

        self._server = _auth.wrap_http_server(
            ThreadingHTTPServer((bind, port), Handler), tls
        )
        self._thread: Optional[threading.Thread] = None

    # -- lease persistence --------------------------------------------

    def _load_leases(self) -> Dict[str, Tuple[str, float]]:
        leases: Dict[str, Tuple[str, float]] = {}
        try:
            names = self._backend.get_children(LEASE_PREFIX)
        except PersisterError:
            return leases
        for name in names:
            try:
                raw = self._backend.get(f"{LEASE_PREFIX}/{name}")
                entry = json.loads(raw or b"{}")
                leases[name] = (entry["owner"], float(entry["expires_at"]))
            except (PersisterError, KeyError, ValueError):
                continue
        return leases

    def _store_lease(self, name: str, owner: str, expires_at: float) -> None:
        self._backend.set(
            f"{LEASE_PREFIX}/{name}",
            json.dumps({"owner": owner, "expires_at": expires_at}).encode(),
        )

    def _drop_lease(self, name: str) -> None:
        try:
            self._backend.recursive_delete(f"{LEASE_PREFIX}/{name}")
        except PersisterError:
            pass

    # -- request handling ---------------------------------------------

    def handle(self, route: str, body: dict) -> dict:
        with self._lock:
            if route == "/v1/kv/get":
                value = None
                try:
                    value = self._backend.get(body["path"])
                    found = True
                except PersisterError:
                    found = False
                return {
                    "found": found,
                    "value": base64.b64encode(value).decode()
                    if value is not None else None,
                }
            if route == "/v1/kv/set":
                self._backend.set(
                    body["path"], base64.b64decode(body["value"] or "")
                )
                return {"ok": True}
            if route == "/v1/kv/children":
                try:
                    return {
                        "found": True,
                        "children": self._backend.get_children(body["path"]),
                    }
                except PersisterError:
                    return {"found": False, "children": []}
            if route == "/v1/kv/delete":
                try:
                    self._backend.recursive_delete(body["path"])
                    return {"found": True}
                except PersisterError:
                    return {"found": False}
            if route == "/v1/kv/apply":
                ops: List[TransactionOp] = []
                for raw in body.get("ops", []):
                    if raw["op"] == "set":
                        ops.append(SetOp(
                            raw["path"],
                            base64.b64decode(raw.get("value") or ""),
                        ))
                    elif raw["op"] == "delete":
                        ops.append(DeleteOp(raw["path"]))
                    else:
                        raise PersisterError(f"unknown op {raw['op']!r}")
                self._backend.apply(ops)
                return {"ok": True, "applied": len(ops)}
            if route == "/v1/lock/acquire":
                return self._acquire(
                    body["name"], body["owner"],
                    float(body.get("ttl_s", 15.0)),
                )
            if route == "/v1/lock/release":
                return self._release(body["name"], body["owner"])
            raise PersisterError(f"no route {route}")

    def _acquire(self, name: str, owner: str, ttl_s: float) -> dict:
        # wall-clock expiry (not monotonic): leases must survive a
        # state-server restart via the backend, and monotonic clocks
        # don't cross processes
        now = time.time()
        held = self._leases.get(name)
        if held is not None and held[1] > now and held[0] != owner:
            return {
                "acquired": False,
                "owner": held[0],
                "expires_in": round(held[1] - now, 1),
            }
        # fresh acquire or renewal by the current owner
        self._leases[name] = (owner, now + ttl_s)
        self._store_lease(name, owner, now + ttl_s)
        return {"acquired": True, "owner": owner}

    def _release(self, name: str, owner: str) -> dict:
        held = self._leases.get(name)
        if held is not None and held[0] == owner:
            del self._leases[name]
            self._drop_lease(name)
            return {"released": True}
        return {"released": False}

    # -- lifecycle ----------------------------------------------------

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        if self.advertise_host:
            host = self.advertise_host
        elif host in ("0.0.0.0", "::"):
            # announce files must carry a dialable address (ADVICE r2)
            import socket

            host = socket.gethostname()
        return f"{self._scheme}://{host}:{port}"

    def start(self) -> "StateServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="state-server", daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._backend.close()


class RemotePersister(Persister):
    """Persister over a StateServer.  Failures raise PersisterError —
    the scheduler treats a dead state server like the reference treats
    a ZK outage: fail the cycle, crash to restart."""

    def __init__(self, base_url: str, timeout_s: float = 10.0,
                 auth_token: str = "", ca_file: str = ""):
        from dcos_commons_tpu.security import auth as _auth

        self._base = base_url.rstrip("/")
        self._timeout_s = timeout_s
        self._headers = {"Content-Type": "application/json",
                         **_auth.auth_headers(auth_token)}
        self._ssl_ctx = (
            _auth.client_ssl_context(ca_file)
            if self._base.startswith("https") else None
        )

    def _call(self, route: str, body: dict) -> dict:
        data = json.dumps(body).encode("utf-8")
        req = urllib.request.Request(
            f"{self._base}{route}", data=data,
            headers=dict(self._headers), method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self._timeout_s, context=self._ssl_ctx
            ) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read().decode("utf-8"))
            except Exception:
                detail = {"error": str(e)}
            raise PersisterError(
                detail.get("error", str(e)), detail.get("path", "")
            )
        except (urllib.error.URLError, OSError) as e:
            raise PersisterError(f"state server unreachable: {e}")

    def get(self, path: str) -> Optional[bytes]:
        out = self._call("/v1/kv/get", {"path": path})
        if not out["found"]:
            raise PersisterError(f"path not found: {path}", path)
        value = out.get("value")
        return base64.b64decode(value) if value is not None else None

    def set(self, path: str, value: bytes) -> None:
        self._call(
            "/v1/kv/set",
            {"path": path, "value": base64.b64encode(value).decode()},
        )

    def get_children(self, path: str) -> List[str]:
        out = self._call("/v1/kv/children", {"path": path})
        if not out["found"]:
            raise PersisterError(f"path not found: {path}", path)
        return out["children"]

    def recursive_delete(self, path: str) -> None:
        if not self._call("/v1/kv/delete", {"path": path})["found"]:
            raise PersisterError(f"path not found: {path}", path)

    def apply(self, ops: Iterable[TransactionOp]) -> None:
        payload = []
        for op in ops:
            if isinstance(op, SetOp):
                payload.append({
                    "op": "set", "path": op.path,
                    "value": base64.b64encode(op.value).decode(),
                })
            else:
                payload.append({"op": "delete", "path": op.path})
        self._call("/v1/kv/apply", {"ops": payload})


class RemoteLocker:
    """Named TTL lease on the state server: the CuratorLocker analogue.

    ``acquire`` takes (or renews) the lease and starts a renewal thread
    at a third of the TTL; if the holder dies, the lease expires and a
    standby scheduler's next acquire succeeds — real failover, not a
    per-host file lock.

    Lease LOSS is fatal to the holder: if a renewal comes back
    ``acquired=false`` (someone else took the lease — we stalled past
    the TTL) or the server stays unreachable beyond the TTL, the
    renewal thread fires ``on_lost`` exactly once and stops.  The
    runner wires ``on_lost`` to crash the scheduler — the reference's
    CuratorLocker exits the process on ZK lock loss for the same
    reason: two active schedulers over one state tree corrupt plans.
    """

    def __init__(
        self,
        base_url: str,
        name: str,
        owner: str,
        ttl_s: float = 15.0,
        timeout_s: float = 5.0,
        auth_token: str = "",
        ca_file: str = "",
    ):
        self._persister = RemotePersister(
            base_url, timeout_s, auth_token=auth_token, ca_file=ca_file
        )
        self.name = name
        self.owner = owner
        self.ttl_s = ttl_s
        # callable(reason: str); set before or after acquire()
        self.on_lost = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _acquire_once(self) -> bool:
        out = self._persister._call(
            "/v1/lock/acquire",
            {"name": self.name, "owner": self.owner, "ttl_s": self.ttl_s},
        )
        return bool(out.get("acquired"))

    def acquire(self) -> bool:
        try:
            if not self._acquire_once():
                return False
        except PersisterError:
            return False
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._renew_loop, name=f"lease-{self.name}", daemon=True
        )
        self._thread.start()
        return True

    def _renew_loop(self) -> None:
        last_renewed = time.monotonic()
        while not self._stop.wait(self.ttl_s / 3.0):
            try:
                if self._acquire_once():
                    last_renewed = time.monotonic()
                    continue
                # someone else holds OUR lease: we stalled past the
                # TTL and a standby took over — we are no longer the
                # instance and must not keep mutating state
                self._lost("lease taken by another scheduler instance")
                return
            except PersisterError as e:
                # transient hiccups are survivable while the lease is
                # still live; once we cannot renew for a full TTL the
                # lease has lapsed server-side and a standby may hold
                # it — same outcome as above
                if time.monotonic() - last_renewed > self.ttl_s:
                    self._lost(f"state server unreachable past TTL: {e}")
                    return

    def _lost(self, reason: str) -> None:
        callback = self.on_lost
        if callback is not None:
            callback(reason)

    def release(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.ttl_s)
        try:
            self._persister._call(
                "/v1/lock/release", {"name": self.name, "owner": self.owner}
            )
        except PersisterError:
            pass  # lease will expire on its own


def main(argv: Optional[list] = None) -> int:
    """``python -m dcos_commons_tpu state-server`` — run the cluster
    state server over a durable file WAL."""
    import argparse

    from dcos_commons_tpu.storage.file_persister import FileWalPersister

    parser = argparse.ArgumentParser(prog="dcos_commons_tpu state-server")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--bind", default="127.0.0.1")
    parser.add_argument(
        "--advertise-host", default="",
        help="hostname/IP to announce instead of the bind address "
             "(required when binding 0.0.0.0 on a multi-host fleet)",
    )
    parser.add_argument("--data-dir", default="./state-server")
    parser.add_argument(
        "--announce-file", default="",
        help="write the URL here once listening (ephemeral ports)",
    )
    parser.add_argument(
        "--auth-token-file", default="",
        help="cluster bearer token file; also $AUTH_TOKEN(_FILE)",
    )
    parser.add_argument("--tls-cert", default="", help="serve HTTPS: cert PEM")
    parser.add_argument("--tls-key", default="", help="serve HTTPS: key PEM")
    args = parser.parse_args(argv)
    from dcos_commons_tpu.security.auth import load_token

    token = load_token(token_file=args.auth_token_file)
    if not token and args.bind not in ("127.0.0.1", "localhost", "::1"):
        import sys

        print(
            "WARNING: state server bound on a non-loopback address with NO "
            "auth token — anyone who can reach this port can clobber all "
            "cluster state. Pass --auth-token-file.",
            file=sys.stderr,
        )
    from dcos_commons_tpu.agent.daemon import _tls_pair_or_die

    server = StateServer(
        FileWalPersister(args.data_dir), port=args.port, bind=args.bind,
        auth_token=token,
        tls=_tls_pair_or_die(args.tls_cert, args.tls_key),
        advertise_host=args.advertise_host,
    )
    if args.announce_file:
        from dcos_commons_tpu.common import atomic_write_text

        atomic_write_text(args.announce_file, server.url + "\n")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
