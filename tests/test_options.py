"""Per-package options schema: the config.json / Cosmos plane.

Reference: frameworks/helloworld/universe/config.json (typed operator
options with defaults/enums/constraints), rendered by Cosmos into
scheduler env, faked in tests by CosmosRenderer
(sdk/testing/.../CosmosRenderer.java:24).  Here: options.json beside
svc.yml; `package install --options` validates + renders; the sim
harness's cosmos_render drives ServiceTest-style flows from options;
`package build`/`lint` refuse a self-inconsistent schema.
"""

import json
import os
import time

import pytest

from dcos_commons_tpu.tools.options import (
    OptionsError,
    default_env_name,
    load_schema,
    merge_options,
    render_options,
    validate_schema,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = {
    "properties": {
        "hello": {
            "properties": {
                "count": {"type": "integer", "default": 2, "minimum": 1,
                          "env": "HELLO_COUNT"},
                "mode": {"type": "string", "default": "blue",
                         "enum": ["blue", "green"]},
                "rate": {"type": "number", "default": 1.5,
                         "maximum": 10},
                "debug": {"type": "boolean", "default": False},
            },
        },
        "auth": {
            "properties": {
                "token": {"type": "string", "required": True},
            },
        },
    },
}


def test_defaults_render_to_env():
    env = render_options(SCHEMA, {"auth": {"token": "s3cret"}})
    assert env == {
        "HELLO_COUNT": "2",
        "HELLO_MODE": "blue",
        "HELLO_RATE": "1.5",
        "HELLO_DEBUG": "false",
        "AUTH_TOKEN": "s3cret",
    }


def test_overrides_and_bool_rendering():
    env = render_options(SCHEMA, {
        "hello": {"count": 5, "debug": True, "mode": "green"},
        "auth": {"token": "t"},
    })
    assert env["HELLO_COUNT"] == "5"
    assert env["HELLO_DEBUG"] == "true"
    assert env["HELLO_MODE"] == "green"


def test_pointed_errors_all_at_once():
    """Every violation reported in one pass, each naming the option."""
    with pytest.raises(OptionsError) as err:
        render_options(SCHEMA, {
            "hello": {"count": 0, "mode": "purple", "rate": 99,
                      "debug": "yes", "typo_opt": 1},
            "unknown_section": {"x": 1},
            # auth.token missing (required)
        })
    text = "; ".join(err.value.errors)
    assert "hello.count: 0 below minimum 1" in text
    assert "hello.mode: 'purple' not one of ['blue', 'green']" in text
    assert "hello.rate: 99 above maximum 10" in text
    assert "hello.debug: expected boolean" in text
    assert "no such option hello.typo_opt" in text
    assert "no such options section 'unknown_section'" in text
    assert "auth.token is required" in text


def test_type_confusions_rejected():
    with pytest.raises(OptionsError, match="expected integer"):
        render_options(SCHEMA, {"hello": {"count": "3"},
                                "auth": {"token": "t"}})
    # bool is an int subclass in Python: must still be rejected
    with pytest.raises(OptionsError, match="got boolean"):
        render_options(SCHEMA, {"hello": {"count": True},
                                "auth": {"token": "t"}})


def test_no_schema_means_no_options():
    assert render_options(None, None) == {}
    with pytest.raises(OptionsError, match="ships no options.json"):
        render_options(None, {"hello": {"count": 1}})


def test_schema_self_validation():
    assert validate_schema(SCHEMA) == []
    bad = {
        "properties": {
            "s": {
                "properties": {
                    "no_default": {"type": "string"},
                    "bad_type": {"type": "blob", "default": 1},
                    "bad_default": {"type": "integer", "default": "x"},
                    "bad_range": {"type": "integer", "default": 5,
                                  "minimum": 9, "maximum": 3},
                    "dup_env": {"type": "string", "default": "",
                                "env": "S_NO_DEFAULT"},
                },
            },
        },
    }
    findings = "; ".join(validate_schema(bad))
    assert "s.no_default: needs a 'default'" in findings
    assert "s.bad_type: type must be one of" in findings
    assert "expected integer" in findings  # bad_default
    assert "minimum > maximum" in findings
    assert "collides" in findings


def test_merge_options_per_section():
    prior = {"hello": {"count": 5, "mode": "green"}, "auth": {"token": "t"}}
    new = {"hello": {"count": 7}}
    merged = merge_options(prior, new)
    assert merged["hello"] == {"count": 7, "mode": "green"}
    assert merged["auth"] == {"token": "t"}
    assert prior["hello"]["count"] == 5  # no aliasing


def test_prune_unknown_prior_options():
    """A new package version that DROPS an option must not be bricked
    by the stored value — pruned with the dropped list reported."""
    from dcos_commons_tpu.tools.options import prune_unknown

    kept, dropped = prune_unknown(SCHEMA, {
        "hello": {"count": 3, "legacy_opt": "x"},
        "gone_section": {"y": 1},
        "auth": {"token": "t"},
    })
    assert kept == {"hello": {"count": 3}, "auth": {"token": "t"}}
    assert dropped == ["gone_section.y", "hello.legacy_opt"]
    kept, dropped = prune_unknown(None, {"a": {"b": 1}})
    assert kept == {} and dropped == ["a.b"]


def test_schema_bugs_are_findings_not_crashes():
    # constraint type mismatch: minimum on a string
    findings = "; ".join(validate_schema({
        "properties": {"s": {"properties": {
            "x": {"type": "string", "default": "hi", "minimum": 1},
        }}},
    }))
    assert "not comparable" in findings
    # misspelled 'properties' in a section
    findings = "; ".join(validate_schema({
        "properties": {"s": {"propertes": {
            "x": {"type": "string", "default": ""},
        }}},
    }))
    assert "needs a 'properties' object" in findings
    # non-dict section
    findings = "; ".join(validate_schema({"properties": {"s": "oops"}}))
    assert "needs a 'properties' object" in findings


def test_non_object_schema_is_a_finding_not_a_crash(tmp_path):
    from dcos_commons_tpu.tools import PackageError, build_package
    from dcos_commons_tpu.tools.options import options_findings

    d = tmp_path / "fw"
    d.mkdir()
    (d / "svc.yml").write_text(
        "name: fw\npods:\n  a:\n    count: 1\n    tasks:\n"
        "      t:\n        goal: RUNNING\n        cmd: sleep 1\n"
        "        cpus: 0.1\n        memory: 32\n"
    )
    (d / "options.json").write_text("[]")
    findings = options_findings(str(d))
    assert findings and "JSON object" in findings[0]
    with pytest.raises(PackageError, match="JSON object"):
        build_package(str(d), str(tmp_path / "fw.tgz"))


def test_default_env_name():
    assert default_env_name("hello-pod", "max.per_host") == \
        "HELLO_POD_MAX_PER_HOST"


def test_shipped_framework_schemas_are_clean():
    """helloworld, jax, and hdfs ship schemas that lint clean and
    whose env names actually appear in at least one of the
    framework's service YAMLs (jax spreads its options across the
    train and serve variants)."""
    import glob

    for framework in ("helloworld", "jax", "hdfs"):
        framework_dir = os.path.join(REPO, "frameworks", framework)
        schema = load_schema(framework_dir)
        assert schema is not None, f"{framework} ships no options.json"
        assert validate_schema(schema) == [], framework
        env = render_options(schema, {})
        yaml_text = ""
        for path in sorted(glob.glob(
            os.path.join(framework_dir, "svc*.yml")
        )):
            with open(path) as f:
                yaml_text += f.read()
        for env_name in env:
            assert f"{{{{{env_name}" in yaml_text, (
                f"{framework} option env {env_name} unused in any svc*.yml"
            )


def test_cosmos_render_drives_sim_harness():
    """ServiceTest-style flow from package options: world.count=3
    deploys three world pods (reference: CosmosRenderer + ServiceTest
    option-bump flows)."""
    from dcos_commons_tpu.testing import (
        AdvanceCycles,
        ExpectLaunchedTasks,
        SendTaskRunning,
        ServiceTestRunner,
        cosmos_render,
    )

    framework_dir = os.path.join(REPO, "frameworks", "helloworld")
    env = cosmos_render(framework_dir, {"world": {"count": 3}})
    assert env["WORLD_COUNT"] == "3"
    with open(os.path.join(framework_dir, "svc.yml")) as f:
        runner = ServiceTestRunner(f.read(), env=env)
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        SendTaskRunning("world-0-server"),
        AdvanceCycles(1),
        SendTaskRunning("world-1-server"),
        AdvanceCycles(2),
    ])
    # the third world pod exists ONLY because the rendered option said
    # count=3 (the YAML default is 2)
    assert runner.world.agent.task_id_of("world-2-server") is not None
    # and bad options are rejected with a pointed error
    with pytest.raises(OptionsError, match="world.count: 0 below minimum"):
        cosmos_render(framework_dir, {"world": {"count": 0}})


def test_package_build_and_lint_refuse_bad_schema(tmp_path):
    from dcos_commons_tpu.tools import PackageError, build_package
    from dcos_commons_tpu.tools.packaging import main as package_main

    d = tmp_path / "fw"
    d.mkdir()
    (d / "svc.yml").write_text(
        "name: fw\npods:\n  a:\n    count: 1\n    tasks:\n"
        "      t:\n        goal: RUNNING\n        cmd: sleep 1\n"
        "        cpus: 0.1\n        memory: 32\n"
    )
    (d / "options.json").write_text(json.dumps({
        "properties": {
            "a": {"properties": {
                "count": {"type": "integer", "default": "oops"},
            }},
        },
    }))
    with pytest.raises(PackageError, match="options.json is inconsistent"):
        build_package(str(d), str(tmp_path / "fw.tgz"))
    assert package_main(["lint", str(d)]) == 1
    # fix the schema: build + lint pass
    (d / "options.json").write_text(json.dumps({
        "properties": {
            "a": {"properties": {
                "count": {"type": "integer", "default": 1, "minimum": 1},
            }},
        },
    }))
    build_package(str(d), str(tmp_path / "fw.tgz"))
    assert package_main(["lint", str(d)]) == 0


def _drive_install(multi, agent, name, count):
    deadline = time.monotonic() + 20
    from dcos_commons_tpu.common import TaskState, TaskStatus

    while time.monotonic() < deadline:
        multi.run_cycle()
        for i in range(count):
            task_id = agent.task_id_of(f"app-{i}-main")
            if task_id is not None and task_id in agent.active_task_ids():
                agent.send(TaskStatus(
                    task_id=task_id, state=TaskState.RUNNING, ready=True,
                ))
        svc = multi.get_service(name)
        plans = svc.plans()
        rollout = plans.get("update") or plans.get("deploy")
        if rollout.is_complete:
            return svc
    raise AssertionError("rollout did not complete")


@pytest.mark.slow
def test_cli_install_with_options_through_served_scheduler(tmp_path):
    """`package install --options file.json` end to end: the options
    ride the X-Service-Options header, the served multi scheduler
    validates + renders them, and a bad options file is refused with
    the pointed error on stderr."""
    import subprocess
    import sys
    import urllib.request

    d = tmp_path / "optsvc"
    d.mkdir()
    (d / "svc.yml").write_text(
        "name: optsvc\npods:\n  app:\n    count: {{APP_COUNT:-1}}\n"
        "    tasks:\n      main:\n        goal: RUNNING\n"
        "        cmd: \"sleep 100\"\n"
        "        cpus: 0.1\n        memory: 32\n"
    )
    (d / "options.json").write_text(json.dumps({
        "properties": {
            "app": {"properties": {
                "count": {"type": "integer", "default": 1, "minimum": 1,
                          "maximum": 4, "env": "APP_COUNT"},
            }},
        },
    }))
    out = str(tmp_path / "optsvc.tgz")
    from dcos_commons_tpu.tools import build_package

    build_package(str(d), out)
    topology = tmp_path / "topology.yml"
    topology.write_text(
        "hosts:\n  - host_id: h0\n    cpus: 8\n    memory_mb: 8192\n"
    )
    announce = tmp_path / "announce"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dcos_commons_tpu", "serve", "--multi",
            "--topology", str(topology),
            "--port", "0",
            "--state-dir", str(tmp_path / "state"),
            "--sandbox-root", str(tmp_path / "sbx"),
            "--announce-file", str(announce),
        ],
        cwd=REPO,
    )
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not announce.exists():
            time.sleep(0.1)
        url = announce.read_text().strip()
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"app": {"count": 9}}))
        refused = subprocess.run(
            [sys.executable, "-m", "dcos_commons_tpu", "package",
             "install", out, "--url", url, "--options", str(bad)],
            cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        assert refused.returncode == 1
        assert "app.count: 9 above maximum 4" in refused.stderr
        good = tmp_path / "good.json"
        good.write_text(json.dumps({"app": {"count": 2}}))
        installed = subprocess.run(
            [sys.executable, "-m", "dcos_commons_tpu", "package",
             "install", out, "--url", url, "--options", str(good)],
            cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        assert installed.returncode == 0, installed.stderr

        def get(path):
            with urllib.request.urlopen(url + path, timeout=5) as r:
                return json.loads(r.read())

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                pods = get("/v1/multi/optsvc/v1/pod/status")["pods"]
                tasks = [
                    t for p in pods for i in p["instances"]
                    for t in i["tasks"]
                ]
                if len(tasks) == 2:  # count=2 from the options
                    break
            except Exception:
                pass
            time.sleep(0.3)
        else:
            raise AssertionError("optioned pod count never appeared")
    finally:
        proc.terminate()
        proc.wait(timeout=20)


def test_install_package_with_options_and_upgrade_keeps_them(tmp_path):
    """The full Cosmos flow: install with options renders them into
    the spec; a bad option is refused with a pointed error; an
    upgrade WITHOUT options re-renders with the prior ones."""
    from dcos_commons_tpu.multi import MultiServiceScheduler
    from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
    from dcos_commons_tpu.scheduler import SchedulerConfig
    from dcos_commons_tpu.specification.specs import SpecError
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import FakeAgent
    from dcos_commons_tpu.tools import build_package

    d = tmp_path / "optsvc"
    d.mkdir()
    (d / "svc.yml").write_text(
        "name: optsvc\npods:\n  app:\n    count: {{APP_COUNT:-1}}\n"
        "    tasks:\n      main:\n        goal: RUNNING\n"
        "        cmd: \"echo {{GREETING:-hi}} && sleep 100\"\n"
        "        cpus: 0.1\n        memory: 32\n"
    )
    (d / "options.json").write_text(json.dumps({
        "properties": {
            "app": {"properties": {
                "count": {"type": "integer", "default": 1, "minimum": 1,
                          "maximum": 4, "env": "APP_COUNT"},
                "greeting": {"type": "string", "default": "hi",
                             "env": "GREETING"},
            }},
        },
    }))
    v1 = str(tmp_path / "v1.tgz")
    build_package(str(d), v1, version="0.1.0")
    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory([TpuHost(host_id="h0")]),
        agent=FakeAgent(),
        scheduler_config=SchedulerConfig(
            backoff_enabled=False,
            revive_capacity=1_000_000,
            state_dir=str(tmp_path / "state"),
        ),
    )
    payload = open(v1, "rb").read()
    # bad option: pointed refusal, nothing installed
    with pytest.raises(SpecError, match="app.count: 9 above maximum 4"):
        multi.install_package(
            "optsvc", payload, options={"app": {"count": 9}}
        )
    assert multi.get_service("optsvc") is None
    multi.install_package(
        "optsvc", payload,
        options={"app": {"count": 2, "greeting": "bonjour"}},
    )
    svc = _drive_install(multi, multi.agent, "optsvc", 2)
    assert svc.spec.pod("app").count == 2
    assert "bonjour" in svc.spec.pod("app").task("main").cmd
    # upgrade with NO options: prior options re-render into v2
    (d / "svc.yml").write_text(
        open(d / "svc.yml").read().replace("sleep 100", "sleep 200")
    )
    v2 = str(tmp_path / "v2.tgz")
    build_package(str(d), v2, version="0.2.0")
    multi.install_package("optsvc", open(v2, "rb").read(), upgrade=True)
    svc = _drive_install(multi, multi.agent, "optsvc", 2)
    assert svc.spec.pod("app").count == 2, "prior options lost on upgrade"
    assert "bonjour" in svc.spec.pod("app").task("main").cmd
    # upgrade overlaying one option keeps the other
    multi.install_package(
        "optsvc", open(v2, "rb").read(), upgrade=True,
        options={"app": {"count": 3}},
    )
    svc = _drive_install(multi, multi.agent, "optsvc", 3)
    assert svc.spec.pod("app").count == 3
    assert "bonjour" in svc.spec.pod("app").task("main").cmd
    # v3 DROPS the greeting option entirely: stored greeting must not
    # brick the upgrade — it is pruned, the rest survive
    (d / "options.json").write_text(json.dumps({
        "properties": {
            "app": {"properties": {
                "count": {"type": "integer", "default": 1, "minimum": 1,
                          "maximum": 4, "env": "APP_COUNT"},
            }},
        },
    }))
    v3 = str(tmp_path / "v3.tgz")
    build_package(str(d), v3, version="0.3.0")
    multi.install_package("optsvc", open(v3, "rb").read(), upgrade=True)
    svc = _drive_install(multi, multi.agent, "optsvc", 3)
    assert svc.spec.pod("app").count == 3  # count option survived
