"""Weight-only int8 quantization (models/quantize.py).

The contract: quantized params are a drop-in param tree for forward /
prefill / decode / generate, the per-element error is bounded by the
per-channel scale, and the stored bytes roughly halve.  The oracle for
end-to-end behavior is the same model with unquantized weights — close
logits, and identical greedy continuations for the seeded cases here
(quantization error far below the seeded models' argmax margins).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcos_commons_tpu.models import (
    TransformerConfig,
    forward,
    generate,
    init_params,
    prefill,
    quantize_params_int8,
)
from dcos_commons_tpu.models.quantize import (
    dequantize_weight,
    quantize_weight,
)
from dcos_commons_tpu.utils import synthetic_tokens

CFG = TransformerConfig(
    vocab=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq=64, dtype=jnp.float32, remat=False,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def qparams(params):
    return quantize_params_int8(params)


def test_per_channel_error_bound():
    """|W - dq(q(W))| <= scale/2 = max|column| / 254 per element."""
    w = jax.random.normal(jax.random.key(3), (2, 32, 48), jnp.float32)
    q = quantize_weight(w)
    assert q["q"].dtype == jnp.int8
    assert q["scale"].shape == (2, 1, 48)
    err = np.abs(np.asarray(dequantize_weight(q, jnp.float32) - w))
    bound = np.asarray(q["scale"]) / 2.0 + 1e-7
    assert (err <= bound).all(), f"max err {err.max()} exceeds scale/2"


def test_dequantize_identity_on_plain_arrays():
    w = jnp.ones((3, 4), jnp.float32)
    assert dequantize_weight(w, jnp.float32) is w


def test_tree_shape_and_bytes(params, qparams):
    # same tree layout apart from the {"q","scale"} leaves; scan axis
    # (leading n_layers) preserved on both members
    for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        leaf = qparams["layers"][name]
        full = params["layers"][name]
        assert leaf["q"].shape == full.shape
        assert leaf["q"].dtype == jnp.int8
        assert leaf["scale"].shape[0] == CFG.n_layers
    # norms and embed untouched
    assert qparams["embed"] is params["embed"]
    assert qparams["layers"]["attn_norm"] is params["layers"]["attn_norm"]
    # stored bytes shrink: f32 layers -> ~1/4 (bf16 would be ~1/2);
    # embed stays native so compare the layer stacks only
    full_bytes = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(params["layers"])
    )
    q_bytes = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(qparams["layers"])
    )
    assert q_bytes < 0.35 * full_bytes
    from dcos_commons_tpu.utils import param_bytes

    assert param_bytes(qparams) < param_bytes(params)


def test_forward_close(params, qparams):
    tokens, _ = synthetic_tokens(jax.random.key(1), 2, 16, CFG.vocab)
    full = np.asarray(forward(CFG, params, tokens))
    quant = np.asarray(forward(CFG, qparams, tokens))
    # int8 per-channel keeps logits within a small fraction of their
    # dynamic range on this model
    scale = np.abs(full).max()
    assert np.abs(quant - full).max() < 0.05 * scale


def test_prefill_accepts_quantized(params, qparams):
    tokens, _ = synthetic_tokens(jax.random.key(2), 2, 12, CFG.vocab)
    logits_q, cache = prefill(CFG, qparams, tokens, max_len=24)
    logits_f, _ = prefill(CFG, params, tokens, max_len=24)
    assert cache["k"].shape == (2, 2, 24, CFG.n_kv_heads, CFG.head_dim)
    scale = np.abs(np.asarray(logits_f)).max()
    assert np.abs(np.asarray(logits_q - logits_f)).max() < 0.05 * scale


def test_greedy_generate_matches_unquantized(params, qparams):
    """Seeded greedy continuations agree end-to-end (the argmax margins
    of this model dwarf the int8 error)."""
    tokens, _ = synthetic_tokens(jax.random.key(4), 2, 8, CFG.vocab)
    full = np.asarray(generate(CFG, params, tokens, max_new_tokens=8))
    quant = np.asarray(generate(CFG, qparams, tokens, max_new_tokens=8))
    np.testing.assert_array_equal(full, quant)


def test_composes_with_int8_kv_cache(params, qparams):
    """int8 weights + int8 KV cache in one generate (the full serving
    quantization stack)."""
    tokens, _ = synthetic_tokens(jax.random.key(5), 2, 8, CFG.vocab)
    full = np.asarray(generate(CFG, params, tokens, max_new_tokens=8))
    quant = np.asarray(
        generate(CFG, qparams, tokens, max_new_tokens=8, kv_dtype="int8")
    )
    np.testing.assert_array_equal(full, quant)


def test_mixed_length_quantized(qparams):
    """Per-row true_len (the serving micro-batch path) works on the
    quantized tree."""
    prompt = jnp.zeros((2, 10), jnp.int32)
    tokens, _ = synthetic_tokens(jax.random.key(6), 2, 10, CFG.vocab)
    prompt = tokens.at[1, 6:].set(0)  # row 1 really ends at 6
    out = generate(
        CFG, qparams, prompt, max_new_tokens=4,
        true_len=jnp.asarray([10, 6], jnp.int32),
    )
    assert out.shape == (2, 4)


def test_quantized_moe_decode():
    """MoE expert stacks quantize through the same leaf names; the
    drop-free decode path consumes them."""
    cfg = TransformerConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq=32, dtype=jnp.float32, remat=False,
        n_experts=4, moe_top_k=2,
    )
    params = init_params(cfg, jax.random.key(7))
    qparams = quantize_params_int8(params)
    assert qparams["layers"]["router"] is params["layers"]["router"]
    assert qparams["layers"]["w_gate"]["q"].dtype == jnp.int8
    tokens, _ = synthetic_tokens(jax.random.key(8), 2, 6, cfg.vocab)
    full = np.asarray(generate(cfg, params, tokens, max_new_tokens=4))
    quant = np.asarray(generate(cfg, qparams, tokens, max_new_tokens=4))
    np.testing.assert_array_equal(full, quant)


def test_jit_generate_quantized(qparams):
    """The serving entry: one jitted generate over the quantized tree
    with traced temperature + true_len (serve_worker's exact shape)."""
    gen = jax.jit(lambda p, t, key, temp, n: generate(
        CFG, p, t, max_new_tokens=4, max_len=16, temperature=temp,
        key=key, true_len=n,
    ))
    tokens, _ = synthetic_tokens(jax.random.key(9), 2, 8, CFG.vocab)
    out = gen(
        qparams, tokens, jax.random.key(0), jnp.float32(0.0),
        jnp.asarray([8, 8], jnp.int32),
    )
    assert out.shape == (2, 4)
