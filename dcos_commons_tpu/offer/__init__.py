"""L3 + L0-replacement: TPU slice inventory + evaluation + placement.

The reference consumes Mesos resource *offers* (sdk/scheduler/.../offer/:
MesosResourcePool, OfferEvaluator.java:65,113, evaluation stages,
placement rules).  TPU fleets have no Mesos, so this package *owns*
the substrate the reference outsourced (SURVEY.md section 7 delta a):

- inventory.py   the fleet model: hosts, chips, ICI torus coordinates,
                 and ResourceSnapshots (the offer equivalent)
- ledger.py      the reservation ledger: WAL-backed, idempotent —
                 replaces Mesos reservation labels + resource ids
- torus.py       contiguous sub-slice search over the host grid
- placement.py   placement-rule DSL (max-per-host, zones, task-type
                 colocate/avoid, marathon-style JSON, torus rules)
- evaluate.py    the evaluation pipeline: requirement + snapshots ->
                 reserve/launch recommendations, or per-stage reasons
- outcome.py     EvaluationOutcome + the "explain why placement
                 failed" record (feeds debug/OfferOutcomeTracker)
"""

from dcos_commons_tpu.offer.inventory import (
    ResourceSnapshot,
    SliceInventory,
    TpuHost,
)
from dcos_commons_tpu.offer.ledger import Reservation, ReservationLedger
from dcos_commons_tpu.offer.outcome import EvaluationOutcome
from dcos_commons_tpu.offer.placement import PlacementRule, parse_placement
from dcos_commons_tpu.offer.evaluate import (
    EvaluationContext,
    EvaluationResult,
    LaunchRecommendation,
    OfferEvaluator,
    ReserveRecommendation,
)

__all__ = [
    "EvaluationContext",
    "EvaluationOutcome",
    "EvaluationResult",
    "LaunchRecommendation",
    "OfferEvaluator",
    "PlacementRule",
    "Reservation",
    "ReservationLedger",
    "ReserveRecommendation",
    "ResourceSnapshot",
    "SliceInventory",
    "TpuHost",
    "parse_placement",
]
