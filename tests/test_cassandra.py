"""frameworks/cassandra: the second standalone stateful service.

Reference: frameworks/cassandra — seed computation + SeedsResource
(Main.java:60-89), CassandraRecoveryPlanOverrider (:38-67, the
replace_address relaunch), and parameterized backup/restore sidecar
plans.  The sim flows here mirror the reference's ServiceTest +
test_backup_and_restore.py shapes.
"""

import os


from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.recovery.monitor import TestingFailureMonitor
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    ExpectLaunchedTasks,
    ExpectPlanStatus,
    SendTaskFailed,
    SendTaskFinished,
    SendTaskRunning,
    ServiceTestRunner,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CASSANDRA_DIR = os.path.join(REPO, "frameworks", "cassandra")

# load under a UNIQUE module name: test_hdfs imports ITS framework's
# scheduler.py as `scheduler`, and a shared name would collide in
# sys.modules when both test files run in one session
import importlib.util  # noqa: E402

_spec = importlib.util.spec_from_file_location(
    "cassandra_scheduler", os.path.join(CASSANDRA_DIR, "scheduler.py")
)
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)
make_node_replace_overrider = _mod.make_node_replace_overrider
make_seeds_routes = _mod.make_seeds_routes
ring_name = _mod.ring_name


def load_svc() -> str:
    with open(os.path.join(CASSANDRA_DIR, "svc.yml")) as f:
        return f.read()


def deploy_ticks():
    ticks = []
    for i in range(3):
        ticks += [
            AdvanceCycles(1),
            ExpectLaunchedTasks(f"node-{i}-server"),
            SendTaskRunning(f"node-{i}-server"),
        ]
    ticks.append(ExpectDeploymentComplete())
    return ticks


def test_ring_deploys_serially():
    runner = ServiceTestRunner(load_svc())
    runner.run(deploy_ticks())
    agent = runner.world.agent
    # the durable ring volume is attached to every node
    info = agent.task_info_of("node-1-server")
    assert "cassandra-data" in info.volumes


def test_seeds_endpoint_lists_first_two_nodes(monkeypatch):
    """/v1/seeds = the SeedsResource analogue: first min(2, count)
    instances with liveness, plus TASKCFG_ALL_REMOTE_SEEDS."""
    runner = ServiceTestRunner(load_svc())
    runner.run(deploy_ticks())
    monkeypatch.setenv(
        "TASKCFG_ALL_REMOTE_SEEDS",
        "node-0.dc2.fleet.local,node-1.dc2.fleet.local",
    )
    ((method, pattern, handler),) = make_seeds_routes(
        runner.world.scheduler
    )
    assert (method, pattern) == ("GET", r"/v1/seeds")
    code, body = handler(None, None)
    assert code == 200
    assert [s["seed"] for s in body["seeds"]] == [
        "node-0.cassandra.fleet.local",
        "node-1.cassandra.fleet.local",
    ]
    assert all(s["state"] == "TASK_RUNNING" for s in body["seeds"])
    assert body["remote_seeds"] == [
        "node-0.dc2.fleet.local", "node-1.dc2.fleet.local",
    ]


def test_permanent_replace_carries_replace_address():
    """The overrider's replacement launch injects REPLACE_ADDRESS so
    the new node takes over the dead node's ring position (reference:
    CassandraRecoveryPlanOverrider appending replace_address)."""
    runner = ServiceTestRunner(load_svc())
    spec = runner.spec

    def hook(builder):
        builder.add_recovery_overrider(make_node_replace_overrider(spec))
        builder.set_failure_monitor(
            TestingFailureMonitor(permanent_tasks=["node-1-server"])
        )

    runner._builder_hook = hook
    runner.run(deploy_ticks())
    first_id = runner.world.agent.task_id_of("node-1-server")
    runner.run([
        SendTaskFailed("node-1-server"),
        AdvanceCycles(1),
    ])
    recovery = runner.world.scheduler.plan("recovery")
    assert [s.name for s in recovery.phases[0].steps] == [
        "replace-node-1"
    ]
    runner.run([
        ExpectLaunchedTasks("node-1-server"),
        SendTaskRunning("node-1-server"),
        ExpectPlanStatus("recovery", Status.COMPLETE),
    ])
    agent = runner.world.agent
    info = agent.task_info_of("node-1-server")
    assert info.task_id != first_id
    assert info.env["REPLACE_ADDRESS"] == ring_name(spec, 1)
    assert info.env["REPLACE_ADDRESS"] == \
        "node-1.cassandra.fleet.local"


def test_transient_failure_keeps_default_recovery():
    """Only PERMANENT replaces get the overrider: a transient crash
    relaunches in place with NO replace_address (a live ring position
    must not be taken over)."""
    runner = ServiceTestRunner(load_svc())
    spec = runner.spec
    runner._builder_hook = lambda b: b.add_recovery_overrider(
        make_node_replace_overrider(spec)
    )
    runner.run(deploy_ticks())
    runner.run([
        SendTaskFailed("node-2-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("node-2-server"),
        SendTaskRunning("node-2-server"),
        ExpectPlanStatus("recovery", Status.COMPLETE),
    ])
    info = runner.world.agent.task_info_of("node-2-server")
    assert info.env.get("REPLACE_ADDRESS", "") == ""


def test_backup_plan_parameterized():
    """`plan start backup -p BACKUP_DIR=...` runs the backup sidecar
    on every node inside the existing footprint (reference: cassandra
    backup plans)."""
    runner = ServiceTestRunner(load_svc())
    runner.run(deploy_ticks())
    scheduler = runner.world.scheduler
    from dcos_commons_tpu.http.api import SchedulerApi

    api = SchedulerApi(scheduler)
    code, _body = api.plan_start(
        "backup", {"BACKUP_DIR": "/mnt/backups/snap-1"}
    )
    assert code == 200
    runner.run([AdvanceCycles(2)])
    agent = runner.world.agent
    for i in range(3):
        info = agent.task_info_of(f"node-{i}-backup")
        assert info is not None, f"backup sidecar {i} never launched"
        assert info.env["BACKUP_DIR"] == "/mnt/backups/snap-1"
        # sidecars join the node's existing footprint (same host)
        server = agent.task_info_of(f"node-{i}-server")
        assert info.agent_id == server.agent_id
    runner.run([
        SendTaskFinished("node-0-backup"),
        SendTaskFinished("node-1-backup"),
        SendTaskFinished("node-2-backup"),
        ExpectPlanStatus("backup", Status.COMPLETE),
    ])


def test_cassandra_options_schema_clean():
    from dcos_commons_tpu.tools.options import (
        load_schema,
        render_options,
        validate_schema,
    )

    schema = load_schema(CASSANDRA_DIR)
    assert schema is not None
    assert validate_schema(schema) == []
    env = render_options(schema, {"node": {"count": 5}})
    assert env["NODE_COUNT"] == "5"
    with open(os.path.join(CASSANDRA_DIR, "svc.yml")) as f:
        yaml_text = f.read()
    for env_name in env:
        assert f"{{{{{env_name}" in yaml_text, env_name


def test_default_permanent_replace_skips_never_launched_sidecars():
    """WITHOUT the overrider: a default PERMANENT replace re-places
    the pod's LAUNCHED footprint — the server (and any launched FINISH
    init tasks), never the backup/restore sidecars whose plan hasn't
    run (a spurious backup on replace would be an operator incident)."""
    runner = ServiceTestRunner(load_svc())
    runner._builder_hook = lambda b: b.set_failure_monitor(
        TestingFailureMonitor(permanent_tasks=["node-0-server"])
    )
    runner.run(deploy_ticks())
    runner.run([
        SendTaskFailed("node-0-server"),
        AdvanceCycles(1),
        ExpectLaunchedTasks("node-0-server"),
        SendTaskRunning("node-0-server"),
        ExpectPlanStatus("recovery", Status.COMPLETE),
    ])
    agent = runner.world.agent
    assert agent.task_id_of("node-0-backup") is None
    assert agent.task_id_of("node-0-restore") is None


def test_widened_transient_recovery_stays_scoped():
    """An essential failure arriving while a non-essential subset
    phase is in flight widens the recovery — to the LAUNCHED
    running-goal footprint, never to completed FINISH sidecars (r4
    review finding: the widening rebuild used all-tasks scope)."""
    yaml_text = """
name: widen
pods:
  app:
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: "sleep 100"
        cpus: 0.1
        memory: 32
      metrics:
        goal: RUNNING
        cmd: "sleep 100"
        cpus: 0.1
        memory: 32
        essential: false
      initjob:
        goal: FINISH
        cmd: "echo init"
        cpus: 0.1
        memory: 32
"""
    runner = ServiceTestRunner(yaml_text)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("app-0-server"),
        SendTaskRunning("app-0-metrics"),
        SendTaskFinished("app-0-initjob"),
        ExpectDeploymentComplete(),
    ])
    init_id = runner.world.agent.task_id_of("app-0-initjob")
    # non-essential fails -> subset recovery in flight; then the
    # essential server fails -> recovery widens
    runner.run([
        SendTaskFailed("app-0-metrics"),
        AdvanceCycles(1),
        SendTaskFailed("app-0-server"),
        AdvanceCycles(2),
    ])
    recovery = runner.world.scheduler.plan("recovery")
    step_tasks = {
        t for s in recovery.phases[0].steps
        for t in s.requirement.tasks_to_launch
    }
    assert step_tasks == {"server", "metrics"}, step_tasks
    runner.run([
        SendTaskRunning("app-0-server"),
        SendTaskRunning("app-0-metrics"),
        AdvanceCycles(1),
        ExpectPlanStatus("recovery", Status.COMPLETE),
    ])
    # the completed FINISH task was never relaunched
    assert runner.world.agent.task_id_of("app-0-initjob") == init_id
