"""Back-compat alias: lockcheck grew into racecheck (ISSUE 17).

PR 2's runtime lock-order checker is now the dynamic half of
``dcos_commons_tpu.analysis.racecheck`` — same ``install``/``watch``/
``report`` API, plus vector-clock happens-before tracking, Thread
start/join edges, and ``watch_type``.  Lock-order cycle detection is
unchanged (reported as the ``race-lock-cycle`` rule).  This module
keeps every historical import site and the ``SDKLINT_LOCKCHECK=1``
opt-in working; new code should import racecheck directly.
"""

from __future__ import annotations

from dcos_commons_tpu.analysis.racecheck import (  # noqa: F401
    InstrumentedLock,
    LockReport,
    RaceRecord,
    RaceReport,
    env_requested,
    install,
    is_enabled,
    report,
    reset,
    uninstall,
    unwatch_types,
    watch,
    watch_type,
)
from dcos_commons_tpu.analysis.racecheck import (  # noqa: F401
    LEGACY_ENV_VAR as ENV_VAR,
)
