"""HDFS-analogue scheduler customizations + entrypoint.

Reference: frameworks/hdfs/src/main/java/.../Main.java (framework-
specific scheduler wiring) and HdfsRecoveryPlanOverrider — a name-node
PERMANENT replace must NOT be a bare relaunch: the replacement has an
empty volume, so the recovery phase re-runs the bootstrap task (pull
the namespace image from the other name node) before starting the
node task.  The cassandra analogue restarts seeds on node replace
(CassandraRecoveryPlanOverrider.java:38-67); both are consumers of the
RecoveryPlanOverrider hook (recovery/manager.py).

Run as a service process:

    python frameworks/hdfs/scheduler.py svc.yml --topology fleet.yml
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.step import (
    DeploymentStep,
    PodInstanceRequirement,
    RecoveryType,
)
from dcos_commons_tpu.plan.strategy import SerialStrategy
from dcos_commons_tpu.specification.specs import ServiceSpec


def make_name_node_overrider(spec: ServiceSpec):
    """RecoveryPlanOverrider: custom choreography for name-pod
    PERMANENT replaces; everything else keeps default recovery."""

    def overrider(
        pod_type: str, instances: List[int], recovery_type: RecoveryType
    ) -> Optional[Phase]:
        if pod_type != "name" or recovery_type is not RecoveryType.PERMANENT:
            return None
        pod = spec.pod("name")
        steps = []
        for index in instances:
            # re-seed the empty replacement volume, then start the node
            steps.append(DeploymentStep(
                f"bootstrap-name-{index}",
                PodInstanceRequirement(
                    pod=pod, instances=[index],
                    tasks_to_launch=["bootstrap"],
                    recovery_type=RecoveryType.PERMANENT,
                ),
            ))
            steps.append(DeploymentStep(
                f"relaunch-name-{index}",
                PodInstanceRequirement(
                    pod=pod, instances=[index],
                    tasks_to_launch=["node"],
                    recovery_type=RecoveryType.PERMANENT,
                ),
            ))
        return Phase(
            f"recover-name-{'-'.join(map(str, instances))}",
            steps,
            SerialStrategy(),
        )

    return overrider


def make_name_nodes_routes(scheduler):
    """Custom framework endpoint (reference: Cassandra's SeedsResource
    — Main.java registers a service-specific HTTP resource next to the
    SDK's): GET /v1/namenodes lists the name-node fleet with host
    placement and liveness, the discovery surface HDFS clients use."""

    def name_nodes(_match, _query):
        statuses = scheduler.state_store.fetch_statuses()
        nodes = []
        for index in range(scheduler.spec.pod("name").count):
            full = f"name-{index}-node"
            info = scheduler.state_store.fetch_task(full)
            status = statuses.get(full)
            nodes.append({
                "name": full,
                "host": info.agent_id if info else None,
                "state": status.state.value if status else None,
            })
        return 200, {"namenodes": nodes}

    return [("GET", r"/v1/namenodes", name_nodes)]


def main(argv: Optional[List[str]] = None) -> int:
    from dcos_commons_tpu.runtime.runner import serve_main

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0].startswith("-"):
        argv.insert(0, os.path.join(os.path.dirname(__file__), "svc.yml"))
    return serve_main(
        argv,
        builder_hook=lambda builder, spec: builder.add_recovery_overrider(
            make_name_node_overrider(spec)
        ),
        routes_hook=make_name_nodes_routes,
    )


if __name__ == "__main__":
    raise SystemExit(main())
