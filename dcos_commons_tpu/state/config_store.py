"""ConfigStore: UUID -> serialized ServiceSpec, plus target pointer.

Reference: state/ConfigStore.java — configs are content-addressed by
UUID; a separate "target" pointer names the config tasks should be
running.  Config updates store a new UUID then flip the pointer
(config/DefaultConfigurationUpdater.java:159).
"""

from __future__ import annotations

import json
import uuid as uuid_mod
from typing import Any, Callable, Dict, List, Optional

from dcos_commons_tpu.storage import Persister, PersisterError


class ConfigStore:
    """Stores configs as JSON dicts; the spec layer provides codecs."""

    def __init__(self, persister: Persister, namespace: str = "") -> None:
        self._persister = persister
        self._root = f"/{namespace}" if namespace else ""

    def _path(self, leaf: str) -> str:
        return f"{self._root}/{leaf}"

    def store(self, config: Dict[str, Any]) -> str:
        config_id = str(uuid_mod.uuid4())
        self._persister.set(
            self._path(f"configurations/{config_id}"),
            json.dumps(config, sort_keys=True).encode("utf-8"),
        )
        return config_id

    def fetch(self, config_id: str) -> Optional[Dict[str, Any]]:
        try:
            raw = self._persister.get(self._path(f"configurations/{config_id}"))
        except PersisterError:
            return None
        return json.loads(raw.decode("utf-8")) if raw is not None else None

    def list_ids(self) -> List[str]:
        return self._persister.get_children_or_empty(self._path("configurations"))

    def clear(self, config_id: str) -> None:
        try:
            self._persister.recursive_delete(
                self._path(f"configurations/{config_id}")
            )
        except PersisterError:
            pass

    # -- target pointer ----------------------------------------------

    def set_target_config(self, config_id: str) -> None:
        self._persister.set(
            self._path("config-target"), config_id.encode("utf-8")
        )

    def get_target_config(self) -> Optional[str]:
        try:
            raw = self._persister.get(self._path("config-target"))
        except PersisterError:
            return None
        return raw.decode("utf-8") if raw is not None else None

    def fetch_target(self) -> Optional[Dict[str, Any]]:
        target = self.get_target_config()
        return self.fetch(target) if target else None

    # -- GC (reference: DefaultConfigurationUpdater cleanup of configs
    #    no longer referenced by any task) ---------------------------

    def prune(self, referenced_ids: List[str]) -> List[str]:
        keep = set(referenced_ids)
        target = self.get_target_config()
        if target:
            keep.add(target)
        removed = []
        for config_id in self.list_ids():
            if config_id not in keep:
                self.clear(config_id)
                removed.append(config_id)
        return removed
