"""Ahead-of-time spec analyzer: deploy-time failures at lint time.

Reference: the 19 config validators run when a target config is
SUBMITTED (specification/validation.py) — but by then the package is
built and the operator is mid-install.  This pass runs the same
validators plus placement/port/plan/resource feasibility over every
``frameworks/*/svc*.yml`` rendered with its ``options.json``
defaults, so a spec that cannot possibly deploy fails in CI.

Checks, each with its own rule id (suppressible like lint rules,
``# sdklint: disable-file=<rule>`` in the YAML):

- ``spec-options``     options.json schema findings (tools/options)
- ``spec-render``      template/YAML/spec mapping errors
- ``spec-validators``  default config validators against old=None
- ``spec-placement``   constraints unsatisfiable on the declared torus
- ``spec-ports``       fixed-port conflicts within a pod / across count
- ``spec-plan``        unknown pods/tasks, bad strategies, dependency
                       cycles in plan phases
- ``spec-resources``   one pod instance exceeding any single host
- ``no-gpus-resource`` a ``gpus:`` key in the YAML (BASELINE invariant)
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from dcos_commons_tpu.analysis.linter import Finding, Suppressions


@dataclass
class HostModel:
    """The host shape feasibility checks assume.  Defaults mirror
    ``TpuHost``'s (offer/inventory.py); override via CLI flags when
    your fleet is beefier."""

    cpus: float = 8.0
    memory_mb: int = 16384
    disk_mb: int = 102400


def _yml_files(framework_dir: str) -> List[str]:
    return sorted(
        os.path.join(framework_dir, f)
        for f in os.listdir(framework_dir)
        if f.endswith(".yml")
    )


def analyze_all(
    root: str, host_model: Optional[HostModel] = None
) -> List[Finding]:
    frameworks_dir = os.path.join(root, "frameworks")
    findings: List[Finding] = []
    if not os.path.isdir(frameworks_dir):
        return findings
    for name in sorted(os.listdir(frameworks_dir)):
        framework_dir = os.path.join(frameworks_dir, name)
        if os.path.isdir(framework_dir):
            findings += analyze_framework(framework_dir, root, host_model)
    return findings


def analyze_framework(
    framework_dir: str,
    root: str,
    host_model: Optional[HostModel] = None,
) -> List[Finding]:
    from dcos_commons_tpu.tools import options as options_mod

    host_model = host_model or HostModel()
    findings: List[Finding] = []
    rel_dir = os.path.relpath(framework_dir, root).replace(os.sep, "/")

    schema = None
    disabled: set = set()
    try:
        schema = options_mod.load_schema(framework_dir)
        if schema is not None:
            # JSON carries no comments, so options.json suppresses via
            # a top-level key instead:  "x-sdklint-disable": ["rule"]
            # (framework-wide, like disable-file)
            disabled = {str(r) for r in schema.get("x-sdklint-disable") or []}
        for text in options_mod.validate_schema(schema) if schema else []:
            findings.append(Finding(
                f"{rel_dir}/options.json", 1, "spec-options", text
            ))
        env = options_mod.render_options(schema, {})
    except options_mod.OptionsError as e:
        findings += [
            Finding(f"{rel_dir}/options.json", 1, "spec-options", text)
            for text in e.errors
        ]
        env = {}

    for path in _yml_files(framework_dir):
        findings += _analyze_yaml(path, root, env, host_model)
    if disabled:
        findings = [
            f for f in findings
            if f.rule not in disabled and "all" not in disabled
        ]
    return findings


def _analyze_yaml(
    path: str, root: str, env: Dict[str, str], host_model: HostModel
) -> List[Finding]:
    from dcos_commons_tpu.specification.yaml_spec import from_yaml_file

    rel = os.path.relpath(path, root).replace(os.sep, "/")
    with open(path, "r", encoding="utf-8") as f:
        lines = f.read().splitlines()
    spec, render_error = render_spec(rel, lambda: from_yaml_file(path, env))
    return check_spec_lines(rel, lines, spec, render_error, host_model)


def render_spec(rel: str, render):
    """Run one spec-render callable, classifying failures into the
    ``spec-render`` Finding shape BOTH enforcement points share — the
    CI walker above and the admission gate (multi/admission.py).  One
    classifier, so a future special-cased exception type cannot give
    CI and a 422 body divergent wordings for the same failure."""
    from dcos_commons_tpu.specification.specs import SpecError

    try:
        return render(), None
    except SpecError as e:
        return None, Finding(rel, 1, "spec-render", str(e))
    except Exception as e:
        return None, Finding(
            rel, 1, "spec-render", f"{type(e).__name__}: {e}"
        )


def check_spec_lines(
    rel: str,
    lines: Sequence[str],
    spec,
    render_error: Optional[Finding] = None,
    host_model=None,
    apply_suppressions: bool = True,
    feasibility_hint: str = " (--host-cpus/--host-mem/--host-disk to raise)",
) -> List[Finding]:
    """Every spec-level check over an ALREADY-RENDERED spec + its
    source lines.  Shared by the CI walker above and the dynamic
    add-service admission gate (multi/admission.py) — one rule set,
    two enforcement points.  ``host_model`` may be one HostModel (the
    CI walker's hypothetical fleet) or a LIST of them (admission's
    real per-host shapes): a pod is infeasible only when it fits NONE
    of them — per-dimension maxima across different hosts would admit
    specs no single host can run.  An EMPTY list means the fleet is
    unknown (admission with no up hosts): feasibility is skipped
    entirely rather than judged against the CI default shape.
    ``apply_suppressions=False`` is the admission gate's setting:
    suppression comments live in the operator-submitted payload
    there, so honoring them would let any payload waive its own
    rejection.  ``feasibility_hint`` tails the spec-resources message
    so each enforcement point names its own remediation."""
    if host_model is None:
        host_models = [HostModel()]
    elif isinstance(host_model, HostModel):
        host_models = [host_model]
    else:
        host_models = list(host_model)
    raw_findings: List[Finding] = []
    raw_findings += _check_gpus_keys(rel, lines)
    if render_error is not None:
        raw_findings.append(render_error)
    if spec is not None:
        anchor = _make_anchor(lines)
        raw_findings += _check_validators(rel, spec)
        raw_findings += _check_placement(rel, spec, anchor)
        raw_findings += _check_ports(rel, spec, anchor)
        raw_findings += _check_plans(rel, spec, anchor)
        if host_models:
            raw_findings += _check_resources(
                rel, spec, host_models, anchor, feasibility_hint
            )
    if not apply_suppressions:
        return raw_findings
    suppressions = Suppressions(lines)
    return [f for f in raw_findings if not suppressions.covers(f)]


def _make_anchor(lines: Sequence[str]):
    """Line of the first ``<name>:`` key in the YAML, so pod/plan
    findings land on (and are suppressible at) the declaring line;
    1 when not found."""
    def anchor(name: str) -> int:
        pattern = re.compile(rf"^\s*{re.escape(str(name))}\s*:")
        for i, text in enumerate(lines, start=1):
            if pattern.match(text):
                return i
        return 1
    return anchor


def _check_gpus_keys(rel: str, lines: Sequence[str]) -> List[Finding]:
    out = []
    for i, text in enumerate(lines, start=1):
        if re.match(r"^\s*gpus\s*:", text):
            out.append(Finding(
                rel, i, "no-gpus-resource",
                "`gpus:` key: accelerators are the pod-level tpu: "
                "block (BASELINE invariant)",
            ))
    return out


def _check_validators(rel: str, spec) -> List[Finding]:
    from dcos_commons_tpu.specification.validation import (
        ConfigValidationError,
        validate_spec_change,
    )

    try:
        validate_spec_change(None, spec)
    except ConfigValidationError as e:
        return [
            Finding(rel, 1, "spec-validators", text) for text in e.errors
        ]
    return []


def _conjunctive_rules(rule) -> List:
    """The rules that must ALL pass: the root plus AndRule members,
    recursively.  Or/Not branches are skipped — no unsatisfiability
    conclusion is safe through them."""
    from dcos_commons_tpu.offer.placement import AndRule

    if isinstance(rule, AndRule):
        out = []
        for child in rule.rules:
            out += _conjunctive_rules(child)
        return out
    return [rule]


def _implied_hosts(pod) -> Optional[int]:
    """Host count the pod's own tpu block declares, or None (CPU pods
    run on an unknown fleet)."""
    tpu = pod.tpu
    if tpu is None or not tpu.topology:
        return None
    per_host = tpu.chips_per_host
    if per_host <= 0 or tpu.total_chips % per_host:
        return None  # gang_pods_need_topology reports this shape
    return (tpu.total_chips // per_host) * max(tpu.slices, 1)


def _check_placement(rel: str, spec, anchor) -> List[Finding]:
    from dcos_commons_tpu.offer.placement import (
        FieldMatchRule,
        MaxPerRule,
        parse_placement,
    )

    out = []
    for pod in spec.pods:
        try:
            rule = parse_placement(pod.placement)
        except ValueError:
            continue  # spec-validators already reports the parse error
        hosts = _implied_hosts(pod)
        for term in _conjunctive_rules(rule):
            if isinstance(term, MaxPerRule):
                if term.max_count <= 0:
                    out.append(Finding(
                        rel, anchor(pod.type), "spec-placement",
                        f"pod {pod.type!r}: max-per-{term.field_name}:"
                        f"{term.max_count} excludes every host",
                    ))
                elif (
                    term.field_name == "hostname"
                    and hosts is not None
                    and term.max_count * hosts < pod.count
                ):
                    out.append(Finding(
                        rel, anchor(pod.type), "spec-placement",
                        f"pod {pod.type!r}: count {pod.count} cannot fit "
                        f"max-per-hostname:{term.max_count} on the "
                        f"declared torus's {hosts} host(s)",
                    ))
            elif (
                isinstance(term, FieldMatchRule)
                and term.field_name == "generation"
                and not term.regex
                and not term.invert
                and pod.tpu is not None
                and pod.tpu.generation not in term.values
            ):
                out.append(Finding(
                    rel, anchor(pod.type), "spec-placement",
                    f"pod {pod.type!r}: placement requires generation "
                    f"{term.values} but the pod declares "
                    f"{pod.tpu.generation!r} — no host satisfies both",
                ))
    return out


def _check_ports(rel: str, spec, anchor) -> List[Finding]:
    out = []
    for pod in spec.pods:
        fixed: Dict[int, str] = {}
        for task in pod.tasks:
            for port in task.resources.ports:
                if not port.port:
                    continue
                where = f"{pod.type}/{task.name}:{port.name}"
                if port.port in fixed:
                    out.append(Finding(
                        rel, anchor(pod.type), "spec-ports",
                        f"fixed port {port.port} requested by both "
                        f"{fixed[port.port]} and {where}; one pod "
                        "instance's tasks share a host",
                    ))
                else:
                    fixed[port.port] = where
        if fixed and pod.count > 1 and \
                "max-per-host" not in (pod.placement or ""):
            ports = sorted(fixed)
            out.append(Finding(
                rel, anchor(pod.type), "spec-ports",
                f"pod {pod.type!r}: count {pod.count} with fixed "
                f"port(s) {ports} but no max-per-host placement — "
                "co-located instances would collide",
            ))
    return out


def _check_plans(rel: str, spec, anchor) -> List[Finding]:
    from dcos_commons_tpu.plan.generator import dependency_cycle
    from dcos_commons_tpu.plan.strategy import strategy_for_name

    out = []
    pod_types = {p.type: p for p in spec.pods}
    for plan_name, raw_plan in (spec.plans or {}).items():
        raw_plan = raw_plan or {}
        try:
            strategy_for_name(str(raw_plan.get("strategy", "serial")))
        except ValueError as e:
            out.append(Finding(
                rel, anchor(plan_name), "spec-plan", f"plan {plan_name!r}: {e}"
            ))
        phases = raw_plan.get("phases") or {}
        edges: Dict[str, List[str]] = {}
        for phase_name, raw_phase in phases.items():
            raw_phase = raw_phase or {}
            where = f"plan {plan_name!r} phase {phase_name!r}"
            deps = [str(d) for d in raw_phase.get("dependencies") or []]
            edges[str(phase_name)] = deps
            for dep in deps:
                if dep not in phases:
                    out.append(Finding(
                        rel, anchor(plan_name), "spec-plan",
                        f"{where}: dependency {dep!r} names no phase "
                        f"of this plan (have: {sorted(map(str, phases))})",
                    ))
            pod_name = raw_phase.get("pod")
            if not pod_name or str(pod_name) not in pod_types:
                out.append(Finding(
                    rel, anchor(plan_name), "spec-plan",
                    f"{where}: pod {pod_name!r} is not declared "
                    f"(have: {sorted(pod_types)})",
                ))
                continue
            pod = pod_types[str(pod_name)]
            task_names = {t.name for t in pod.tasks}
            for entry in raw_phase.get("steps") or []:
                if not isinstance(entry, dict) or len(entry) != 1:
                    out.append(Finding(
                        rel, anchor(plan_name), "spec-plan",
                        f"{where}: each step must be one "
                        "{index: [[tasks...]]} mapping",
                    ))
                    continue
                ((raw_index, task_groups),) = entry.items()
                if str(raw_index) != "default":
                    try:
                        index = int(raw_index)
                    except (TypeError, ValueError):
                        out.append(Finding(
                            rel, anchor(plan_name), "spec-plan",
                            f"{where}: step index {raw_index!r} is not "
                            "an integer or 'default'",
                        ))
                        continue
                    if not 0 <= index < pod.count:
                        out.append(Finding(
                            rel, anchor(plan_name), "spec-plan",
                            f"{where}: step index {index} out of range "
                            f"for pod {pod.type!r} (count {pod.count})",
                        ))
                for group in task_groups or []:
                    for task_name in group or []:
                        if str(task_name) not in task_names:
                            out.append(Finding(
                                rel, anchor(plan_name), "spec-plan",
                                f"{where}: step task {task_name!r} not "
                                f"in pod {pod.type!r} "
                                f"(have: {sorted(task_names)})",
                            ))
        edges = {k: v for k, v in edges.items() if v}
        if edges and "strategy" in raw_plan:
            out.append(Finding(
                rel, anchor(plan_name), "spec-plan",
                f"plan {plan_name!r}: explicit 'strategy' conflicts "
                "with phase 'dependencies' (the DAG defines the "
                "order; drop one)",
            ))
        cycle = dependency_cycle(edges)
        if cycle:
            out.append(Finding(
                rel, anchor(plan_name), "spec-plan",
                f"plan {plan_name!r}: phase dependency cycle "
                + " -> ".join(cycle),
            ))
    return out


def _check_resources(
    rel: str, spec, host_models: Sequence[HostModel], anchor,
    hint: str = "",
) -> List[Finding]:
    out = []
    for pod in spec.pods:
        cpus = sum(t.resources.cpus for t in pod.tasks)
        mem = sum(t.resources.memory_mb for t in pod.tasks)
        disk = sum(t.resources.disk_mb for t in pod.tasks)
        # one durable dir per instance+path: sibling tasks sharing a
        # container path share the volume, so dedupe by path
        vol_by_path: Dict[str, int] = {}
        for task in pod.tasks:
            for vol in task.volumes:
                vol_by_path[vol.container_path] = max(
                    vol_by_path.get(vol.container_path, 0), vol.size_mb
                )
        disk += sum(vol_by_path.values())
        # feasible iff SOME host shape fits every dimension; report
        # the closest fit's shortfalls when none does
        best_over: Optional[List[str]] = None
        for model in host_models:
            over = []
            if cpus > model.cpus:
                over.append(f"cpus {cpus} > {model.cpus}")
            if mem > model.memory_mb:
                over.append(f"memory {mem}MB > {model.memory_mb}MB")
            if disk > model.disk_mb:
                over.append(f"disk {disk}MB > {model.disk_mb}MB")
            if not over:
                best_over = None
                break
            if best_over is None or len(over) < len(best_over):
                best_over = over
        if best_over:
            out.append(Finding(
                rel, anchor(pod.type), "spec-resources",
                f"pod {pod.type!r}: one instance needs "
                + ", ".join(best_over)
                + " — exceeds any single host" + hint,
            ))
    return out
