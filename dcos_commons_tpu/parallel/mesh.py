"""Device mesh construction + named sharding helpers.

Axes vocabulary (scaling-book conventions):
    dcn   cross-slice data parallel — batch split ACROSS ICI slices,
          gradient allreduce rides the data-center network (the only
          collective that should: params replicate over dcn)
    dp    data parallel — batch split, gradient allreduce
    fsdp  fully-sharded data parallel — params/optimizer sharded,
          all-gathered per layer
    ep    expert parallel — MoE experts split, all_to_all dispatch
    pp    pipeline parallel — layer stages split, ppermute activations
    tp    tensor parallel — heads/ffn split, activation collectives
    sp    sequence/context parallel — ring attention over sequence
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape; axes with size 1 are kept (harmless)."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1
    dcn: int = 1

    @property
    def total(self) -> int:
        return (self.dcn * self.dp * self.fsdp * self.tp * self.sp
                * self.pp * self.ep)

    def axes(self) -> Dict[str, int]:
        return {
            "dcn": self.dcn,
            "dp": self.dp,
            "fsdp": self.fsdp,
            "ep": self.ep,
            "pp": self.pp,
            "sp": self.sp,
            "tp": self.tp,
        }


def make_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh whose device order follows the hardware order.

    jax puts same-host devices adjacent in jax.devices(); keeping the
    fastest-varying mesh axis (tp) innermost maps tp collectives onto
    intra-host ICI first — the scaling-book layout rule.
    """
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < spec.total:
        raise ValueError(
            f"mesh {spec} needs {spec.total} devices, have {len(devices)}"
        )
    devices = devices[: spec.total]
    # tp innermost (intra-host ICI), then sp ring, then pp neighbors,
    # then ep all_to_alls; dp/fsdp outer, and dcn OUTERMOST — jax
    # orders devices slice-by-slice, so the leading axis is exactly
    # the slice boundary and only dcn collectives cross it
    arr = np.array(devices).reshape(
        spec.dcn, spec.dp, spec.fsdp, spec.ep, spec.pp, spec.sp, spec.tp
    )
    return Mesh(arr, ("dcn", "dp", "fsdp", "ep", "pp", "sp", "tp"))


def mesh_from_env(env: Dict[str, str], n_devices: Optional[int] = None) -> Mesh:
    """Derive a mesh from the scheduler's env contract.

    TPU_TOPOLOGY "XxY" at TPU_CHIPS_PER_HOST chips/host: default to
    dp over hosts x tp within host — the layout the torus placement
    guarantees is ICI-contiguous.
    """
    n = n_devices if n_devices is not None else len(jax.devices())
    chips_per_host = int(env.get("TPU_CHIPS_PER_HOST", "0") or 0)
    n_slices = int(env.get("TPU_NUM_SLICES", "1") or 1)
    if n_slices > 1 and n % n_slices == 0:
        # multi-slice gang: dcn (pure data parallel) over the slice
        # boundary, dp x tp within each slice over ICI
        per_slice = n // n_slices
        if chips_per_host and per_slice % chips_per_host == 0 \
                and per_slice >= chips_per_host:
            return make_mesh(MeshSpec(
                dcn=n_slices,
                dp=per_slice // chips_per_host,
                tp=chips_per_host,
            ))
        return make_mesh(MeshSpec(dcn=n_slices, dp=per_slice))
    if chips_per_host and n % chips_per_host == 0 and n > chips_per_host:
        return make_mesh(
            MeshSpec(dp=n // chips_per_host, tp=chips_per_host)
        )
    return make_mesh(MeshSpec(dp=n))


# -- sharding rules ---------------------------------------------------

Rules = Tuple[Tuple[str, PartitionSpec], ...]


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


BATCH_AXES = ("dcn", "dp", "fsdp")  # batch shards over all data axes


def batch_spec() -> PartitionSpec:
    return PartitionSpec(BATCH_AXES, "sp")  # [batch, seq, ...]


def replicated() -> PartitionSpec:
    return PartitionSpec()
