"""X3: metrics — counters, gauges, Prometheus text exposition.

Reference: sdk/scheduler/.../metrics/Metrics.java:26-97 (Dropwizard
registry, StatsD push, Prometheus + codahale scrape endpoints; offer/
revive/decline/suppress/operation/status counters) and
PlanReporter.java (per-plan status gauges).
"""

from dcos_commons_tpu.metrics.registry import (
    MetricHistory,
    Metrics,
    prometheus_name,
)

__all__ = ["MetricHistory", "Metrics", "prometheus_name"]
