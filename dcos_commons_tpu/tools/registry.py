"""Package registry: publish and install-from-registry.

Reference: tools/publish_http.py (serve built artifacts over HTTP so
clusters install without cloud credentials) and
tools/release_builder.py (immutable, digest-indexed releases).  A
registry is a directory holding artifacts plus an ``index.json``:

    {"packages": {name: {version: {"artifact": "<file>",
                                   "sha256": "<hex>",
                                   "description": "..."}}}}

used either directly by path (a shared filesystem / airgapped USB
drop) or served over HTTP:

    GET /v1/registry/index              -> the index
    GET /v1/registry/artifacts/<file>   -> artifact bytes
    PUT /v1/registry/artifacts/<file>   -> publish (bearer-gated)

Releases are IMMUTABLE: republishing a (name, version) with different
bytes is rejected — release_builder's stable-artifact rule; bump the
version instead.  Install verifies the artifact's digest against the
index before anything reaches the scheduler.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
from typing import Dict, Optional, Tuple

from dcos_commons_tpu.tools.packaging import (
    PackageError,
    read_manifest,
)

INDEX_NAME = "index.json"
ARTIFACT_DIR = "artifacts"


def _is_http(registry: str) -> bool:
    return registry.startswith(("http://", "https://"))


def _artifact_name(name: str, version: str) -> str:
    for field, value in (("name", name), ("version", version)):
        if not re.fullmatch(r"[A-Za-z0-9._-]+", value or ""):
            raise PackageError(
                f"package {field} {value!r} is not registry-safe "
                "([A-Za-z0-9._-] only)"
            )
    return f"{name}-{version}.tar.gz"


def _version_key(version: str):
    """Order '0.10.2' above '0.9.9' (numeric segments compare as
    ints) and a RELEASE above its own prereleases ('1.0.0' outranks
    '1.0.0-rc1' — semver's prerelease rule; naive list comparison
    would resolve the rc as "latest")."""
    pieces = re.split(r"[.\-+]", version)
    core = []
    i = 0
    while i < len(pieces) and pieces[i].isdigit():
        core.append(int(pieces[i]))
        i += 1
    pre = pieces[i:]
    return (
        core,
        1 if not pre else 0,  # release > any prerelease of same core
        [(0, int(p)) if p.isdigit() else (1, p) for p in pre],
    )


def _load_index(path: str) -> Dict:
    if not os.path.exists(path):
        return {"packages": {}}
    with open(path, "r", encoding="utf-8") as f:
        try:
            index = json.load(f)
        except ValueError as e:
            raise PackageError(f"corrupt registry index {path}: {e}")
    index.setdefault("packages", {})
    return index


def _registry_lock(root: str):
    """Context manager: the registry's advisory index lock.  Every
    index read-modify-write (publish, prune) must hold it — in the
    documented shared-filesystem mode a concurrent writer's
    os.replace would otherwise erase this writer's entry.  (The HTTP
    path serializes in-process on top of this.)"""
    import contextlib

    @contextlib.contextmanager
    def _held():
        with contextlib.ExitStack() as stack:
            try:
                import fcntl

                lock = stack.enter_context(
                    open(os.path.join(root, ".index.lock"), "a+")
                )
                fcntl.flock(lock.fileno(), fcntl.LOCK_EX)
            except ImportError:  # pragma: no cover — non-POSIX
                pass
            yield

    return _held()


def _store_index(path: str, index: Dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(index, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _http_call(
    url: str, *, data: Optional[bytes] = None, method: str = "GET",
    token: str = "", timeout: float = 60.0,
):
    import urllib.request

    headers = {}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    if data is not None:
        headers["Content-Type"] = "application/octet-stream"
    req = urllib.request.Request(
        url, data=data, method=method, headers=headers
    )
    return urllib.request.urlopen(req, timeout=timeout)


# -- publish ----------------------------------------------------------


def publish_package(
    package_path: str, registry: str, token: str = ""
) -> Dict:
    """Publish a built package into a registry (dir path or HTTP URL).
    Returns {"name", "version", "sha256", "artifact"}."""
    with open(package_path, "rb") as f:
        payload = f.read()
    manifest = read_manifest(package_path)  # validates it IS a package
    name, version = manifest["name"], manifest.get("version", "0.0.0")
    artifact = _artifact_name(name, version)
    digest = hashlib.sha256(payload).hexdigest()
    if _is_http(registry):
        import urllib.error

        try:
            with _http_call(
                f"{registry.rstrip('/')}/v1/registry/artifacts/{artifact}",
                data=payload, method="PUT", token=token,
            ) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            raise PackageError(
                f"registry rejected publish: {e.read().decode('utf-8')}"
            )
        except urllib.error.URLError as e:
            raise PackageError(f"registry unreachable at {registry}: {e}")
    return _publish_local(registry, artifact, payload, manifest, digest)


def _publish_local(
    root: str, artifact: str, payload: bytes, manifest: Dict, digest: str
) -> Dict:
    os.makedirs(os.path.join(root, ARTIFACT_DIR), exist_ok=True)
    index_path = os.path.join(root, INDEX_NAME)
    with _registry_lock(root):
        return _publish_local_locked(
            root, index_path, artifact, payload, manifest, digest
        )


def _publish_local_locked(
    root: str, index_path: str, artifact: str, payload: bytes,
    manifest: Dict, digest: str,
) -> Dict:
    name, version = manifest["name"], manifest.get("version", "0.0.0")
    index = _load_index(index_path)
    existing = index["packages"].get(name, {}).get(version)
    if existing is not None:
        if existing["sha256"] == digest:
            return {  # idempotent re-publish of identical bytes
                "name": name, "version": version,
                "sha256": digest, "artifact": artifact,
            }
        raise PackageError(
            f"{name} {version} is already published with different "
            "bytes — releases are immutable, bump the version"
        )
    tombstone = index.get("tombstones", {}).get(name, {}).get(version)
    if tombstone is not None and tombstone != digest:
        # a PRUNED version stays burned: clients that pinned it must
        # never see different bytes under the same (name, version);
        # republishing the original bytes restores it
        raise PackageError(
            f"{name} {version} was pruned from this registry and its "
            "digest is tombstoned — releases are immutable even after "
            "pruning; bump the version"
        )
    artifact_path = os.path.join(root, ARTIFACT_DIR, artifact)
    tmp = artifact_path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, artifact_path)
    index["packages"].setdefault(name, {})[version] = {
        "artifact": artifact,
        "sha256": digest,
        "description": manifest.get("description", ""),
    }
    _store_index(index_path, index)
    return {
        "name": name, "version": version,
        "sha256": digest, "artifact": artifact,
    }


def prune_registry(
    registry: str, keep: int, name: str = "", grace_s: float = 0.0,
) -> Dict:
    """Retire old releases (release_builder's lifecycle cleanup): for
    each package — or just ``name`` — keep the newest ``keep``
    versions by the semver ordering and drop the rest from the index,
    deleting artifact files no retained release references.  Runs on
    the registry HOST directory (the same place publishes land in
    shared-filesystem mode); an HTTP URL is refused — pruning is a
    registry-admin operation, not a client verb.  Returns
    {package: [pruned versions]}.  Immutability SURVIVES the prune:
    each pruned (name, version) leaves a digest TOMBSTONE in the
    index, so republishing different bytes under it is still
    rejected (republishing the original bytes restores it).

    CONCURRENT-READER CAVEAT: a ``RegistryServer`` (or a shared-
    filesystem client) may be mid-fetch of an artifact this prune
    just unreferenced.  On POSIX local disk the open stream survives
    the unlink, but in the documented NFS mode deleting the file a
    client is streaming yields TRUNCATED reads or stale-handle
    errors, not a clean 404.  Either quiesce fetches around the
    prune, or pass ``grace_s`` > 0: unreferenced artifacts are then
    RENAMED to ``<file>.trash-<epoch>-<grace>`` (dropping them from
    the index and from fetch immediately) and only unlinked by a
    LATER prune once the window RECORDED IN THE NAME has elapsed (a
    later prune with a smaller ``grace_s`` cannot shorten an earlier
    prune's promise) — any fetch that resolved the old index entry
    before the prune has ``grace_s`` seconds to finish streaming."""
    if _is_http(registry):
        raise PackageError(
            "prune runs on the registry host's directory, not over "
            "HTTP — ssh to the registry and pass its --dir path"
        )
    if keep < 1:
        raise PackageError(f"--keep must be >= 1, got {keep}")
    if not os.path.isdir(registry):
        raise PackageError(
            f"registry directory {registry!r} not found"
        )
    index_path = os.path.join(registry, INDEX_NAME)
    with _registry_lock(registry):
        index = _load_index(index_path)
        if name and name not in index["packages"]:
            raise PackageError(f"package {name!r} not in the registry")
        pruned: Dict = {}
        for pkg, versions in index["packages"].items():
            if name and pkg != name:
                continue
            ordered = sorted(versions, key=_version_key)
            for version in ordered[:-keep]:
                pruned.setdefault(pkg, []).append(version)
                # the tombstone carries the digest forward: pruning
                # must not reopen the (name, version) namespace to
                # different bytes
                index.setdefault("tombstones", {}).setdefault(
                    pkg, {}
                )[version] = versions[version]["sha256"]
                del versions[version]
        if pruned:
            _store_index(index_path, index)
        elif grace_s <= 0:
            return {}
        # delete artifacts nothing retained references (a file can be
        # shared only by index entries; recompute the live set).  With
        # a grace window, dead artifacts are parked as .trash-<epoch>
        # first (invisible to fetch, bytes intact for in-flight
        # readers) and reaped by whichever prune runs after the
        # window — so this block also runs when nothing was pruned,
        # to reap earlier prunes' leavings.
        import time

        now = time.time()
        live = {
            entry["artifact"]
            for versions in index["packages"].values()
            for entry in versions.values()
        }
        artifact_dir = os.path.join(registry, ARTIFACT_DIR)
        if os.path.isdir(artifact_dir):
            for fname in os.listdir(artifact_dir):
                path = os.path.join(artifact_dir, fname)
                if ".trash-" in fname:
                    # the window a parked file was PROMISED rides in
                    # its name (.trash-<epoch>-<grace>): a later prune
                    # run with a smaller --grace-s must not break the
                    # promise an earlier one made to in-flight readers
                    try:
                        parts = fname.rsplit(".trash-", 1)[1].split("-")
                        parked = float(parts[0])
                        promised = float(parts[1]) if len(parts) > 1 \
                            else 0.0
                    except (ValueError, IndexError):
                        parked = promised = 0.0
                    if now - parked >= promised:
                        try:
                            os.remove(path)
                        except OSError:
                            pass
                    continue
                if fname not in live and not fname.endswith(".tmp"):
                    try:
                        if grace_s > 0:
                            os.rename(
                                path,
                                f"{path}.trash-{int(now)}-{int(grace_s)}",
                            )
                        else:
                            os.remove(path)
                    except OSError:
                        pass
        return pruned


# -- resolve / fetch --------------------------------------------------


def registry_index(registry: str, token: str = "") -> Dict:
    if _is_http(registry):
        import urllib.error

        try:
            with _http_call(
                f"{registry.rstrip('/')}/v1/registry/index", token=token
            ) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            # HTTPError IS-A URLError: without this arm a reachable
            # server's 404/500 would read as "unreachable"
            raise PackageError(
                f"registry error {e.code} at {registry}: "
                f"{e.read().decode('utf-8', 'replace')[:200]}"
            )
        except urllib.error.URLError as e:
            raise PackageError(f"registry unreachable at {registry}: {e}")
    return _load_index(os.path.join(registry, INDEX_NAME))


def fetch_package(
    registry: str, name: str, version: str = "", token: str = ""
) -> Tuple[str, bytes]:
    """Resolve ``name`` (latest version unless pinned) and return
    (version, payload) with the payload digest-verified against the
    index — a tampered artifact never reaches the scheduler."""
    index = registry_index(registry, token=token)
    versions = index.get("packages", {}).get(name)
    if not versions:
        known = sorted(index.get("packages", {}))
        raise PackageError(
            f"package {name!r} not in registry (has: {known})"
        )
    if not version:
        version = max(versions, key=_version_key)
    entry = versions.get(version)
    if entry is None:
        raise PackageError(
            f"{name} has no version {version!r} "
            f"(has: {sorted(versions, key=_version_key)})"
        )
    if _is_http(registry):
        import urllib.error

        try:
            with _http_call(
                f"{registry.rstrip('/')}/v1/registry/artifacts/"
                f"{entry['artifact']}",
                token=token,
            ) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            raise PackageError(
                f"registry error {e.code} fetching {entry['artifact']} "
                f"from {registry}"
            )
        except urllib.error.URLError as e:
            raise PackageError(f"registry unreachable at {registry}: {e}")
    else:
        with open(
            os.path.join(registry, ARTIFACT_DIR, entry["artifact"]), "rb"
        ) as f:
            payload = f.read()
    digest = hashlib.sha256(payload).hexdigest()
    if digest != entry["sha256"]:
        raise PackageError(
            f"artifact digest mismatch for {name} {version}: the "
            "registry copy does not match its index"
        )
    return version, payload


# -- HTTP registry server ---------------------------------------------


class RegistryServer:
    """Serve a registry directory over HTTP (publish_http.py spirit).

    Reads are open; publish (PUT) requires the bearer token when one
    is set.  Publishing re-validates the payload as a package and goes
    through the same immutability gate as local publish."""

    def __init__(
        self, root: str, port: int = 0, bind: str = "127.0.0.1",
        auth_token: str = "",
    ):
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._write_lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code: int, payload: bytes,
                       content_type: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _reply_json(self, code: int, body: dict) -> None:
                self._reply(code, json.dumps(body).encode("utf-8"))

            def do_GET(self):
                if self.path == "/v1/registry/index":
                    index = _load_index(
                        os.path.join(server.root, INDEX_NAME)
                    )
                    self._reply_json(200, index)
                    return
                prefix = "/v1/registry/artifacts/"
                if self.path.startswith(prefix):
                    name = os.path.basename(self.path[len(prefix):])
                    path = os.path.join(server.root, ARTIFACT_DIR, name)
                    if not os.path.isfile(path):
                        self._reply_json(404, {"error": f"no {name}"})
                        return
                    with open(path, "rb") as f:
                        self._reply(
                            200, f.read(), "application/octet-stream"
                        )
                    return
                self._reply_json(404, {"error": "unknown route"})

            def do_PUT(self):
                if auth_token:
                    got = self.headers.get("Authorization", "")
                    if got != f"Bearer {auth_token}":
                        self._reply_json(
                            401, {"error": "publish requires the token"}
                        )
                        return
                prefix = "/v1/registry/artifacts/"
                if not self.path.startswith(prefix):
                    self._reply_json(404, {"error": "unknown route"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                payload = self.rfile.read(length)
                try:
                    manifest = _manifest_of_bytes(payload)
                    digest = hashlib.sha256(payload).hexdigest()
                    artifact = _artifact_name(
                        manifest["name"],
                        manifest.get("version", "0.0.0"),
                    )
                    if os.path.basename(self.path[len(prefix):]) != \
                            artifact:
                        raise PackageError(
                            f"artifact name must be {artifact} for this "
                            "package's manifest"
                        )
                    with server._write_lock:
                        out = _publish_local(
                            server.root, artifact, payload, manifest,
                            digest,
                        )
                    self._reply_json(200, out)
                except PackageError as e:
                    self._reply_json(409, {"error": str(e)})

        self._server = ThreadingHTTPServer((bind, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "RegistryServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="registry",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)


def _manifest_of_bytes(payload: bytes) -> Dict:
    import io
    import tarfile

    from dcos_commons_tpu.tools.packaging import MANIFEST_NAME

    try:
        with tarfile.open(
            fileobj=io.BytesIO(payload), mode="r:gz"
        ) as tar:
            member = tar.extractfile(MANIFEST_NAME)
            if member is None:
                raise PackageError("no manifest in upload")
            return json.loads(member.read().decode("utf-8"))
    except (tarfile.TarError, KeyError, ValueError, OSError) as e:
        raise PackageError(f"upload is not a package: {e}")
