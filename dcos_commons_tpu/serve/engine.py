"""Continuous-batching engines (model-agnostic half): the slot pool
and the paged KV arena that replaced it as the serving default.

The dispatch-per-group serve loop ran one whole ``generate`` per
micro-batch: a request arriving one step after a dispatch started
waited the FULL previous generation before its prefill even began,
and every row padded out to the group's longest generation.
``SlotEngine`` replaces that loop with per-step scheduling over a
persistent slot pool; ``PagedEngine`` (ISSUE 11) further replaces
the pool's row carve with a page-budgeted arena — block-granular KV
through per-request page tables, chunked prefill interleaved with
decode, refcounted prefix caching (serve/paging.py) — while sharing
this loop's admission/retirement/telemetry machinery and keeping
greedy outputs token-identical.  The slot-pool shape:

* the KV cache is allocated ONCE at ``SLOTS x max_len`` (static
  shapes — XLA never recompiles as occupancy changes);
* waiting requests are admitted into free slots at EVERY decode step
  (prefill-into-slot, models/decode.py), so p95 time-to-first-token is
  O(one decode tick + own prefill) instead of O(a whole generation);
* finished rows (per-row EOS / max-token / cache-exhausted) retire
  their slot IMMEDIATELY — the pool never pads a short answer out to
  the longest row, which is where the mean-to-max generation-length
  throughput win comes from (bench.py bench_continuous_serve).

The engine is model-agnostic and jax-free: the device half is two
injected callables (the single-chip server binds them straight to a
``serve.pool.PoolModel``; the gang driver wraps them in ADMIT/DECODE
broadcast ticks so every rank steps the same program).  Liveness
rules inherited from ``utils/microbatch.py`` (which this subsumes for
both servers): FIFO admission order, queue-timeout removal (abandoned
work never reaches the chip — an active abandoned row retires at the
next tick, freeing its slot early), and an ``on_idle`` hook so an
SPMD gang keeps meeting in collectives with no traffic.

Serving load telemetry: ``stats()`` reports queue depth, active
slots, KV occupancy, tokens/s and TTFT percentiles; ``
register_metrics`` exports the gauges through a metrics registry
(StatsD/Prometheus), and ``stats_path`` mirrors them to
``servestats.json`` in the task sandbox, where the scheduler's
``GET /v1/debug/serving`` collects them per pod — the load signal
ROADMAP item 2 names for scale-out decisions.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence

import numpy as np

from dcos_commons_tpu.utils.microbatch import QueueTimeoutError

SERVESTATS_NAME = "servestats.json"
_TTFT_WINDOW = 512      # TTFT samples kept for the percentile gauges
_RATE_WINDOW_S = 10.0   # tokens/s sliding window


class _Group:
    """One ``submit()`` call: N rows answered together."""

    __slots__ = ("rows", "remaining", "done", "error", "abandoned")

    def __init__(self, rows: List["_Row"]):
        self.rows = rows
        self.remaining = len(rows)
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.abandoned = False


class _Row:
    """One prompt riding one KV slot."""

    __slots__ = (
        "tokens", "n", "temp", "eos", "seed", "out", "group",
        "arrival", "slot", "rid", "frozen",
    )

    def __init__(self, tokens, n, temp, eos, seed, group):
        self.tokens = tokens
        self.n = n
        self.temp = temp
        self.eos = eos
        self.seed = seed
        self.out: List[int] = []
        self.group = group
        self.arrival = time.monotonic()
        self.slot = -1
        # migration identity/fence (serve/migration.py, ISSUE 16):
        # rid is the pod-local session id; a frozen row holds its
        # slot and pages but is excluded from every dispatch until
        # unfrozen, released to a peer, or activated after a splice
        self.rid = -1
        self.frozen = False


class SlotEngine:
    """Admission loop over a persistent slot-pool KV cache.

    ``prefill_fn(padded [1, prompt_len] i32, slot=, true_len=, temp=,
    seed=) -> first token`` runs one prompt into a pool row (the
    scalars are passed by KEYWORD — transposing slot and true_len is
    a silent cache corruption);
    ``decode_fn(tok [S] i32, pos [S] i32, temps [S] f32, seeds [S]
    i32, n_active) -> next tokens [S] i32`` advances EVERY row one
    step (inactive rows are parked at slot state (0, 0) — their
    computation is discarded and their cache row is fully overwritten
    by the next admission's prefill).  Both run OUTSIDE the engine
    lock; only host-side bookkeeping holds it.
    """

    _row_cls = _Row
    # gauges register_metrics exports (subclasses extend)
    METRIC_KEYS = (
        "queue_depth", "active_slots", "kv_occupancy", "tokens_per_s",
    )

    def __init__(
        self,
        prefill_fn: Callable,
        decode_fn: Callable,
        slots: int,
        max_len: int,
        prompt_len: int,
        queue_timeout_s: float = 600.0,
        on_idle: Optional[Callable[[], None]] = None,
        idle_every_s: float = 0.05,
        stats_path: Optional[str] = None,
        stats_every_s: float = 1.0,
        log: Optional[Callable[[str], None]] = None,
        extra_stats: Optional[dict] = None,
    ):
        if slots < 1:
            raise ValueError(f"slot pool needs >= 1 slot, got {slots}")
        self._prefill_fn = prefill_fn
        self._decode_fn = decode_fn
        self._slots = slots
        self._max_len = max_len
        self._prompt_len = prompt_len
        self._queue_timeout_s = queue_timeout_s
        self._on_idle = on_idle
        self._idle_every_s = idle_every_s
        self._stats_path = stats_path
        self._stats_every_s = stats_every_s
        self._log = log

        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._rows: List[Optional[_Row]] = [None] * slots
        self._free = list(range(slots - 1, -1, -1))  # pop() -> slot 0 first
        self._active = 0
        self._tok = np.zeros(slots, np.int32)
        self._pos = np.zeros(slots, np.int32)
        self._temps = np.zeros(slots, np.float32)
        self._seeds = np.zeros(slots, np.int32)
        self._stopped = False
        self._next_rid = 1  # session ids (migration's addressing unit)
        # telemetry (counters under the cv; deques pruned on append)
        self._admitted = 0
        self._completed = 0
        self._timeouts = 0
        self._timeouts_by_kind: dict = {}
        self._tokens_out = 0
        self._ttft: deque = deque(maxlen=_TTFT_WINDOW)
        self._rate: deque = deque()  # (monotonic, tokens) per tick
        self._merge_logged = False
        self._stats_written = 0.0  # loop-thread only
        # stats consumers may annotate the snapshot with facts the
        # engine cannot know (the worker's actually-bound HTTP port:
        # the /v1/endpoints advertisement, ISSUE 12).  Constructor-
        # passed extras precede the loop thread's first flush, so the
        # sandbox snapshot carries them from its very first write
        self._extra_stats: dict = dict(extra_stats or {})
        # loop-liveness stamp for the stats_age_s gauge: the router
        # and HealthMonitor discard gauges whose engine stopped
        # ticking instead of balancing on a wedged pod's last-good
        # numbers.  Stamped at every loop wake AND at submit-time
        # enqueue (an idle engine is trivially responsive — its age
        # must start at the arrival, not at the end of the idle gap)
        self._last_tick_mono = time.monotonic()
        self._thread = threading.Thread(
            target=self._loop, name="slot-engine", daemon=True
        )
        self._thread.start()

    # -- client surface ----------------------------------------------

    def submit(
        self,
        rows: Sequence[Sequence[int]],
        max_new_tokens: int,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
    ) -> List[List[int]]:
        """Queue ``rows`` (each its own slot, admitted independently
        as slots free up — a multi-row request may overlap several
        pool generations) and block until every row finished.  Raises
        ``QueueTimeoutError`` on saturation (handlers map it to 503),
        ``ValueError`` on caller error (400)."""
        if not rows:
            raise ValueError("tokens must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        for row in rows:
            if len(row) < 1:
                raise ValueError("prompts must be non-empty")
            if len(row) > self._prompt_len:
                raise ValueError(
                    f"prompt length {len(row)} exceeds the server's "
                    f"context {self._prompt_len}"
                )
            if len(row) + max_new_tokens > self._max_len:
                raise ValueError(
                    f"prompt {len(row)} + {max_new_tokens} new tokens "
                    f"cannot fit the {self._max_len}-position slot"
                )
        group = _Group([])
        group.rows = [
            self._row_cls(
                [int(t) for t in row], max_new_tokens, float(temperature),
                eos_id,
                int.from_bytes(os.urandom(4), "little") % (2 ** 31),
                group,
            )
            for row in rows
        ]
        group.remaining = len(group.rows)
        with self._cv:
            now = time.monotonic()
            if not self._has_work_locked():
                # idle -> working transition: liveness is measured
                # from THIS arrival, not across the idle gap
                self._last_tick_mono = now
            for r in group.rows:
                r.rid = self._next_rid
                self._next_rid += 1
            self._queue.extend(group.rows)
            self._cv.notify_all()
        # the timeout bounds SATURATION, not a healthy generation: a
        # window with no row admitted (starved for a slot) or no new
        # token across the whole group (the pool stalled) abandons;
        # an admitted group that keeps producing is never cut off
        # mid-generation just for being long
        last_progress = -1
        while not group.done.wait(timeout=self._queue_timeout_s):
            with self._cv:
                admitted = any(r.slot >= 0 for r in group.rows)
                progress = self._progress_locked(group)
                if admitted and progress > last_progress:
                    last_progress = progress
                    continue
                # abandoned work never reaches the chip: queued rows
                # leave the queue NOW; already-active rows retire at
                # the next tick, freeing their slots early instead of
                # decoding a dead request to completion
                group.abandoned = True
                self._queue = deque(
                    r for r in self._queue if r.group is not group
                )
                reason, kind = self._timeout_reason_locked(
                    group, admitted
                )
                self._timeouts += 1
                self._timeouts_by_kind[kind] = (
                    self._timeouts_by_kind.get(kind, 0) + 1
                )
            raise QueueTimeoutError(reason, kind=kind)
        if group.error is not None:
            raise group.error
        return [list(r.out) for r in group.rows]

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=10)

    def _progress_locked(self, group: _Group) -> int:
        """Monotone per-group progress measure for the timeout loop
        (tokens produced; the paged engine adds prefilled positions —
        a long prompt mid-chunked-prefill IS making progress)."""
        return sum(len(r.out) for r in group.rows)

    def _timeout_reason_locked(self, group, admitted: bool):
        """(reason string, QueueTimeoutError kind) for a timed-out
        group — the 503 body and the split timeout counters."""
        if not admitted:
            return "request timed out waiting for a KV slot", "kv-slot"
        return (
            f"no decode progress in {self._queue_timeout_s}s", "stalled"
        )

    # -- telemetry ---------------------------------------------------

    def annotate_stats(self, **extra) -> None:
        """Attach static facts to every future ``stats()`` snapshot
        (the worker's actually-bound ``http_port``; anything the
        engine itself cannot know).  Keys must not collide with the
        engine's own gauges."""
        with self._cv:
            self._extra_stats.update(extra)

    def stats(self) -> dict:
        """Serving-load snapshot (the per-pod gauges ROADMAP item 2
        names as the scale-out signal)."""
        now = time.monotonic()
        with self._cv:
            # loop-liveness stamp: 0 while idle (a parked loop is
            # trivially responsive; admission wakes it), else the
            # time since the loop last proved alive — the wedge
            # signal the router's staleness gate keys on
            stats_age = (
                max(0.0, now - self._last_tick_mono)
                if self._has_work_locked() else 0.0
            )
            live_tokens = self._live_tokens_locked()
            window = [n for (t, n) in self._rate
                      if t > now - _RATE_WINDOW_S]
            ttft = sorted(self._ttft)
            kinds = self._timeouts_by_kind
            out = {
                "slots": self._slots,
                "max_len": self._max_len,
                "queue_depth": len(self._queue),
                "active_slots": self._active,
                "free_slots": len(self._free),
                "kv_live_tokens": live_tokens,
                "kv_occupancy": round(
                    live_tokens / float(self._kv_capacity()), 4
                ),
                "tokens_per_s": round(
                    sum(window) / _RATE_WINDOW_S, 2
                ),
                "requests_admitted": self._admitted,
                "requests_completed": self._completed,
                "requests_timed_out": self._timeouts,
                # the saturation split (utils/microbatch.py kinds):
                # memory = the paged arena's page budget never fit;
                # compute = no decode row freed / admitted but stalled
                "requests_timed_out_memory": kinds.get(
                    "kv-page-budget", 0
                ),
                "requests_timed_out_compute": (
                    kinds.get("kv-slot", 0) + kinds.get("stalled", 0)
                ),
                "tokens_out": self._tokens_out,
            }
            out["stats_age_s"] = round(stats_age, 4)
            out.update(self._stats_extra_locked())
            out.update(self._extra_stats)
        if ttft:
            from dcos_commons_tpu.metrics.registry import percentile

            out["ttft_p50_s"] = round(percentile(ttft, 50), 4)
            out["ttft_p95_s"] = round(percentile(ttft, 95), 4)
        out["t"] = time.time()
        return out

    def _live_tokens_locked(self) -> int:
        return int(sum(
            int(self._pos[s])
            for s, row in enumerate(self._rows) if row is not None
        ))

    def _kv_capacity(self) -> int:
        """KV positions the cache can hold (the occupancy basis)."""
        return self._slots * self._max_len

    def _stats_extra_locked(self) -> dict:
        return {}

    def register_metrics(self, metrics, prefix: str = "serving") -> None:
        """Export the load gauges through a metrics registry
        (metrics/registry.py): queue depth, active slots, KV
        occupancy, tokens/s — scraped as gauges / pushed via StatsD
        (the paged engine adds page-budget and prefix-cache gauges)."""
        for key in self.METRIC_KEYS:
            metrics.gauge(
                f"{prefix}.{key}",
                lambda key=key: self.stats()[key],
            )

    # -- the loop ----------------------------------------------------

    def _loop(self) -> None:
        # persists across iterations: the on_idle servers (gang) pass
        # through the outer loop once per idle TICK, and the terminal
        # flush must happen once per idle PERIOD, not at 20 Hz forever
        flushed_idle = False
        while True:
            idle = False
            flush_now = False
            admits: List[_Row] = []
            with self._cv:
                self._last_tick_mono = time.monotonic()
                while not self._has_work_locked() and not self._stopped:
                    if not flushed_idle:
                        # flush the terminal snapshot before parking:
                        # an idle server's LAST burst must be visible
                        # to /v1/debug/serving, not its second-to-last.
                        # The write itself happens OUTSIDE the lock —
                        # file IO on a slow sandbox must not block
                        # submit() callers needing the cv
                        flushed_idle = True
                        flush_now = True
                        break
                    if self._on_idle is None:
                        self._cv.wait()
                        self._last_tick_mono = time.monotonic()
                    else:
                        self._cv.wait(timeout=self._idle_every_s)
                        self._last_tick_mono = time.monotonic()
                        if not self._has_work_locked():
                            break  # fire on_idle OUTSIDE the lock
                if self._stopped:
                    return
                idle = not self._has_work_locked()
                if not idle:
                    flushed_idle = False  # work resumed: re-arm
                    admits = self._pop_admits_locked()
            if flush_now:
                self._write_stats(force=True)
                continue
            if idle:
                self._safe_idle()
                continue
            try:
                self._work_tick(admits)
                self._write_stats()
            except Exception as e:  # noqa: BLE001 — fail FAST, not silent
                # a bookkeeping bug (bad decode shape, broken stats
                # path) must not kill this thread silently: every
                # client would then block its full timeout and the
                # gang's followers would wedge in a stale collective.
                # Fan the error out and keep the loop alive.
                with self._cv:
                    self._fail_all_locked(e)

    def _has_work_locked(self) -> bool:
        return bool(self._queue) or self._active > 0

    def _work_tick(self, admits: List[_Row]) -> None:
        """One scheduling round (loop thread, OUTSIDE the cv): admit,
        then advance every active row one decode step."""
        self._admit_all(admits)
        if self._active:  # loop thread is the only writer
            self._decode_tick()

    def _pop_admits_locked(self) -> List[_Row]:
        """FIFO admission: oldest waiting rows take the free slots —
        a row can never starve behind later arrivals."""
        admits: List[_Row] = []
        while self._queue and self._free:
            row = self._queue.popleft()
            if row.group.abandoned:
                continue
            row.slot = self._free.pop()
            admits.append(row)
        return admits

    def _admit_all(self, admits: List[_Row]) -> None:
        for i, row in enumerate(admits):
            padded = np.zeros((1, self._prompt_len), np.int32)
            padded[0, : len(row.tokens)] = row.tokens
            try:
                first = int(self._prefill_fn(
                    padded, slot=row.slot, true_len=len(row.tokens),
                    temp=row.temp, seed=row.seed,
                ))
            except Exception as e:  # noqa: BLE001 — fan out, keep serving
                with self._cv:
                    # the popped-but-not-installed rows (this one and
                    # the rest of the batch) are invisible to both the
                    # queue and the active set: return their slots and
                    # fail their groups explicitly, or each failure
                    # would leak a slot and leave its client waiting
                    # out the full timeout for a model error
                    for r in admits[i:]:
                        self._free.append(r.slot)
                        r.slot = -1
                    self._fail_all_locked(
                        e, extra_groups={r.group for r in admits[i:]}
                    )
                return
            now = time.monotonic()
            with self._cv:
                self._apply_admit_locked(row, first, now)

    def _apply_admit_locked(self, row: _Row, first: int, now: float):
        self._admitted += 1
        self._ttft.append(now - row.arrival)
        row.out.append(first)
        self._count_tokens_locked(1, now)
        if self._row_finished(row, first, int(len(row.tokens))):
            self._retire_locked(row)
            return
        self._install_decode_locked(row)

    def _install_decode_locked(self, row: _Row) -> None:
        """Enter ``row`` into the decode set at its current progress
        — a fresh admission (out == [first]) and a spliced-in
        migrated session (out carries every token so far) resume
        through the same door: decode continues from (out[-1],
        plen + len(out) - 1), wherever that state was produced."""
        slot = row.slot
        self._rows[slot] = row
        self._active += 1
        self._tok[slot] = row.out[-1]
        self._pos[slot] = len(row.tokens) + len(row.out) - 1
        self._temps[slot] = row.temp
        self._seeds[slot] = row.seed

    _MERGE_NOUN = "slot pool"

    def _decode_prep_locked(self) -> tuple:
        """Extra positional args for ``decode_fn`` (before
        ``n_active``), prepared under the cv — the paged engine
        allocates write pages and snapshots the page tables here."""
        return ()

    def _decode_tick(self) -> None:
        with self._cv:
            extra = self._decode_prep_locked()
            active = self._active
            # who this tick actually computes for: a row installed
            # into a slot AFTER this point (a splice activation or a
            # migration-abort unfreeze, both peer threads) must not
            # be credited this tick's sample — it was computed from
            # the slot's previous state.  Frozen rows count as
            # not-dispatched: their table was zeroed above, so the
            # sample is trash even if they unfreeze mid-tick.
            dispatched = [
                r if (r is not None and not r.frozen) else None
                for r in self._rows
            ]
        try:
            nxt = np.asarray(self._decode_fn(
                self._tok.copy(), self._pos.copy(),
                self._temps.copy(), self._seeds.copy(),
                *extra, active,
            ))
        except Exception as e:  # noqa: BLE001 — fan out, keep serving
            with self._cv:
                self._fail_all_locked(e)
            return
        now = time.monotonic()
        merged = None
        with self._cv:
            self._apply_decode_locked(nxt, now, dispatched)
            if self._active >= 2 and not self._merge_logged:
                self._merge_logged = True
                merged = self._active
            elif self._active <= 1:
                self._merge_logged = False
        if merged is not None and self._log is not None:
            self._log(
                f"continuous-batch: {merged} rows sharing one decode "
                f"step over the {self._MERGE_NOUN}"
            )

    def _apply_decode_locked(self, nxt: np.ndarray, now: float,
                             dispatched=None) -> None:
        produced = 0
        for slot in range(self._slots):
            row = self._rows[slot]
            if row is None:
                continue
            if dispatched is not None and dispatched[slot] is not row:
                # not this tick's row (installed or unfrozen mid-tick
                # by a migration thread): its first real sample is
                # next tick's
                continue
            if row.frozen:
                # fenced for migration: this tick dispatched it with
                # a zero (trash) table row, so the sampled token is
                # discarded and (tok, pos) stand still — decode
                # resumes from the exact frozen state on whichever
                # pod ends up owning the session
                continue
            if row.group.abandoned:
                self._retire_locked(row)
                continue
            token = int(nxt[slot])
            row.out.append(token)
            produced += 1
            self._pos[slot] += 1
            self._tok[slot] = token
            if (self._row_finished(row, token, int(self._pos[slot]))):
                self._retire_locked(row)
        self._count_tokens_locked(produced, now)

    def _row_finished(self, row: _Row, token: int, pos: int) -> bool:
        return (
            len(row.out) >= row.n
            or (row.eos is not None and token == row.eos)
            or pos >= self._max_len  # slot cache exhausted
        )

    def _retire_locked(self, row: _Row) -> None:
        slot = row.slot
        if self._rows[slot] is row:
            self._rows[slot] = None
            self._active -= 1
            self._tok[slot] = 0
            self._pos[slot] = 0
            self._temps[slot] = 0.0
            self._seeds[slot] = 0
        self._free.append(slot)
        group = row.group
        group.remaining -= 1
        if group.remaining <= 0 and not group.abandoned:
            self._completed += 1
            group.done.set()

    def _fail_all_locked(
        self, error: BaseException, extra_groups=(),
    ) -> None:
        """A model-call failure fans out to every waiting and active
        request (the MicroBatcher contract) and clears the pool.
        ``extra_groups``: groups of rows in admission limbo (popped
        from the queue, not yet installed in the pool) — the caller
        has already returned their slots."""
        groups = {r.group for r in self._queue}
        groups |= {r.group for r in self._rows if r is not None}
        groups |= set(extra_groups)
        self._queue.clear()
        for slot, row in enumerate(self._rows):
            if row is not None:
                self._rows[slot] = None
                self._active -= 1
                self._free.append(slot)
        self._tok[:] = 0
        self._pos[:] = 0
        self._temps[:] = 0.0
        self._seeds[:] = 0
        for group in groups:
            group.error = error
            group.done.set()

    def _count_tokens_locked(self, n: int, now: float) -> None:
        if n <= 0:
            return
        self._tokens_out += n
        self._rate.append((now, n))
        while self._rate and self._rate[0][0] < now - _RATE_WINDOW_S:
            self._rate.popleft()

    def _safe_idle(self) -> None:
        try:
            self._on_idle()
        except Exception:  # noqa: BLE001, sdklint: disable=swallowed-exception — idle hook must not kill serving
            pass

    def _write_stats(self, force: bool = False) -> None:
        """Mirror the gauges to the sandbox (loop thread only): the
        scheduler's /v1/debug/serving reads this per task."""
        if self._stats_path is None:
            return
        now = time.monotonic()
        if not force and now - self._stats_written < self._stats_every_s:
            return
        self._stats_written = now
        try:
            tmp = self._stats_path + ".tmp"
            # durcheck: dur-file-discipline=telemetry mirror: loss on power failure is acceptable, the rename alone keeps readers partial-free
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.stats(), f)
            os.replace(tmp, self._stats_path)
        except OSError:
            pass  # sdklint: disable=swallowed-exception — telemetry must never take the server down


class _PagedRow(_Row):
    """A request riding a PAGE TABLE instead of a contiguous slot row
    (serve/paging.py): ``table[v]`` is the physical arena page holding
    virtual positions ``[v*P, (v+1)*P)``; 0 = unallocated."""

    __slots__ = (
        "table", "fill_pos", "admission", "private_pages",
        "registered_to",
    )

    def __init__(self, tokens, n, temp, eos, seed, group):
        super().__init__(tokens, n, temp, eos, seed, group)
        self.table = None            # np.int32 [M], built at admission
        self.fill_pos = 0            # next prompt position to prefill
        self.admission = None        # paging.Admission while admitted
        self.private_pages: List[int] = []
        self.registered_to = 0       # next prompt page to publish


class PagedEngine(SlotEngine):
    """Continuous batching over a PAGED KV arena: block-granular
    allocation, chunked prefill, and prefix caching (the ISSUE 11
    tentpole; vLLM's PagedAttention + SGLang's RadixAttention shape).

    Differences from the slot pool it replaces:

    * **Admission is page-budgeted** (serve/paging.py): a request
      enters the pool only when a free decode row exists AND its
      worst-case page need fits ``available - reserved`` — admitted
      work can never OOM mid-generation, and a short reply returns
      its unused pages immediately instead of stranding a MAX_LEN
      row.  FIFO stays strict: a budget-blocked head is never jumped
      by a smaller later request.
    * **Prefill is chunked**: prompts run ``chunk_tokens`` at a time
      — one chunk per PREFILLING REQUEST per engine tick, interleaved
      with decode — so a long prompt no longer blocks the tick it
      rides and queued requests stop paying head-of-line TTFT.
      Chunk progress counts as progress for the 503 timeout (a long
      prefill is not a stall).
    * **Prefix caching**: fully-prefilled prompt pages are published
      read-only; an identical later prefix pins them instead of
      recomputing (COW-by-recompute on mid-page divergence — shared
      pages are never written; see serve/paging.py).

    ``prefill_chunk_fn(padded [1, C] i32, slot=, table= [M] i32,
    start=, true_len=, temp=, seed=) -> first token`` runs one chunk
    (the return value is consumed only when the chunk completes the
    prompt); ``decode_fn(tok [S], pos [S], temps [S], seeds [S],
    tables [S, M] i32, n_active) -> next tokens [S]`` advances every
    row through its page table.  Scalars by KEYWORD, as ever.
    """

    _row_cls = _PagedRow
    METRIC_KEYS = SlotEngine.METRIC_KEYS + (
        "kv_pages_free", "prefix_cache_hit_rate",
        "prefill_chunk_backlog", "migrations_in", "migrations_out",
    )

    def __init__(
        self,
        prefill_chunk_fn: Callable,
        decode_fn: Callable,
        slots: int,
        max_len: int,
        prompt_len: int,
        *,
        page_tokens: int,
        pages: int,
        chunk_tokens: int,
        prefix_cache: bool = True,
        role: str = "unified",
        read_page: Optional[Callable] = None,
        write_page: Optional[Callable] = None,
        handoff: Optional[Callable] = None,
    **kw,
    ):
        from dcos_commons_tpu.serve.paging import (
            PageAllocator,
            pages_for,
        )

        # subclass state FIRST: the base constructor starts the loop
        # thread as its last act, and the loop reads these
        self._page_tokens = int(page_tokens)
        self._pages_per_row = pages_for(int(max_len), int(page_tokens))
        self._chunk_tokens = int(chunk_tokens)
        self._allocator = PageAllocator(
            int(pages), int(page_tokens), prefix_cache
        )
        self._prefilling: deque = deque()
        # migration state (serve/migration.py, ISSUE 16).  role is
        # the pod's advertised serving posture (unified / prefill /
        # decode) — telemetry and routing read it; the HANDOFF hook's
        # presence is what actually diverts finished prefills.
        # read_page/write_page are the device half of page mobility
        # (PagedPoolModel.export_page/import_page on real pods); both
        # run ONLY on the engine loop thread (_device_io), preserving
        # the single-device-caller discipline.
        self._role = str(role)
        self._read_page = read_page
        self._write_page = write_page
        self._handoff = handoff
        self._page_io: deque = deque()
        self._spliced: dict = {}    # rid -> parked row (pre-cutover)
        self._migrated: dict = {}   # rid -> spliced row (collectable)
        self._migrated_in = 0
        self._migrated_out = 0
        super().__init__(
            prefill_chunk_fn, decode_fn, slots, max_len, prompt_len,
            **kw,
        )

    # -- admission ---------------------------------------------------

    def _has_work_locked(self) -> bool:
        return (
            super()._has_work_locked()
            or bool(self._prefilling)
            or bool(self._page_io)
        )

    def _pop_admits_locked(self) -> List[_Row]:
        """FIFO admission under BOTH constraints — a free decode row
        and the page budget.  Strictly in order: the first request
        that does not fit blocks the queue (admitting a smaller later
        one would starve large requests forever)."""
        admits: List[_Row] = []
        while self._queue and self._free:
            row = self._queue[0]
            if row.group.abandoned:
                self._queue.popleft()
                continue
            admission = self._allocator.admit(row.tokens, row.n)
            if admission is None:
                break
            self._queue.popleft()
            row.slot = self._free.pop()
            row.admission = admission
            row.table = np.zeros(self._pages_per_row, np.int32)
            for i, entry in enumerate(admission.matched):
                row.table[i] = entry.page
            # prefill resumes past the cache-served pages
            row.fill_pos = len(admission.matched) * self._page_tokens
            row.registered_to = len(admission.matched)
            admits.append(row)
        return admits

    def _work_tick(self, admits: List[_Row]) -> None:
        self._run_page_io()
        if admits:
            with self._cv:
                self._prefilling.extend(admits)
        self._prefill_tick()
        if self._active:
            self._decode_tick()

    def _run_page_io(self) -> None:
        """Drain queued migration page reads/writes (loop thread,
        outside the cv — these are device calls like any dispatch)."""
        while True:
            with self._cv:
                if not self._page_io:
                    return
                job = self._page_io.popleft()
            job()

    # -- chunked prefill ---------------------------------------------

    def _prefill_tick(self) -> None:
        """Advance EVERY prefilling row by one chunk, FIFO order.

        Per-ROW chunking is the head-of-line fix: a long prompt costs
        several small dispatches interleaved with decode ticks instead
        of one prompt-wide dispatch that blocks the pool — while a
        BURST of short prompts still admits in one tick (each is one
        cheap chunk; serializing them across decode ticks would tax
        every short request one full decode per queue position).
        Per-tick prefill work stays bounded by the slot count — the
        same bound the slot pool's admit-all batch had, at chunk
        width instead of full prompt width."""
        with self._cv:
            rows = list(self._prefilling)
        for row in rows:
            with self._cv:
                if row.admission is None:
                    continue  # already retired/failed this tick
                if row.frozen:
                    continue  # fenced mid-prefill for migration
                if row.group.abandoned:
                    # abandoned before its first token: free the
                    # pages/slot now, nothing ever reached the client
                    self._prefilling.remove(row)
                    self._retire_locked(row)
                    continue
                plen = len(row.tokens)
                start = row.fill_pos
                clen = min(self._chunk_tokens, plen - start)
                self._ensure_pages_locked(row, start, start + clen - 1)
                table = row.table.copy()
            padded = np.zeros((1, self._chunk_tokens), np.int32)
            padded[0, :clen] = row.tokens[start:start + clen]
            first = self._prefill_fn(
                padded, slot=row.slot, table=table, start=start,
                true_len=clen, temp=row.temp, seed=row.seed,
            )
            now = time.monotonic()
            handoff_row = None
            with self._cv:
                row.fill_pos = start + clen
                self._register_pages_locked(row)
                if row.fill_pos >= plen:
                    if row.group.abandoned:
                        self._prefilling.remove(row)
                        self._retire_locked(row)
                    elif self._handoff is not None:
                        # disaggregation: the prompt is prefilled and
                        # its first token sampled — this pod's work
                        # is done.  Count admission/TTFT HERE (the
                        # destination replays neither), fence the row
                        # and ship it to a decode pod outside the cv
                        self._admitted += 1
                        self._ttft.append(now - row.arrival)
                        row.out.append(int(first))
                        self._count_tokens_locked(1, now)
                        if self._row_finished(row, int(first), plen):
                            self._prefilling.remove(row)
                            self._retire_locked(row)
                        else:
                            row.frozen = True
                            handoff_row = row
                    else:
                        self._prefilling.remove(row)
                        self._apply_admit_locked(row, int(first), now)
            if handoff_row is not None:
                self._run_handoff(handoff_row)

    def _run_handoff(self, row) -> None:
        """Hand a finished prefill to the decode pool (loop thread,
        outside the cv).  Any pre-cutover failure falls back to
        decoding locally — a prefill pod degrades to unified rather
        than failing the request.  A post-cutover failure
        (ReleasePendingError) leaves the row frozen: the destination
        owns the session now, and resuming here would double-serve."""
        from dcos_commons_tpu.serve.migration import (
            ReleasePendingError,
        )

        try:
            ok = self._handoff(self, row.rid)
        except ReleasePendingError:
            if self._log is not None:
                self._log(
                    f"handoff of session {row.rid} cut over but "
                    "release failed; holding the frozen source row "
                    "for a retried release"
                )
            return
        except Exception as e:  # noqa: BLE001 — degrade, don't fail the request
            ok = None
            if self._log is not None:
                self._log(
                    f"prefill handoff failed ({e}); decoding locally"
                )
        if ok is None:
            with self._cv:
                if row.frozen:
                    self._unfreeze_locked(row)

    def _ensure_pages_locked(self, row, first_pos: int,
                             last_pos: int) -> None:
        """Allocate the pages covering positions [first_pos,
        last_pos] — drawn from the row's admission reservation, so
        this cannot fail for an admitted row."""
        for v in range(first_pos // self._page_tokens,
                       last_pos // self._page_tokens + 1):
            if row.table[v] == 0:
                page = self._allocator.alloc(row.admission)
                row.table[v] = page
                row.private_pages.append(page)

    def _register_pages_locked(self, row) -> None:
        """Publish every newly-completed FULL prompt page into the
        prefix cache.  The last (partial) prompt page stays private —
        decode keeps writing into it, and shared pages are read-only
        by contract."""
        p = self._page_tokens
        while ((row.registered_to + 1) * p <= row.fill_pos
               and (row.registered_to + 1) * p <= len(row.tokens)):
            v = row.registered_to
            page = int(row.table[v])
            toks = tuple(row.tokens[v * p:(v + 1) * p])
            if self._allocator.register(row.admission, toks, page):
                row.private_pages.remove(page)
            row.registered_to += 1

    # -- decode ------------------------------------------------------

    _MERGE_NOUN = "paged arena"

    def _decode_prep_locked(self) -> tuple:
        """Allocate this tick's write pages and snapshot every row's
        page table for the decode dispatch."""
        for slot, row in enumerate(self._rows):
            if row is None or row.group.abandoned or row.frozen:
                # an abandoned row retires at apply; its write this
                # tick lands in the trash page (table may miss the
                # next page — masked, discarded).  A FROZEN row gets
                # a zero table below: its pages must stop changing
                # the moment the migration fence drops
                continue
            pos = int(self._pos[slot])
            self._ensure_pages_locked(row, pos, pos)
        tables = np.zeros(
            (self._slots, self._pages_per_row), np.int32
        )
        for slot, row in enumerate(self._rows):
            if row is not None and not row.frozen:
                tables[slot] = row.table
        return (tables,)

    # -- migration (serve/migration.py, ISSUE 16) --------------------

    def _device_io(self, fn):
        """Run a page read/write on the loop thread (the engine's one
        device caller) and return its result.  Called FROM the loop
        thread (prefill handoff) it runs inline; from a migration
        thread it queues and blocks until the loop executes it."""
        from dcos_commons_tpu.serve.migration import MigrationError

        if threading.current_thread() is self._thread:
            return fn()
        done = threading.Event()
        box: dict = {}

        def job():
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised in the waiter
                box["error"] = e
            finally:
                done.set()

        with self._cv:
            if self._stopped:
                raise MigrationError("engine stopped")
            self._page_io.append(job)
            self._cv.notify_all()
        if not done.wait(timeout=60.0):
            raise MigrationError("page io stalled on the engine loop")
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _find_rid_locked(self, rid: int):
        for row in self._rows:
            if row is not None and row.rid == rid:
                return row
        for row in self._prefilling:
            if row.rid == rid:
                return row
        return None

    def sessions(self) -> List[dict]:
        """Live migratable sessions: rows holding pages that are not
        already fenced — the drain/rebalance work list."""
        out: List[dict] = []
        with self._cv:
            for row in self._prefilling:
                if not row.frozen and not row.group.abandoned:
                    out.append({
                        "rid": row.rid, "tokens": list(row.tokens),
                        "state": "prefill",
                        "pages": int(np.count_nonzero(row.table)),
                    })
            for row in self._rows:
                if (row is not None and not row.frozen
                        and not row.group.abandoned
                        and row.admission is not None):
                    out.append({
                        "rid": row.rid, "tokens": list(row.tokens),
                        "state": "decode",
                        "pages": int(np.count_nonzero(row.table)),
                    })
        return out

    def freeze(self, rid: int) -> None:
        """Fence a session: decode/prefill stop at the next tick
        boundary and its pages stop changing (the in-flight tick's
        write is idempotent — K/V at a position is a pure function of
        token and position — and its sampled token is discarded)."""
        from dcos_commons_tpu.serve.migration import MigrationError

        with self._cv:
            row = self._find_rid_locked(rid)
            if row is None or row.admission is None:
                raise MigrationError(f"no live session {rid} to freeze")
            row.frozen = True

    def unfreeze(self, rid: int) -> None:
        """Drop the fence: an aborted migration resumes exactly where
        it froze.  Silently a no-op when the session is gone (a
        failure fan-out already answered its client)."""
        with self._cv:
            row = self._find_rid_locked(rid)
            if row is None:
                return
            if row.frozen:
                self._unfreeze_locked(row)
            self._cv.notify_all()

    def _unfreeze_locked(self, row) -> None:
        row.frozen = False
        if row in self._prefilling and row.fill_pos >= len(row.tokens):
            # a prefill-COMPLETE fenced row (handoff path): it never
            # entered the decode set, so resuming means installing it
            self._prefilling.remove(row)
            if self._row_finished(
                row, row.out[-1], len(row.tokens) + len(row.out) - 1
            ):
                self._retire_locked(row)
            else:
                self._install_decode_locked(row)
        self._cv.notify_all()

    def export_frozen(self, rid: int):
        """Snapshot a frozen session for the wire: request + progress
        + every mapped page's payload, keyed by VIRTUAL index
        (physical ids never leave the pod).  Page reads run on the
        loop thread."""
        from dcos_commons_tpu.serve.migration import (
            MigrationError,
            SessionSnapshot,
        )

        if self._read_page is None:
            raise MigrationError(
                "no page reader bound (PagedEngine read_page=...)"
            )
        with self._cv:
            row = self._find_rid_locked(rid)
            if row is None or row.admission is None:
                raise MigrationError(f"no live session {rid} to export")
            if not row.frozen:
                raise MigrationError(
                    f"session {rid} is not frozen — export without a "
                    "fence would race decode"
                )
            plen = len(row.tokens)
            kv_end = (
                plen + len(row.out) - 1
                if row.fill_pos >= plen and row.out else row.fill_pos
            )
            pages = [
                (v, int(row.table[v]))
                for v in range(len(row.table)) if row.table[v] != 0
            ]
            meta = (
                list(row.tokens), row.n, row.temp, row.eos, row.seed,
                list(row.out), row.fill_pos,
            )
        payloads = self._device_io(
            lambda: [(v, self._read_page(p)) for v, p in pages]
        )
        tokens, n, temp, eos, seed, out, fill_pos = meta
        return SessionSnapshot(
            rid=rid, tokens=tokens, max_new=n, temperature=temp,
            eos=eos, seed=seed, out=out, fill_pos=fill_pos,
            kv_end=kv_end, page_tokens=self._page_tokens,
            pages=payloads, source=self._role,
        )

    def splice(self, snap) -> int:
        """Admit a migrated session under the SAME transactional rule
        a fresh request faces (paging.admit — worst-case reservation,
        prefix-cache matching), copy only the pages the local prefix
        cache cannot serve, and PARK the row.  Nothing decodes until
        ``activate``; ``abort_splice`` undoes everything.  Returns
        the destination-local rid."""
        from dcos_commons_tpu.serve.migration import MigrationError
        from dcos_commons_tpu.serve.paging import pages_for

        if self._write_page is None:
            raise MigrationError(
                "no page writer bound (PagedEngine write_page=...)"
            )
        if int(snap.page_tokens) != self._page_tokens:
            raise MigrationError(
                f"page geometry mismatch: snapshot has "
                f"{snap.page_tokens}-token pages, this arena "
                f"{self._page_tokens}"
            )
        plen = len(snap.tokens)
        if plen > self._prompt_len or plen + snap.max_new > self._max_len:
            raise MigrationError(
                f"session does not fit this pod's geometry "
                f"({plen}+{snap.max_new} vs {self._max_len})"
            )
        incoming = dict(snap.pages)
        with self._cv:
            if not self._free:
                raise MigrationError("no free decode row")
            admission = self._allocator.admit(snap.tokens, snap.max_new)
            if admission is None:
                raise MigrationError(
                    "page budget cannot admit the migrated session"
                )
            m = len(admission.matched)
            need = (
                pages_for(int(snap.kv_end), self._page_tokens)
                if snap.kv_end > 0 else 0
            )
            missing = [
                v for v in range(m, need) if v not in incoming
            ]
            if missing:
                self._allocator.retire(admission, [])
                raise MigrationError(
                    f"snapshot is missing pages {missing}"
                )
            group = _Group([])
            row = self._row_cls(
                list(snap.tokens), snap.max_new, snap.temperature,
                snap.eos, snap.seed, group,
            )
            group.rows = [row]
            group.remaining = 1
            row.rid = self._next_rid
            self._next_rid += 1
            row.slot = self._free.pop()
            row.admission = admission
            row.table = np.zeros(self._pages_per_row, np.int32)
            for i, entry in enumerate(admission.matched):
                row.table[i] = entry.page
            row.registered_to = m
            # the local cache may hold MORE of the prompt than the
            # source had prefilled — prefill resumes past it
            row.fill_pos = max(int(snap.fill_pos),
                               m * self._page_tokens)
            row.out = [int(t) for t in snap.out]
            row.frozen = True
            imports = []
            for v in range(m, need):
                page = self._allocator.alloc(admission)
                row.table[v] = page
                row.private_pages.append(page)
                imports.append((page, incoming[v]))
            self._spliced[row.rid] = row
            self._migrated[row.rid] = row
            if len(self._migrated) > 256:
                # uncollected finished sessions age out (a router
                # always collects; this bounds a buggy caller)
                for old_rid in [
                    r for r, rw in self._migrated.items()
                    if rw.group.done.is_set()
                ][:64]:
                    self._migrated.pop(old_rid, None)
            self._cv.notify_all()
        try:
            self._device_io(lambda: [
                self._write_page(p, payload) for p, payload in imports
            ])
        except BaseException:
            self.abort_splice(row.rid)
            raise
        return row.rid

    def activate(self, rid: int) -> None:
        """CUTOVER: the parked spliced row starts serving here.  Full
        prompt pages it carried are published to the prefix cache
        only now — after their payloads landed (registering sooner
        would let a concurrent admission pin an unwritten page)."""
        from dcos_commons_tpu.serve.migration import MigrationError

        with self._cv:
            row = self._spliced.pop(rid, None)
            if row is None:
                raise MigrationError(f"no spliced session {rid}")
            row.frozen = False
            self._register_pages_locked(row)
            self._migrated_in += 1
            plen = len(row.tokens)
            if row.fill_pos < plen:
                self._prefilling.append(row)  # resumes chunked prefill
            elif row.out and self._row_finished(
                row, row.out[-1], plen + len(row.out) - 1
            ):
                self._retire_locked(row)
            elif row.out:
                self._install_decode_locked(row)
            else:
                raise MigrationError(
                    f"spliced session {rid} has no resume point"
                )
            if not self._has_work_locked():
                self._last_tick_mono = time.monotonic()
            self._cv.notify_all()

    def abort_splice(self, rid: int) -> None:
        """Undo a splice that never activated: pages and slot return
        to the arena.  No-op when the rid is unknown (already
        activated or never spliced) — abort is best-effort."""
        with self._cv:
            row = self._spliced.pop(rid, None)
            if row is None:
                return
            self._migrated.pop(rid, None)
            self._free.append(row.slot)
            if row.admission is not None:
                self._allocator.retire(row.admission, row.private_pages)
                row.admission = None
                row.private_pages = []
                row.table = None

    def release_migrated(self, rid: int, *, moved_to: str,
                         dest_rid: int) -> None:
        """The protocol's last verb: after cutover, retire the frozen
        source row, free its pages, and answer its blocked client
        with ``SessionMigratedError`` naming the destination (the
        router follows with a collect request)."""
        from dcos_commons_tpu.serve.migration import (
            MigrationError,
            SessionMigratedError,
        )

        with self._cv:
            row = self._find_rid_locked(rid)
            if row is None:
                raise MigrationError(f"no session {rid} to release")
            if not row.frozen:
                raise MigrationError(
                    f"session {rid} is not frozen — release without a "
                    "fence would double-serve"
                )
            if row in self._prefilling:
                self._prefilling.remove(row)
            self._migrated_out += 1
            row.group.error = SessionMigratedError(
                rid, moved_to, dest_rid
            )
            self._retire_locked(row)

    def collect(self, rid: int,
                timeout: Optional[float] = None) -> List[int]:
        """Block until a migrated-in session finishes and return its
        FULL output — the tokens the source already produced plus
        everything decoded here, one seamless reply."""
        from dcos_commons_tpu.serve.migration import MigrationError

        with self._cv:
            row = self._migrated.get(rid)
        if row is None:
            raise MigrationError(
                f"no migrated session {rid} to collect"
            )
        wait_s = timeout if timeout is not None else self._queue_timeout_s
        if not row.group.done.wait(timeout=wait_s):
            raise QueueTimeoutError(
                "migrated session did not finish", kind="stalled"
            )
        with self._cv:
            self._migrated.pop(rid, None)
        if row.group.error is not None:
            raise row.group.error
        return list(row.out)

    # -- retirement / failure ----------------------------------------

    def _retire_locked(self, row) -> None:
        super()._retire_locked(row)
        if row.admission is not None:
            self._allocator.retire(row.admission, row.private_pages)
            row.admission = None
            row.private_pages = []
            row.table = None

    def _fail_all_locked(self, error, extra_groups=()) -> None:
        extra = set(extra_groups)
        extra |= {r.group for r in self._prefilling}
        for row in self._prefilling:
            self._free.append(row.slot)
            row.slot = -1
        self._prefilling.clear()
        # parked spliced rows die with everything else: their groups
        # error out so a blocked collect() unblocks, and their slots
        # return (allocator.reset() below reclaims the pages)
        extra |= {r.group for r in self._spliced.values()}
        for row in self._spliced.values():
            self._free.append(row.slot)
            row.slot = -1
            row.admission = None
        self._spliced.clear()
        super()._fail_all_locked(error, extra_groups=extra)
        # every admission died with its group: rebuild the arena
        # bookkeeping (the prefix cache's pages may hold K/V written
        # before the failure — integrity unknown, so drop them too)
        self._allocator.reset()

    # -- timeout basis / telemetry -----------------------------------

    def _progress_locked(self, group) -> int:
        # chunk progress counts: a long prompt mid-prefill must not
        # be cut off as "stalled" just because no token landed yet
        return super()._progress_locked(group) + sum(
            r.fill_pos for r in group.rows
        )

    def _timeout_reason_locked(self, group, admitted: bool):
        if not admitted:
            alloc = self._allocator
            budget_reason = (
                "request timed out waiting for the KV page budget "
                f"({alloc.free_pages} pages free of "
                f"{alloc.pages_total}, {alloc.reserved_pages} "
                "reserved)",
                "kv-page-budget",
            )
            own = next(
                (r for r in group.rows if r.slot < 0), group.rows[0]
            )
            if not alloc.would_admit(own.tokens, own.n):
                return budget_reason
            if not self._free:
                return (
                    "request timed out waiting for a KV slot",
                    "kv-slot",
                )
            # our own rows fit and decode rows are free, so the
            # starvation came from strict FIFO behind a blocked HEAD
            # (our rows left the queue before this ran): classify by
            # what blocks the head — a small request stuck behind a
            # big budget-blocked one is memory saturation too
            head = self._queue[0] if self._queue else None
            if head is not None and not alloc.would_admit(
                    head.tokens, head.n):
                return budget_reason
            return (
                "request timed out waiting for a KV slot", "kv-slot"
            )
        return super()._timeout_reason_locked(group, admitted)

    def _live_tokens_locked(self) -> int:
        return super()._live_tokens_locked() + sum(
            r.fill_pos for r in self._prefilling
        )

    def _kv_capacity(self) -> int:
        return self._allocator.pages_total * self._page_tokens

    def _stats_extra_locked(self) -> dict:
        out = self._allocator.stats()
        # PHYSICAL occupancy (overrides the base virtual-positions
        # gauge): shared prefix pages count once, not once per
        # pinning row — under heavy sharing the virtual sum can
        # exceed the arena and would falsely breach kv_occupancy_slo
        # while headroom exists.  Occupied = pages neither free nor
        # reclaimable-by-admission.
        alloc = self._allocator
        out["kv_occupancy"] = round(
            (alloc.pages_total - alloc.free_pages
             - alloc.reclaimable_pages) / float(alloc.pages_total),
            4,
        )
        out["kv_page_tokens"] = self._page_tokens
        out["prefill_chunk_tokens"] = self._chunk_tokens
        # prompt tokens not yet prefilled (queued + mid-chunk): the
        # chunked-prefill pressure signal — sustained growth means
        # prefill demand outruns the chunk-per-tick budget
        out["prefill_chunk_backlog"] = int(
            sum(len(r.tokens) - r.fill_pos for r in self._prefilling)
            + sum(len(r.tokens) for r in self._queue)
        )
        # migration surfaces (ISSUE 16): the pod's serving posture —
        # the router's role-aware placement and the role-aware health
        # gating (health/detectors.py) key on serving_role — and the
        # protocol's traffic counters for /v1/debug/serving
        out["serving_role"] = self._role
        out["migrations_in"] = self._migrated_in
        out["migrations_out"] = self._migrated_out
        return out


def read_servestats(path: str) -> dict:
    """Parse a worker's servestats.json; {} when absent/corrupt (a
    worker killed mid-replace leaves the previous snapshot or none)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return data if isinstance(data, dict) else {}
