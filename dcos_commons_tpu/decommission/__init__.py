"""Decommission: planned scale-down of pod instances.

Reference: scheduler/decommission/ — DecommissionPlanFactory builds
kill -> unreserve -> erase step sequences for pod instances that the
target config no longer covers (count shrunk, or the whole pod type
removed); resources drain through the same write-ahead discipline as
uninstall (DefaultScheduler.java:170-177,456-459,527-536).
"""

from dcos_commons_tpu.decommission.factory import (
    DECOMMISSION_PLAN_NAME,
    DecommissionPlanFactory,
    find_surplus_instances,
)

__all__ = [
    "DECOMMISSION_PLAN_NAME",
    "DecommissionPlanFactory",
    "find_surplus_instances",
]
