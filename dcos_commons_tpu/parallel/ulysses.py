"""Ulysses attention: all-to-all sequence/context parallelism.

The second long-context recipe (task brief: "ring attention OR
all-to-all sequence/context parallelism"; DeepSpeed-Ulysses is the
public pattern).  Where ring attention keeps the sequence sharded and
rotates K/V around the ``sp`` ring, Ulysses RESHAPES the parallelism
with two all_to_alls:

    [b, H, s/P, d]  --all_to_all-->  [b, H/P, s, d]
         (sequence sharded)              (heads sharded)

Each device then runs ordinary full-sequence attention — the in-repo
flash kernel (ops/attention.py) — over its H/P heads, and a second
all_to_all restores sequence sharding.  Two all_to_alls move the same
bytes a single ring rotation does, but in O(1) collective steps
instead of P ppermute hops, so Ulysses wins when the per-hop latency
dominates (small chunks / large P) and ring wins when overlap with
compute matters more.  Causality is exact: every device sees the FULL
sequence for its heads, so the flash kernel's causal mask needs no
cross-chunk bookkeeping.

Requires heads % axis_size == 0 (heads are the split resource).
Run inside shard_map with ``axis_name`` bound, sequence sharded on
the -2 axis of q/k/v.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from dcos_commons_tpu.parallel.compat import axis_size as _mesh_axis_size


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    axis_size: Optional[int] = None,
) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name``.

    Per-device shapes: q/k/v [batch, heads, chunk, head_dim] with the
    FULL head count and chunk = seq / axis_size; returns the same
    shape (sequence sharded again).
    """
    from dcos_commons_tpu.ops.attention import flash_attention

    if axis_size is None:
        axis_size = _mesh_axis_size(axis_name)
    if axis_size == 1:
        return flash_attention(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k
        )
    heads = q.shape[1]
    if heads % axis_size != 0:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by the sp axis "
            f"size ({axis_size})"
        )

    def seq_to_heads(x):
        # [b, H, s/P, d] -> [b, H/P, s, d]: split the head axis across
        # the group, concatenate the sequence chunks
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    q_h, k_h, v_h = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = flash_attention(
        q_h, k_h, v_h, causal=causal, block_q=block_q, block_k=block_k
    )
    return heads_to_seq(out)
