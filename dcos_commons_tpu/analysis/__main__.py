"""sdklint CLI: ``python -m dcos_commons_tpu.analysis``.

    --lint              framework lint (AST rules + baseline)
    --specs             ahead-of-time spec analyzer (frameworks/*)
    --all               both (the CI gate; default when no mode given)
    --update-baseline   rewrite the baseline from current lint findings
    --catalog           print the rule catalog and exit
    --root DIR          repo root (default: auto-detect from this file)

Exit code 0 = no non-baselined findings; 1 = findings; 2 = bad usage.
The gate test (tests/test_lint_gate.py) runs the same entry points
in-process.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List


def _default_root() -> str:
    """The repo root: the directory holding the ``dcos_commons_tpu``
    package this module was imported from."""
    package_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(package_dir)


def main(argv: List[str] = None) -> int:
    from dcos_commons_tpu.analysis import baseline as baseline_mod
    from dcos_commons_tpu.analysis import speccheck
    from dcos_commons_tpu.analysis.linter import lint_tree
    from dcos_commons_tpu.analysis.rules import rule_catalog

    parser = argparse.ArgumentParser(
        prog="python -m dcos_commons_tpu.analysis",
        description=__doc__.splitlines()[0],
    )
    parser.add_argument("--lint", action="store_true")
    parser.add_argument("--specs", action="store_true")
    parser.add_argument("--all", action="store_true")
    parser.add_argument("--update-baseline", action="store_true")
    parser.add_argument("--catalog", action="store_true")
    parser.add_argument("--root", default=_default_root())
    parser.add_argument("--baseline", default="")
    parser.add_argument("--host-cpus", type=float, default=8.0)
    parser.add_argument("--host-mem", type=int, default=16384)
    parser.add_argument("--host-disk", type=int, default=102400)
    parser.add_argument(
        "--verbose", "-v", action="store_true",
        help="also list suppressed and baselined findings",
    )
    args = parser.parse_args(argv)

    if args.catalog:
        print(rule_catalog())
        return 0

    run_lint = args.lint or args.all or not (args.lint or args.specs)
    run_specs = args.specs or args.all or not (args.lint or args.specs)
    root = os.path.abspath(args.root)
    baseline_path = args.baseline or baseline_mod.baseline_path(root)
    failed = False

    if run_lint:
        result = lint_tree(root)
        if args.update_baseline:
            counts = baseline_mod.save_baseline(
                baseline_path, result.findings
            )
            print(
                f"baseline: {sum(counts.values())} finding(s) across "
                f"{len(counts)} file/rule pair(s) -> {baseline_path}"
            )
            fresh, absorbed = [], result.findings
        else:
            known = baseline_mod.load_baseline(baseline_path)
            fresh, absorbed = baseline_mod.apply_baseline(
                result.findings, known
            )
        for finding in fresh:
            print(finding.render())
        if args.verbose:
            for finding in absorbed:
                print(f"{finding.render()}  [baselined]")
            for finding in result.suppressed:
                print(f"{finding.render()}  [suppressed]")
        print(
            f"lint: {result.files_checked} files, "
            f"{len(fresh)} new finding(s), {len(absorbed)} baselined, "
            f"{len(result.suppressed)} suppressed"
        )
        failed |= bool(fresh)

    if run_specs:
        host_model = speccheck.HostModel(
            cpus=args.host_cpus,
            memory_mb=args.host_mem,
            disk_mb=args.host_disk,
        )
        findings = speccheck.analyze_all(root, host_model)
        for finding in findings:
            print(finding.render())
        print(f"specs: {len(findings)} finding(s)")
        failed |= bool(findings)

    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
