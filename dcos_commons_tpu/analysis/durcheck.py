"""durcheck: crash-consistency & durability-ordering analysis.

The HA control plane's core robustness claim is "cold start and
failover are one code path": every externally visible action is
WAL'd before it happens, every persisted record kind has a replay
consumer, every scheduler-path store mutation runs behind the
leader fence, and every file-backed persist uses tmp+fsync+rename.
Those invariants were previously enforced only by five hand-wired
chaos points (testing/chaos.py) and whatever tests remembered to
cover — this pass verifies them statically, the way spmdcheck
verifies collective schedules: a per-function *persistence-effect
summary* (store writes, journal appends, WAL records, file/
checkpoint persists, and external effects — agent launch/kill,
HTTP 2xx acks, lease resignation) is built per file, propagated
over the call graph to a fixpoint, and five flow-ordered rules run
over the result.

Rules (suppressible with ``# sdklint: disable=<rule>`` or the
rationale-carrying ``# durcheck: <rule>=<reason>`` annotation, and
absorbable by the shared ``.sdklint-baseline.json``):

- ``dur-effect-before-wal``: an external effect (agent launch/kill,
  HTTP 2xx ack, lease resign) is reachable on some path *before* an
  intent-class persist (launch WAL, task-record store, raw persister
  write) later in the same flow.  A crash in that window leaves an
  effect the successor cannot derive from the store.  May-analysis:
  effect sets union at branch joins, so a persist on only one branch
  never masks the finding; loop back-edges are NOT modeled (the
  per-iteration persist-then-effect pattern is correct, and
  cross-iteration ordering is each item's own WAL's concern).
  Journal appends, property writes, file persists, and deletions do
  not trigger the rule: they are telemetry, derived state, or
  garbage collection of completed intent — not intent records.
- ``dur-replay-parity``: every property key (and journal event kind)
  written somewhere must have a rehydrate/replay reader, and vice
  versa.  A dead record is debt the store carries forever; an orphan
  reader is a replay path that can never fire (usually a typo'd key
  or a record kind that was renamed on only one side).  Keys are
  matched as normalized tokens: literals exactly, constant-prefixed
  f-strings/concats by prefix, shared symbolic prefixes
  (``PLAN_CKPT_PREFIX + name``) by the constant's resolved value or
  name, and fully dynamic keys (HTTP passthrough) are exempt.
- ``dur-unfenced-write``: the flow-sensitive upgrade of sdklint's
  ``lease-gated-mutation``: a raw persister mutation OUTSIDE the
  lint's scoped directories that is nevertheless *reachable* from
  scheduler-path code over the call graph — exactly the sites the
  single-file lint structurally cannot see.  The two rules are
  cross-referenced: any site ``lease-gated-mutation`` would report
  is skipped here, so one site is never double-reported.
- ``dur-nonatomic-pair``: two coupled store keys (same derived base
  path, different leaves — the classic task info/status pair)
  mutated by separate single-key ``set`` calls with no generation
  bump between them and no single-transaction ``apply`` batch.  A
  crash between the writes leaves a torn record a replayer can
  observe.
- ``dur-file-discipline``: a file opened for writing in a
  persistence-relevant module without BOTH an ``os.fsync`` and an
  ``os.replace``/``os.rename`` in the same function — the
  tmp+fsync+rename pattern ``storage/file_persister.compact`` is the
  in-tree exemplar of.

The pass also emits the full **persistence-point map**: every
WAL/store/property/persister/journal/checkpoint/file boundary it
discovered, as (file, line range, kind, function).  ``analysis dur
--points`` dumps it as JSON, and ``testing/chaos.py`` consumes it to
auto-derive crash-injection points — the chaos matrix grows from the
five hand-wired kinds to every statically discovered boundary, and a
boundary the harness cannot reach is reported, not silently skipped
(the map stays probe-verified the way plancheck's quotient does).

Scope: the persistence-relevant subtrees (scheduler, state, storage,
ha, health, recovery, plan, offer, http, serve, router, multi,
decommission, uninstall, runtime, utils) plus ``common.py`` (the
atomic-write helper lives there).  Findings reuse the sdklint
``Finding``/``Suppressions`` machinery so CLI, baseline, and gate
treatment are identical to every other analyzer.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from functools import lru_cache
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from dcos_commons_tpu.analysis.linter import (
    Finding,
    LintResult,
    Suppressions,
)

# directories (relative to the repo root) the analyzer walks; entries
# may also name single files (common.py holds atomic_write_text)
DUR_SUBDIRS = (
    "dcos_commons_tpu/scheduler",
    "dcos_commons_tpu/state",
    "dcos_commons_tpu/storage",
    "dcos_commons_tpu/ha",
    "dcos_commons_tpu/health",
    "dcos_commons_tpu/recovery",
    "dcos_commons_tpu/plan",
    "dcos_commons_tpu/offer",
    "dcos_commons_tpu/http",
    "dcos_commons_tpu/serve",
    "dcos_commons_tpu/router",
    "dcos_commons_tpu/multi",
    "dcos_commons_tpu/decommission",
    "dcos_commons_tpu/uninstall",
    "dcos_commons_tpu/runtime",
    "dcos_commons_tpu/utils",
    "dcos_commons_tpu/common.py",
)

# persist kinds that count as INTENT records for dur-effect-before-wal
TRIGGER_KINDS = frozenset({"wal", "store", "persister"})
# every kind the persistence-point map carries
PERSIST_KINDS = (
    "wal", "store", "property", "persister", "checkpoint",
    "journal", "journal-flush", "delete", "file",
)
EFFECT_KINDS = frozenset({"launch", "kill", "http-ack", "lease-resign"})

# methods the primitive classifier owns.  When one of these is called
# on a receiver that does NOT match its pattern (outcome_tracker
# .record, metrics set, dict.set, ...), the call is treated as inert
# rather than resolved by simple name — otherwise every ``record``/
# ``set``/``commit`` in the tree would union in the WAL summaries.
_PRIMITIVE_METHODS = frozenset({
    "store_tasks", "store_status", "store_launch", "store_goal_override",
    "store_framework_id", "store_target", "set_target_config",
    "store_config", "store_property", "set_deployment_completed",
    "record", "commit", "set", "apply", "append", "flush", "store",
    "recursive_delete", "clear_task", "clear_property",
    "clear_all_data", "release", "checkpoint",
    "kill", "launch", "launch_one", "resign", "send_response",
})

# rationale-carrying inline suppression, durcheck's own grammar
# (mirrors racecheck's ``# racecheck: handoff=<reason>``):
#   self.ledger.commit(...)  # durcheck: dur-effect-before-wal=<why>
# valid on the finding's line or the line above; the reason is
# REQUIRED — an annotation without one does not suppress.
_DUR_ANNOT_RE = re.compile(
    r"#\s*durcheck:\s*(?P<rule>dur-[a-z\-]+)\s*=\s*(?P<reason>\S.*)"
)


def _receiver_name(call: ast.Call) -> str:
    """Name of the object a method is called on: ``a.b.c(...)`` -> b,
    ``x.f(...)`` -> x, bare ``f(...)`` -> ''."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return ""
    value = func.value
    if isinstance(value, ast.Name):
        return value.id
    if isinstance(value, ast.Attribute):
        return value.attr
    return ""


def _call_method(call: ast.Call) -> str:
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


@dataclass(frozen=True)
class Prim:
    """One classified primitive: ``category`` is ``persist`` /
    ``delete`` / ``journal`` / ``effect``; ``kind`` the map kind or
    effect kind."""

    category: str
    kind: str


def classify_call(call: ast.Call) -> Optional[Prim]:
    """Classify a call against the persistence/effect vocabulary.

    Receiver-gated: ``record`` is a WAL write only on a *recorder*,
    ``commit`` only on a *ledger*, ``set``/``apply`` only on a
    *persister*/*backend* — everything else with a primitive method
    name is deliberately inert (see ``_PRIMITIVE_METHODS``)."""
    method = _call_method(call)
    recv = _receiver_name(call).lower()
    if method == "store_launch":
        return Prim("persist", "wal")
    if method in ("store_tasks", "store_status", "store_goal_override",
                  "store_framework_id", "store_target",
                  "set_target_config", "store_config"):
        return Prim("persist", "store")
    if method in ("store_property", "set_deployment_completed"):
        return Prim("persist", "property")
    if method == "record" and "recorder" in recv:
        return Prim("persist", "wal")
    if method == "commit" and "ledger" in recv:
        return Prim("persist", "wal")
    if method in ("set", "apply") and (
            "persister" in recv or "backend" in recv):
        return Prim("persist", "persister")
    if method == "checkpoint" and "checkpoint" in recv:
        return Prim("persist", "checkpoint")
    if method in ("recursive_delete", "clear_all_data") and (
            "persister" in recv or "backend" in recv):
        return Prim("delete", "delete")
    if method in ("clear_task", "clear_property"):
        return Prim("delete", "delete")
    if method == "release" and "ledger" in recv:
        return Prim("delete", "delete")
    if method == "append" and "journal" in recv:
        return Prim("journal", "journal")
    if method == "flush" and "journal" in recv:
        return Prim("journal", "journal-flush")
    if method == "kill" and ("killer" in recv or "agent" in recv):
        return Prim("effect", "kill")
    if method in ("launch", "launch_one") and "agent" in recv:
        return Prim("effect", "launch")
    if method == "resign" and ("lease" in recv or "lock" in recv
                               or "ha" in recv):
        return Prim("effect", "lease-resign")
    if method == "send_response" and call.args:
        code = call.args[0]
        if isinstance(code, ast.Constant) and isinstance(code.value, int) \
                and 200 <= code.value < 300:
            return Prim("effect", "http-ack")
    return None


# -- key-token normalization (dur-replay-parity) ----------------------------


def _key_descriptor(expr: ast.AST) -> Tuple[str, str]:
    """Structural descriptor of a property-key expression, resolved to
    a canonical token later (once the whole tree's constants are
    harvested): ``("lit", s)`` exact literal, ``("sym", name)`` bare
    constant/attribute, ``("prefixlit", s)`` / ``("prefixsym", name)``
    constant-prefixed f-string or concat, ``("dynamic", "")``
    anything key-shaped only at runtime."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return ("lit", expr.value)
    if isinstance(expr, ast.Name):
        return ("sym", expr.id)
    if isinstance(expr, ast.Attribute):
        return ("sym", expr.attr)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        kind, token = _key_descriptor(expr.left)
        if kind == "lit":
            return ("prefixlit", token)
        if kind == "sym":
            return ("prefixsym", token)
        return ("dynamic", "")
    if isinstance(expr, ast.JoinedStr) and expr.values:
        head = expr.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            if len(expr.values) == 1:
                return ("lit", head.value)
            return ("prefixlit", head.value)
        if isinstance(head, ast.FormattedValue):
            kind, token = _key_descriptor(head.value)
            if kind == "sym":
                return ("prefixsym", token)
    return ("dynamic", "")


def _canonical_token(desc: Tuple[str, str],
                     consts: Dict[str, str]) -> Optional[Tuple[str, str]]:
    """Resolve a descriptor against the harvested constant table:
    ``("exact", s)`` or ``("prefix", s)`` with symbolic names replaced
    by their string values where known.  ``None`` = dynamic, exempt
    from parity."""
    kind, token = desc
    if kind == "lit":
        return ("exact", token)
    if kind == "prefixlit":
        return ("prefix", token)
    if kind == "sym":
        value = consts.get(token)
        # an unresolved symbol (function parameter, instance field set
        # at runtime) is a dynamic key: exempt, not a pseudo-token —
        # parity is a contract over the *static* key vocabulary
        return ("exact", value) if value is not None else None
    if kind == "prefixsym":
        value = consts.get(token)
        return ("prefix", value) if value is not None else None
    return None


def _tokens_match(writer: Tuple[str, str], reader: Tuple[str, str]) -> bool:
    wk, wv = writer
    rk, rv = reader
    if wk == "exact" and rk == "exact":
        return wv == rv
    if wk == "prefix" and rk == "prefix":
        return wv.startswith(rv) or rv.startswith(wv)
    exact, prefix = (wv, rv) if wk == "exact" else (rv, wv)
    return exact.startswith(prefix)


# -- program summary --------------------------------------------------------


@dataclass
class DurSummary:
    """What one function may do, transitively, to durable state and
    the outside world."""

    qualname: str
    file: str
    lineno: int
    persists: Set[str] = field(default_factory=set)   # persist kinds
    effects: Set[str] = field(default_factory=set)    # effect kinds
    # calls: names used for summary PROPAGATION — receiver-gated, so a
    # primitive-named method on the wrong receiver (outcome_tracker
    # .record) never unions a WAL summary into its caller.
    calls: Set[str] = field(default_factory=set)
    # edge_calls: EVERY method call, used only for call-graph
    # reachability (dur-unfenced-write).  Over-approximate on purpose
    # — reachability wants "could scheduler code get here", and
    # union-by-name is the safe answer to that question.
    edge_calls: Set[str] = field(default_factory=set)


@dataclass(frozen=True)
class PersistencePoint:
    """One statically discovered durability boundary."""

    file: str
    line: int
    end_line: int
    kind: str
    function: str

    def to_dict(self) -> Dict[str, object]:
        return {
            "file": self.file,
            "line": self.line,
            "end_line": self.end_line,
            "kind": self.kind,
            "function": self.function,
        }


@dataclass
class _KeySite:
    file: str
    line: int
    desc: Tuple[str, str]
    function: str


@dataclass
class _MutationSite:
    """A raw persister mutation call site (dur-unfenced-write)."""

    file: str
    line: int
    receiver: str
    method: str
    function: str


class DurProgram:
    """All function summaries + the registries the program-level rules
    read.  Call resolution is name-based, like spmdcheck: a simple
    name resolves to every scanned function carrying it, and the
    union is the safe over-approximation."""

    def __init__(self) -> None:
        self.functions: Dict[str, DurSummary] = {}
        self.by_name: Dict[str, Set[str]] = {}
        # NAME -> string value, module/class-level str constants
        self.consts: Dict[str, str] = {}
        self.points: List[PersistencePoint] = []
        self.prop_writes: List[_KeySite] = []
        self.prop_reads: List[_KeySite] = []
        self.journal_appends: List[Tuple[str, int, str]] = []
        self.journal_filters: List[Tuple[str, int, str]] = []
        self.journal_generic_reads: int = 0
        # appends whose kind is fully dynamic — each one could emit
        # any kind, so they satisfy every filter (no orphan teeth lost
        # in this tree: the one dynamic append carries a literal
        # default that IS harvested)
        self.journal_wildcard_appends: int = 0
        self.mutation_sites: List[_MutationSite] = []

    def add(self, summary: DurSummary) -> None:
        self.functions[summary.qualname] = summary
        simple = summary.qualname.rsplit(".", 1)[-1]
        self.by_name.setdefault(simple, set()).add(summary.qualname)

    def resolve(self, name: str) -> List[DurSummary]:
        if name in self.functions:
            return [self.functions[name]]
        keys = self.by_name.get(name.rsplit(".", 1)[-1], ())
        return [self.functions[k] for k in keys]

    def propagate(self) -> int:
        """Union callee persists/effects into callers to a fixpoint.
        Monotone: sets only ever grow, so the fixpoint exists and a
        re-run is a no-op (pinned by the property tests).  Returns the
        number of rounds taken."""
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for summary in self.functions.values():
                for callee_name in summary.calls:
                    for callee in self.resolve(callee_name):
                        if callee is summary:
                            continue
                        if not callee.persists <= summary.persists:
                            summary.persists |= callee.persists
                            changed = True
                        if not callee.effects <= summary.effects:
                            summary.effects |= callee.effects
                            changed = True
        return rounds

    def reachable_from(self, entry_keys: Iterable[str]) -> Set[str]:
        """Transitive closure over ``edge_calls`` from ``entry_keys``
        (the full call graph, including primitive-named methods the
        propagation graph deliberately gates out)."""
        seen: Set[str] = set()
        frontier = list(entry_keys)
        while frontier:
            key = frontier.pop()
            if key in seen:
                continue
            seen.add(key)
            summary = self.functions.get(key)
            if summary is None:
                continue
            for callee_name in summary.edge_calls:
                for callee in self.resolve(callee_name):
                    if callee.qualname not in seen:
                        frontier.append(callee.qualname)
        return seen


class _SummaryBuilder(ast.NodeVisitor):
    """One file's summaries, constants, points, and key registries.

    Nested functions fold into their enclosing def's summary (calling
    a factory may run the closure; over-approximation is the safe
    direction for ordering hazards)."""

    def __init__(self, rel: str, program: DurProgram):
        self.rel = rel
        self.program = program
        self._stack: List[DurSummary] = []
        self._class: List[str] = []
        self._pending_prefix_reads: List[_KeySite] = []
        self._saw_fetch_keys = False

    # constants -------------------------------------------------------

    def _harvest_const(self, node: ast.Assign) -> None:
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            return
        for target in node.targets:
            if isinstance(target, ast.Name):
                self.program.consts.setdefault(target.id, node.value.value)
            elif isinstance(target, ast.Attribute):
                self.program.consts.setdefault(target.attr, node.value.value)

    def visit_Assign(self, node: ast.Assign) -> None:
        if not self._stack:
            self._harvest_const(node)
        self.generic_visit(node)

    # functions -------------------------------------------------------

    def _enter(self, node) -> None:
        if self._stack:
            self._stack.append(self._stack[-1])  # fold into enclosing
        else:
            qual = ".".join(
                [self.rel[:-3].replace("/", ".")]
                + self._class + [node.name]
            )
            self._stack.append(DurSummary(qual, self.rel, node.lineno))
            # startswith-prefix reads are only property-key scans when
            # the SAME function iterates fetch_property_keys — buffer
            # them until we know (every other startswith is a URL or
            # path check, not a replay reader)
            self._pending_prefix_reads: List[_KeySite] = []
            self._saw_fetch_keys = False

    def _exit(self) -> None:
        summary = self._stack.pop()
        if not self._stack:
            self.program.add(summary)
            if self._saw_fetch_keys:
                self.program.prop_reads.extend(self._pending_prefix_reads)
            self._pending_prefix_reads = []
            self._saw_fetch_keys = False

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter(node)
        self.generic_visit(node)
        self._exit()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                self._harvest_const(stmt)
        self._class.append(node.name)
        self.generic_visit(node)
        self._class.pop()

    # calls -----------------------------------------------------------

    def _record_point(self, call: ast.Call, kind: str) -> None:
        self.program.points.append(PersistencePoint(
            self.rel, call.lineno,
            getattr(call, "end_lineno", call.lineno) or call.lineno,
            kind,
            self._stack[-1].qualname if self._stack else "<module>",
        ))

    def _journal_append_kind(self, node: ast.Call) -> None:
        kind_arg = node.args[0]
        if isinstance(kind_arg, ast.Constant) and \
                isinstance(kind_arg.value, str):
            self.program.journal_appends.append(
                (self.rel, node.lineno, kind_arg.value)
            )
            return
        # ``journal.append(event.get("kind", "alert"), ...)``: the
        # literal default is a kind this call genuinely emits
        if isinstance(kind_arg, ast.Call) and \
                _call_method(kind_arg) == "get" and \
                len(kind_arg.args) >= 2 and \
                isinstance(kind_arg.args[1], ast.Constant) and \
                isinstance(kind_arg.args[1].value, str):
            self.program.journal_appends.append(
                (self.rel, node.lineno, kind_arg.args[1].value)
            )
            return
        # any other dynamic kind could emit anything: wildcard
        self.program.journal_wildcard_appends += 1

    def visit_Call(self, node: ast.Call) -> None:
        func_name = (
            self._stack[-1].qualname if self._stack else "<module>"
        )
        prim = classify_call(node)
        method = _call_method(node)
        if self._stack and method:
            self._stack[-1].edge_calls.add(method)
        if prim is not None:
            if prim.category != "effect":
                # the point map is the durability-boundary contract
                # chaos consumes — effects are rule inputs, not points
                self._record_point(node, prim.kind)
            if self._stack:
                if prim.category == "persist":
                    self._stack[-1].persists.add(prim.kind)
                elif prim.category == "effect":
                    self._stack[-1].effects.add(prim.kind)
            if prim.kind == "persister" or (
                    prim.category == "delete"
                    and method in ("recursive_delete", "clear_all_data")):
                recv = _receiver_name(node)
                if "persister" in recv.lower() or "backend" in recv.lower():
                    self.program.mutation_sites.append(_MutationSite(
                        self.rel, node.lineno, recv, method, func_name,
                    ))
            if method == "store_property" and node.args:
                self.program.prop_writes.append(_KeySite(
                    self.rel, node.lineno,
                    _key_descriptor(node.args[0]), func_name,
                ))
                # clear_property is GC of a written key, neither a
                # replay reader nor a record writer for parity
            if prim.kind == "journal" and node.args:
                self._journal_append_kind(node)
        elif method == "fetch_property" and node.args:
            self.program.prop_reads.append(_KeySite(
                self.rel, node.lineno,
                _key_descriptor(node.args[0]), func_name,
            ))
        elif method == "fetch_property_keys":
            self._saw_fetch_keys = True
        elif method == "startswith" and node.args and self._stack:
            # ``key.startswith(PREFIX)`` over fetch_property_keys is
            # the prefix-scan replay reader (checkpoint prune, the
            # /v1/state file listing) — buffered; registered only if
            # this function turns out to iterate fetch_property_keys
            desc = _key_descriptor(node.args[0])
            if desc[0] != "dynamic":
                self._pending_prefix_reads.append(_KeySite(
                    self.rel, node.lineno,
                    (
                        "prefixlit" if desc[0] == "lit" else "prefixsym",
                        desc[1],
                    ),
                    func_name,
                ))
        elif method == "events":
            kinds_arg = None
            for kw in node.keywords:
                if kw.arg == "kinds":
                    kinds_arg = kw.value
            if kinds_arg is None:
                self.program.journal_generic_reads += 1
            elif isinstance(kinds_arg, (ast.Tuple, ast.List)):
                for elt in kinds_arg.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        self.program.journal_filters.append(
                            (self.rel, node.lineno, elt.value)
                        )
        elif method == "open" or (isinstance(node.func, ast.Name)
                                  and node.func.id == "open"):
            if _open_write_mode(node):
                self._record_point(node, "file")
        if self._stack and prim is None:
            if method and method not in _PRIMITIVE_METHODS:
                self._stack[-1].calls.add(method)
        self.generic_visit(node)


def _open_write_mode(call: ast.Call) -> bool:
    """True for ``open(..., "w"/"wb"/...)`` (create/truncate modes;
    reads and r+ replay-side patching are out of scope)."""
    mode: Optional[ast.AST] = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False
    return (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and "w" in mode.value
    )


def build_summary(files: Sequence[Tuple[str, str, str]]) -> DurProgram:
    program = DurProgram()
    for _, rel, source in files:
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        _SummaryBuilder(rel, program).visit(tree)
    program.propagate()
    return program


# -- suppression handling ---------------------------------------------------


class DurSuppressions:
    """Standard sdklint ``disable`` grammar plus durcheck's
    rationale-required ``# durcheck: <rule>=<reason>`` annotation."""

    def __init__(self, lines: Sequence[str]):
        self._std = Suppressions(lines)
        self.annotated: Dict[int, Set[str]] = {}
        for i, text in enumerate(lines, start=1):
            match = _DUR_ANNOT_RE.search(text)
            if match:
                self.annotated.setdefault(i, set()).add(match.group("rule"))

    def covers(self, finding: Finding) -> bool:
        if self._std.covers(finding):
            return True
        for lineno in (finding.line, finding.line - 1):
            if finding.rule in self.annotated.get(lineno, ()):
                return True
        return False


# -- flow walk (dur-effect-before-wal) --------------------------------------


def _calls_in_order(node: ast.AST) -> List[ast.Call]:
    calls = [n for n in ast.walk(node) if isinstance(n, ast.Call)]
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return calls


class _EffectFlow:
    """May-analysis of effect kinds reaching each statement of one
    function: effects union at joins, a terminated branch (return/
    raise/break/continue) does not flow past its join, and loop
    bodies are walked once (no back-edges — see the rule docstring).
    Emits at most one finding per function: the FIRST intent-class
    persist reachable after an effect."""

    def __init__(self, program: DurProgram, rel: str, funcname: str):
        self.program = program
        self.rel = rel
        self.funcname = funcname
        self.finding: Optional[Finding] = None

    def run(self, func: ast.AST) -> Optional[Finding]:
        self._block(func.body, set())
        return self.finding

    # statement dispatch ----------------------------------------------

    def _block(self, stmts, effects: Set[str]) -> Tuple[Set[str], bool]:
        for stmt in stmts:
            effects, terminated = self._stmt(stmt, effects)
            if terminated:
                return effects, True
        return effects, False

    def _stmt(self, stmt, effects: Set[str]) -> Tuple[Set[str], bool]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return effects, False  # nested defs don't execute here
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self._scan(stmt, effects)
            return effects, True
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return effects, True
        if isinstance(stmt, ast.If):
            self._scan(stmt.test, effects)
            body_eff, body_term = self._block(stmt.body, set(effects))
            else_eff, else_term = self._block(stmt.orelse, set(effects))
            outs = []
            if not body_term:
                outs.append(body_eff)
            if not else_term:
                outs.append(else_eff)
            if not outs:
                return effects, True
            return set().union(*outs), False
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            head = stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor)) \
                else stmt.test
            self._scan(head, effects)
            body_eff, _ = self._block(stmt.body, set(effects))
            else_eff, _ = self._block(stmt.orelse,
                                      effects | body_eff)
            return effects | body_eff | else_eff, False
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan(item.context_expr, effects)
            return self._block(stmt.body, effects)
        if isinstance(stmt, ast.Try):
            body_eff, body_term = self._block(stmt.body, set(effects))
            # a handler can enter from anywhere in the body
            entry = effects | body_eff
            outs = [] if body_term else [body_eff]
            for handler in stmt.handlers:
                h_eff, h_term = self._block(handler.body, set(entry))
                if not h_term:
                    outs.append(h_eff)
            if stmt.orelse and not body_term:
                o_eff, o_term = self._block(stmt.orelse, set(body_eff))
                outs = [e for e in outs if e is not body_eff]
                if not o_term:
                    outs.append(o_eff)
            joined = set().union(*outs) if outs else set(entry)
            if stmt.finalbody:
                f_eff, f_term = self._block(stmt.finalbody,
                                            joined | entry)
                if f_term:
                    return f_eff, True
                joined = f_eff
            return joined, not outs and not stmt.finalbody
        self._scan(stmt, effects)
        return effects, False

    # call scan -------------------------------------------------------

    def _scan(self, node: ast.AST, effects: Set[str]) -> None:
        for call in _calls_in_order(node):
            prim = classify_call(call)
            method = _call_method(call)
            if prim is not None:
                if prim.category == "persist" and \
                        prim.kind in TRIGGER_KINDS and effects:
                    self._emit(call, effects, method)
                elif prim.category == "effect":
                    effects.add(prim.kind)
                continue
            if not method or method in _PRIMITIVE_METHODS:
                continue  # receiver-gated primitive name: inert
            # accumulate the callee's transitive effects at the call
            # site; its own persist-vs-effect ordering is checked in
            # the callee's body, where the flow is precise — flagging
            # "transitively persists" call sites here drowns the
            # signal in union-by-name resolution noise
            for callee in self.program.resolve(method):
                effects |= callee.effects

    def _emit(self, call: ast.Call, effects: Set[str],
              method: str) -> None:
        if self.finding is not None:
            return
        self.finding = Finding(
            self.rel, call.lineno, "dur-effect-before-wal",
            f"{self.funcname}() reaches {method}(...) AFTER external "
            f"effect(s) {sorted(effects)} on some path — a crash "
            "between the effect and this intent persist leaves state "
            "the successor cannot replay; persist intent first, or "
            "annotate why the effect is recovery-covered",
        )


# -- rules ------------------------------------------------------------------


class DurRule:
    id = ""
    description = ""


class EffectBeforeWalRule(DurRule):
    """An external effect (agent launch/kill, HTTP 2xx ack, lease
    resign) occurs before an intent-class persist (launch WAL,
    task-record store, raw persister write) later in the same flow.
    The WAL discipline (DefaultScheduler.java:454: reservations and
    task infos durable BEFORE the agent sees a launch) demands the
    reverse order: a crash in the effect→persist window leaves an
    externally visible action the successor's replay cannot derive.
    May-analysis over branches (union at joins, so a persist on only
    one branch never masks the finding); loop bodies single-pass.
    Deliberate orderings (the kill-before-relaunch-WAL in
    _process_candidates, which recovery covers) carry a
    ``# durcheck: dur-effect-before-wal=<reason>`` annotation."""

    id = "dur-effect-before-wal"
    description = "external effect reachable before its intent persist"

    def check_file(self, rel: str, tree: ast.AST,
                   program: DurProgram) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                flow = _EffectFlow(program, rel, node.name)
                finding = flow.run(node)
                if finding is not None:
                    out.append(finding)
        return out


class ReplayParityRule(DurRule):
    """Every property key written must have a replay reader (exact
    fetch, symbolic-prefix fetch, or a prefix scan over
    fetch_property_keys), and every reader must have a writer: a
    dead record is store debt forever, an orphan reader a replay
    path that can never fire.  Journal event kinds get the same
    treatment — a kind-filtered ``events(kinds=...)`` query for a
    kind nothing appends is an orphan reader (dead-record parity for
    journal kinds is satisfied by any generic ``events()`` consumer;
    the journal is a telemetry ring, replayed wholesale).  Fully
    dynamic keys (the /v1/state property passthrough) are exempt —
    parity is a static contract over the key vocabulary."""

    id = "dur-replay-parity"
    description = "persisted record kind without a replay reader (or vice versa)"

    def check_program(self, program: DurProgram) -> List[Finding]:
        out: List[Finding] = []
        consts = program.consts
        writers = [
            (site, _canonical_token(site.desc, consts))
            for site in program.prop_writes
        ]
        readers = [
            (site, _canonical_token(site.desc, consts))
            for site in program.prop_reads
        ]
        for site, token in writers:
            if token is None:
                continue
            if not any(
                rt is not None and _tokens_match(token, rt)
                for _, rt in readers
            ):
                out.append(Finding(
                    site.file, site.line, self.id,
                    f"property key {token[1]!r} is written in "
                    f"{site.function}() but nothing ever reads it "
                    "back — a dead record the store carries forever; "
                    "add the rehydrate/replay reader or drop the write",
                ))
        for site, token in readers:
            if token is None:
                continue
            if not any(
                wt is not None and _tokens_match(wt, token)
                for _, wt in writers
            ):
                out.append(Finding(
                    site.file, site.line, self.id,
                    f"property key {token[1]!r} is read in "
                    f"{site.function}() but nothing ever writes it — "
                    "an orphan replay path (typo'd key, or a record "
                    "kind renamed on only one side)",
                ))
        appended = {kind for _, _, kind in program.journal_appends}
        for file, line, kind in program.journal_filters:
            if program.journal_wildcard_appends and kind not in appended:
                continue  # a dynamic-kind append could emit anything
            if kind not in appended:
                out.append(Finding(
                    file, line, self.id,
                    f"journal query filters on kind {kind!r} but "
                    "nothing ever appends that kind — the filter can "
                    "never match",
                ))
        if not program.journal_generic_reads:
            for file, line, kind in program.journal_appends:
                if not any(k == kind
                           for _, _, k in program.journal_filters):
                    out.append(Finding(
                        file, line, self.id,
                        f"journal kind {kind!r} is appended but no "
                        "events() consumer exists in the tree",
                    ))
        return out


class UnfencedWriteRule(DurRule):
    """Flow-sensitive upgrade of sdklint's ``lease-gated-mutation``:
    a raw persister/backend mutation in a module OUTSIDE that lint's
    scoped directories that is reachable from scheduler-path code
    over the call graph.  The single-file lint owns the direct sites
    in its scope (this rule skips them — one site is never reported
    by both); this rule catches the helper three calls away.  The
    sanctioned store layer (state/, storage/), the fence itself
    (ha/election.py), and multi/store.py are exempt — raw mutations
    are the layer those modules ARE."""

    id = "dur-unfenced-write"
    description = "scheduler-reachable raw persister mutation outside the fenced store layer"

    _EXEMPT_PREFIXES = (
        "dcos_commons_tpu/state/",
        "dcos_commons_tpu/storage/",
    )
    _EXEMPT_FILES = (
        "dcos_commons_tpu/ha/election.py",
        "dcos_commons_tpu/multi/store.py",
    )

    def check_program(self, program: DurProgram) -> List[Finding]:
        from dcos_commons_tpu.analysis.rules import LeaseGatedMutationRule

        lint_scope = LeaseGatedMutationRule._SCOPED
        lint_exempt = LeaseGatedMutationRule._EXEMPT
        entries = [
            key for key, summary in program.functions.items()
            if any(summary.file.startswith(p) for p in lint_scope)
            and summary.file not in lint_exempt
        ]
        reachable = program.reachable_from(entries)
        out: List[Finding] = []
        for site in program.mutation_sites:
            if any(site.file.startswith(p) for p in lint_scope) \
                    and site.file not in lint_exempt:
                continue  # lease-gated-mutation owns this site
            if any(site.file.startswith(p)
                   for p in self._EXEMPT_PREFIXES):
                continue
            if site.file in self._EXEMPT_FILES:
                continue
            if site.function not in reachable:
                continue
            out.append(Finding(
                site.file, site.line, self.id,
                f"raw {site.receiver}.{site.method}(...) in "
                f"{site.function.rsplit('.', 1)[-1]}() is reachable "
                "from scheduler-path code but lives outside the "
                "fenced store layer — a write here can bypass the "
                "leader fence on failover; route it through a store "
                "class or annotate why the injected persister is "
                "already fenced",
            ))
        return out


class NonatomicPairRule(DurRule):
    """Two coupled store keys — same derived base path, different
    leaves (the task info/status pair is the canonical case) —
    written by separate single-key ``set`` calls with no generation
    bump between them and no single ``apply`` transaction.  A crash
    between the two writes leaves a torn record: an info whose
    status belongs to the previous launch, exactly what
    ``StateStore.store_launch`` batches one ``apply`` to prevent."""

    id = "dur-nonatomic-pair"
    description = "coupled store keys mutated without a batch or generation bump"

    @staticmethod
    def _base_and_leaf(expr: ast.AST) -> Optional[Tuple[str, str]]:
        """(base, leaf) of a path expression, or None when unshaped.
        ``self._task_path(name, "info")`` -> ("_task_path(name)",
        "info"); an f-string/concat splits at its first dynamic part."""
        if isinstance(expr, ast.Call):
            method = _call_method(expr)
            if not method or not expr.args:
                return None
            first = ast.dump(expr.args[0])
            leaf = ""
            if len(expr.args) >= 2:
                leaf_node = expr.args[1]
                leaf = (
                    leaf_node.value
                    if isinstance(leaf_node, ast.Constant)
                    else ast.dump(leaf_node)
                )
            return (f"{method}({first})", str(leaf))
        desc = _key_descriptor(expr)
        if desc[0] in ("prefixlit", "prefixsym"):
            return (desc[1], ast.dump(expr))
        return None

    def check_file(self, rel: str, tree: ast.AST,
                   program: DurProgram) -> List[Finding]:
        out: List[Finding] = []
        for func in ast.walk(tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            sets: List[Tuple[int, str, str]] = []
            bumps: List[int] = []
            for call in _calls_in_order(func):
                method = _call_method(call)
                if "generation" in method or "bump" in method:
                    bumps.append(call.lineno)
                    continue
                prim = classify_call(call)
                if prim is None or prim.kind != "persister" \
                        or method != "set" or not call.args:
                    continue
                shaped = self._base_and_leaf(call.args[0])
                if shaped is not None:
                    sets.append((call.lineno,) + shaped)
            for i, (line_a, base_a, leaf_a) in enumerate(sets):
                for line_b, base_b, leaf_b in sets[i + 1:]:
                    if base_a != base_b or leaf_a == leaf_b:
                        continue
                    if any(line_a < b < line_b for b in bumps):
                        continue
                    out.append(Finding(
                        rel, line_b, self.id,
                        f"{func.name}() writes coupled keys "
                        f"<base>/{leaf_a} (line {line_a}) and "
                        f"<base>/{leaf_b} as separate set() calls — "
                        "a crash between them tears the record; "
                        "batch both into one apply([...]) or bump a "
                        "generation between the writes",
                    ))
        return out


class FileDisciplineRule(DurRule):
    """A file opened for writing without BOTH an ``os.fsync`` and an
    ``os.replace``/``os.rename`` in the same function.  The
    tmp+fsync+rename pattern (``storage/file_persister.compact`` is
    the exemplar) is the only way a crashed writer leaves either the
    old file or the new one — rename-only leaves readers
    partial-free but loses the write on power failure; fsync-only
    leaves a torn file under the final name.  Telemetry mirrors that
    accept loss annotate with ``# durcheck: dur-file-discipline=``."""

    id = "dur-file-discipline"
    description = "file persist without the tmp+fsync+rename pattern"

    def check_file(self, rel: str, tree: ast.AST,
                   program: DurProgram) -> List[Finding]:
        out: List[Finding] = []
        for func in ast.walk(tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            opens: List[ast.Call] = []
            has_fsync = False
            has_rename = False
            for call in _calls_in_order(func):
                method = _call_method(call)
                if method == "open" and _open_write_mode(call):
                    opens.append(call)
                elif method == "fsync":
                    has_fsync = True
                elif method in ("replace", "rename"):
                    has_rename = True
            if not opens or (has_fsync and has_rename):
                continue
            missing = []
            if not has_fsync:
                missing.append("os.fsync before the rename")
            if not has_rename:
                missing.append("a tmp-file os.replace")
            out.append(Finding(
                rel, opens[0].lineno, self.id,
                f"{func.name}() writes a file without "
                f"{' or '.join(missing)} — a crash mid-write leaves "
                "a torn or lost file; use the tmp+fsync+rename "
                "pattern (storage/file_persister.compact)",
            ))
        return out


def all_dur_rules() -> List[DurRule]:
    return [
        EffectBeforeWalRule(),
        ReplayParityRule(),
        UnfencedWriteRule(),
        NonatomicPairRule(),
        FileDisciplineRule(),
    ]


def dur_rule_catalog() -> str:
    blocks = []
    for rule in all_dur_rules():
        doc = " ".join((rule.__doc__ or "").split())
        blocks.append(f"{rule.id}: {rule.description}\n    {doc}")
    return "\n\n".join(blocks)


# -- driver -----------------------------------------------------------------


@dataclass
class DurResult(LintResult):
    """LintResult plus the persistence-point map and per-rule trend
    counts (fresh + suppressed — suppressions document debt, they
    don't hide it from the trend line)."""

    persistence_points: List[PersistencePoint] = field(
        default_factory=list
    )
    per_rule: Dict[str, int] = field(default_factory=dict)


def _collect_files(root: str,
                   subdirs: Sequence[str]) -> List[Tuple[str, str, str]]:
    out = []
    for sub in subdirs:
        top = os.path.join(root, sub)
        if os.path.isfile(top):
            with open(top, "r", encoding="utf-8") as f:
                out.append((top, sub, f.read()))
            continue
        if not os.path.isdir(top):
            continue
        for dirpath, dirs, files in os.walk(top):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                with open(path, "r", encoding="utf-8") as f:
                    source = f.read()
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                out.append((path, rel, source))
    return out


def analyze_paths(files: Sequence[Tuple[str, str, str]],
                  rules: Optional[Sequence[DurRule]] = None) -> DurResult:
    """Run durcheck over pre-read (path, rel, source) triples."""
    program = build_summary(files)
    active = list(rules) if rules is not None else all_dur_rules()
    result = DurResult()
    result.persistence_points = sorted(
        program.points, key=lambda p: (p.file, p.line, p.kind)
    )
    suppressions: Dict[str, DurSuppressions] = {}
    trees: Dict[str, ast.AST] = {}
    for _, rel, source in files:
        try:
            trees[rel] = ast.parse(source)
        except SyntaxError:
            continue
        result.files_checked += 1
        suppressions[rel] = DurSuppressions(source.splitlines())

    def sift(findings: List[Finding]) -> None:
        for finding in findings:
            result.per_rule[finding.rule] = \
                result.per_rule.get(finding.rule, 0) + 1
            sup = suppressions.get(finding.file)
            if sup is not None and sup.covers(finding):
                result.suppressed.append(finding)
            else:
                result.findings.append(finding)

    for rule in active:
        check_file = getattr(rule, "check_file", None)
        if check_file is not None:
            for rel, tree in trees.items():
                sift(check_file(rel, tree, program))
        check_program = getattr(rule, "check_program", None)
        if check_program is not None:
            sift(check_program(program))
    result.findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return result


def analyze_tree(root: str,
                 subdirs: Sequence[str] = DUR_SUBDIRS) -> DurResult:
    """Run durcheck over the persistence-relevant subtrees."""
    return analyze_paths(_collect_files(root, subdirs))


@lru_cache(maxsize=4)
def _point_map_cached(root: str,
                      subdirs: Tuple[str, ...]) -> Tuple[Dict, ...]:
    program = build_summary(_collect_files(root, subdirs))
    return tuple(
        p.to_dict()
        for p in sorted(program.points,
                        key=lambda p: (p.file, p.line, p.kind))
    )


def persistence_point_map(
    root: Optional[str] = None,
    subdirs: Sequence[str] = DUR_SUBDIRS,
) -> List[Dict[str, object]]:
    """The persistence-point map as plain dicts — the contract
    ``analysis dur --points`` dumps and ``testing/chaos.py`` consumes
    to auto-derive crash-injection points.  Cached per (root,
    subdirs): every chaos run in a test session shares one AST pass
    (the ``shared_write_map`` idiom from racecheck)."""
    if root is None:
        package_dir = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        root = os.path.dirname(package_dir)
    return list(_point_map_cached(root, tuple(subdirs)))
