"""KV-cache autoregressive inference for the flagship transformer.

The training side (transformer.py) is scan-over-layers with flash
kernels; this is its serving half, built the TPU way: STATIC shapes
throughout (the cache is allocated at ``max_len`` once; XLA never
recompiles as generation advances), ``lax.scan`` over decode steps,
``lax.dynamic_update_slice`` for in-place cache writes, and one fused
masked-softmax attention per step (seq-1 queries gain nothing from the
flash kernel's tiling — the dense einsum against the cache IS the
MXU-friendly form).

Layout: cache k/v are [n_layers, batch, max_len, n_kv_heads, head_dim]
(GQA heads stored unexpanded; expanded per step).  Greedy decoding is
exactly argmax-chaining full forwards — the equivalence tests in
tests/test_decode.py and test_workload.py hold argmax agreement.  For
MoE configs decode routes DROP-FREE (capacity covers every token of
the step); the equivalence therefore holds when the forward side is
also in its drop-free capacity regime — with training-style capacity
pressure, dropped tokens make full forwards differ from any
drop-free server by construction.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dcos_commons_tpu.models.quantize import dequantize_weight as dq
from dcos_commons_tpu.models.transformer import (
    TransformerConfig,
    _ffn_block,
    _rope,
)
from dcos_commons_tpu.ops.rmsnorm import rms_norm

Params = Dict[str, Any]
_NEG = -1e30


def init_kv_cache(
    config: TransformerConfig, batch: int, max_len: int,
    kv_dtype: str = "native",
) -> Dict[str, jax.Array]:
    shape = (
        config.n_layers, batch, max_len, config.n_kv_heads,
        config.head_dim,
    )
    if kv_dtype == "int8":
        scale_shape = shape[:-1] + (1,)
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(scale_shape, jnp.float32),
            "v_scale": jnp.zeros(scale_shape, jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, config.dtype),
        "v": jnp.zeros(shape, config.dtype),
    }


def init_paged_kv_cache(
    config: TransformerConfig, n_pages: int, page_tokens: int,
    kv_dtype: str = "native",
) -> Dict[str, jax.Array]:
    """The paged arena: K/V stored as fixed-size pages instead of
    per-request rows.  Shape [n_layers, n_pages, page_tokens,
    n_kv_heads, head_dim]; a request's virtual position ``p`` lives at
    ``(table[p // page_tokens], p % page_tokens)`` through its page
    table.  Page 0 is the TRASH page (serve/paging.py): padding and
    inactive-row writes land there, and table entry 0 also means
    "virtual page unallocated" — those positions are always masked.

    Same dict keys as ``init_kv_cache`` (int8 adds per-vector scales),
    so ``kv_dtype`` handling and sharding rules carry over: dims are
    (layers, pages, page_tokens, kv_heads, head_dim) — kv heads stay
    dim 3, exactly where the gang lays the tp axis."""
    shape = (
        config.n_layers, n_pages, page_tokens, config.n_kv_heads,
        config.head_dim,
    )
    if kv_dtype == "int8":
        scale_shape = shape[:-1] + (1,)
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(scale_shape, jnp.float32),
            "v_scale": jnp.zeros(scale_shape, jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, config.dtype),
        "v": jnp.zeros(shape, config.dtype),
    }


def _quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-vector symmetric int8: each [head_dim] slice gets its own
    max-abs scale.  Decode is HBM-bound on streaming the cache, so
    halving its bytes roughly doubles the throughput roofline; the
    f32 scale adds 4/(head_dim) overhead (~3% at hd=128)."""
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                    keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)
    return q, scale


def _project_kv(config, layer, normed, positions):
    """normed [b, s, d] -> roped q, k, v in [b, s, heads, hd].

    Weights may be weight-only int8 (models/quantize.py); the dequant
    fuses into each projection matmul."""
    b, s, _ = normed.shape
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    q = (normed @ dq(layer["wq"], normed.dtype)).reshape(b, s, h, hd)
    k = (normed @ dq(layer["wk"], normed.dtype)).reshape(b, s, kv, hd)
    v = (normed @ dq(layer["wv"], normed.dtype)).reshape(b, s, kv, hd)
    q = _rope(q, positions, config.rope_theta)
    k = _rope(k, positions, config.rope_theta)
    return q, k, v


def prefill(
    config: TransformerConfig,
    params: Params,
    tokens: jax.Array,
    max_len: int,
    true_len: Optional[jax.Array] = None,
    kv_dtype: str = "native",
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Run the prompt through the trunk, capturing per-layer K/V.

    tokens [b, s] (s <= max_len) -> (logits of the LAST REAL position
    [b, vocab] in f32, cache filled for positions [0, s)).

    ``true_len`` (TRACED, <= s; a scalar for a shared length or a
    [b] vector for PER-ROW lengths) supports RIGHT-padded prompts
    with one compile for every length: causal attention means
    positions < true_len never see the padding, the logits are read
    at true_len - 1 per row, and decode overwrites/masks the pad
    slots — so a server can pad MIXED-length requests to a static
    width without changing any real token's computation.
    """
    b, s = tokens.shape
    if s > max_len:
        raise ValueError(f"prompt {s} exceeds cache max_len {max_len}")
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    x = params["embed"][tokens].astype(config.dtype)
    h, kv = config.n_heads, config.n_kv_heads

    def layer_fn(x, layer):
        from dcos_commons_tpu.ops.attention import flash_attention

        normed = rms_norm(x, layer["attn_norm"])
        q, k, v = _project_kv(config, layer, normed, positions)
        k_full, v_full = k, v
        if kv != h:
            reps = h // kv
            k_full = jnp.repeat(k, reps, axis=2)
            v_full = jnp.repeat(v, reps, axis=2)
        attn = flash_attention(
            *(t.transpose(0, 2, 1, 3) for t in (q, k_full, v_full)),
            causal=True,
            block_q=config.attn_block_q, block_k=config.attn_block_k,
        )
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, -1)
        x = x + attn @ dq(layer["wo"], x.dtype)
        # drop-free MoE routing: serving must not drop prompt tokens
        # (capacity pressure is a training behavior), and the decode
        # steps that continue this cache are drop-free too
        x, _moe_aux = _ffn_block(config, layer, x, decode=True)
        # pad the captured K/V out to the static cache length
        pad = [(0, 0), (0, max_len - s), (0, 0), (0, 0)]
        if kv_dtype == "int8":
            kq, ks = _quantize_kv(k)
            vq, vs = _quantize_kv(v)
            # scales share the pad spec: same axes, trailing dim 1
            return x, (
                jnp.pad(kq, pad), jnp.pad(vq, pad),
                jnp.pad(ks, pad), jnp.pad(vs, pad),
            )
        return x, (jnp.pad(k, pad), jnp.pad(v, pad))

    if kv_dtype == "int8":
        x, (ck, cv, cks, cvs) = lax.scan(layer_fn, x, params["layers"])
        cache = {"k": ck, "v": cv, "k_scale": cks, "v_scale": cvs}
    else:
        x, (ck, cv) = lax.scan(layer_fn, x, params["layers"])
        cache = {"k": ck, "v": cv}
    x = rms_norm(x, params["final_norm"])
    last = (
        jnp.asarray(true_len, jnp.int32) - 1 if true_len is not None
        else jnp.int32(s - 1)
    )
    if last.ndim == 0:
        x_last = lax.dynamic_index_in_dim(x, last, axis=1, keepdims=False)
    else:
        # per-row last REAL position (mixed-length right-padded batch)
        x_last = jnp.take_along_axis(
            x, last[:, None, None], axis=1
        )[:, 0]
    logits = jnp.einsum(
        "bd,vd->bv", x_last.astype(jnp.float32),
        params["embed"].astype(jnp.float32),
    )
    return logits, cache


def prefill_into_slot(
    config: TransformerConfig,
    params: Params,
    cache: Dict[str, jax.Array],
    tokens: jax.Array,
    slot: jax.Array,
    true_len: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Prefill prompt(s) into rows of a PERSISTENT slot-pool cache.

    The continuous-batching form of ``prefill``: the pool cache
    (``init_kv_cache(config, SLOTS, max_len)``) is allocated once and
    lives across requests; this runs ``tokens [nb, s]`` through the
    trunk and scatters the captured per-layer K/V into the pool rows
    at ``slot`` (a TRACED int32 scalar — ``nb`` consecutive rows, the
    nb=1 fast path is ONE dynamic_update_slice per leaf — or a [nb]
    vector of arbitrary rows).  Returns (last-real-position logits
    [nb, vocab] f32, updated cache).

    The whole row is overwritten (``prefill`` pads its capture out to
    the static ``max_len``), so a freed slot needs no scrubbing before
    reuse: nothing of the previous occupant survives admission, and
    ``decode_step``'s per-row valid mask (``<= pos``) never reads past
    what this prefill + subsequent decode writes wrote.  Shapes stay
    static — one compile serves every (slot, prompt content, length)
    the server admits.
    """
    kv_dtype = "int8" if "k_scale" in cache else "native"
    max_len = cache["k"].shape[2]
    logits, row_cache = prefill(
        config, params, tokens, max_len, true_len, kv_dtype=kv_dtype
    )
    slot = jnp.asarray(slot, jnp.int32)
    out = {}
    for name, buf in cache.items():
        new = row_cache[name].astype(buf.dtype)
        if slot.ndim == 0:
            out[name] = lax.dynamic_update_slice(
                buf, new, (0, slot, 0, 0, 0)
            )
        else:
            out[name] = buf.at[:, slot].set(new)
    return logits, out


def sample_token(
    logits: jax.Array, temperature: jax.Array, key: jax.Array
) -> jax.Array:
    """Greedy when ``temperature`` == 0, else softmax sampling — both
    operands TRACED so one compile covers every request.  Works on a
    single row [vocab] or a batch [b, vocab] (one shared key)."""
    temp = jnp.asarray(temperature, jnp.float32)
    sampled = jax.random.categorical(
        key, logits / jnp.maximum(temp, 1e-6), axis=-1
    )
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temp > 0.0, sampled, greedy).astype(jnp.int32)


def decode_step(
    config: TransformerConfig,
    params: Params,
    cache: Dict[str, jax.Array],
    token: jax.Array,
    pos: jax.Array,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One autoregressive step: token [b] at position ``pos`` (int32
    scalar shared by the batch, or a [b] vector for per-row positions
    in a mixed-length batch) -> (logits [b, vocab] f32, updated
    cache).

    The scalar path writes the cache with ONE dynamic_update_slice
    (the HBM-cheapest form); the per-row path scatters b slots via
    ``.at[arange(b), pos]`` — still b slots of bytes, not a full-cache
    rewrite."""
    b = token.shape[0]
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    max_len = cache["k"].shape[2]
    x = params["embed"][token][:, None, :].astype(config.dtype)
    pos = jnp.asarray(pos, jnp.int32)
    per_row = pos.ndim == 1
    if per_row:
        positions = pos[:, None]
        valid = (
            lax.broadcasted_iota(jnp.int32, (1, 1, max_len), 2)
            <= pos[:, None, None]
        )  # [b, 1, max_len]
    else:
        positions = jnp.broadcast_to(pos, (b, 1))
        valid = (
            lax.broadcasted_iota(jnp.int32, (1, 1, max_len), 2) <= pos
        )  # [1, 1, max_len], broadcast over batch and heads

    rows = jnp.arange(b) if per_row else None

    def _cache_write(buf, new):
        """buf [b, L, heads, hd], new [b, 1, heads, hd] at pos."""
        if per_row:
            return buf.at[rows, pos].set(new[:, 0])
        return lax.dynamic_update_slice(buf, new, (0, pos, 0, 0))

    quantized = "k_scale" in cache
    reps = h // kv

    def _attend(q, ck, cv, ks=None, vs=None):
        # grouped GQA contraction against the UNEXPANDED cache: a
        # jnp.repeat to full heads would multiply the cache bytes
        # streamed per step by h/kv in an HBM-bound loop.
        # q [b, 1, kv, reps, hd] x K [b, L, kv, hd] -> [b, kv, reps, L]
        qg = (q.astype(jnp.float32) * hd ** -0.5).reshape(
            b, kv, reps, hd
        )
        scores = jnp.einsum("bkrd,blkd->bkrl", qg, ck.astype(jnp.float32))
        if ks is not None:
            # int8 cache: fold the per-vector K scale into the scores
            # ([b, L, kv, 1] -> [b, kv, 1, L]) and the V scale into
            # the probabilities — the dequantize costs one multiply,
            # never a second pass over the cache bytes
            scores = scores * ks[..., 0].transpose(0, 2, 1)[:, :, None, :]
        scores = jnp.where(valid[:, :, None, :], scores, _NEG)
        probs = jax.nn.softmax(scores, axis=-1)
        if vs is not None:
            probs = probs * vs[..., 0].transpose(0, 2, 1)[:, :, None, :]
        return jnp.einsum(
            "bkrl,blkd->bkrd", probs, cv.astype(jnp.float32)
        ).astype(config.dtype)

    def layer_fn(x, inputs):
        if quantized:
            layer, ck, cv, cks, cvs = inputs
        else:
            layer, ck, cv = inputs
            cks = cvs = None
        normed = rms_norm(x, layer["attn_norm"])
        q, k_new, v_new = _project_kv(config, layer, normed, positions)
        if quantized:
            kq, ks_new = _quantize_kv(k_new)
            vq, vs_new = _quantize_kv(v_new)
            ck = _cache_write(ck, kq)
            cv = _cache_write(cv, vq)
            cks = _cache_write(cks, ks_new)
            cvs = _cache_write(cvs, vs_new)
        else:
            ck = _cache_write(ck, k_new)
            cv = _cache_write(cv, v_new)
        attn = _attend(q, ck, cv, cks, cvs)
        x = x + attn.reshape(b, 1, h * hd) @ dq(layer["wo"], x.dtype)
        x, _moe_aux = _ffn_block(config, layer, x, decode=True)
        if quantized:
            return x, (ck, cv, cks, cvs)
        return x, (ck, cv)

    if quantized:
        x, (ck, cv, cks, cvs) = lax.scan(
            layer_fn,
            x,
            (params["layers"], cache["k"], cache["v"],
             cache["k_scale"], cache["v_scale"]),
        )
        new_cache = {"k": ck, "v": cv, "k_scale": cks, "v_scale": cvs}
    else:
        x, (ck, cv) = lax.scan(
            layer_fn, x, (params["layers"], cache["k"], cache["v"])
        )
        new_cache = {"k": ck, "v": cv}
    x = rms_norm(x, params["final_norm"])
    logits = jnp.einsum(
        "bd,vd->bv", x[:, 0].astype(jnp.float32),
        params["embed"].astype(jnp.float32),
    )
    return logits, new_cache


def paged_prefill_chunk(
    config: TransformerConfig,
    params: Params,
    cache: Dict[str, jax.Array],
    tokens: jax.Array,
    table: jax.Array,
    start: jax.Array,
    true_len: jax.Array,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One CHUNK of a prompt through the trunk into a paged arena.

    ``tokens [1, C]`` carries up to C prompt tokens at virtual
    positions ``[start, start + true_len)`` of one request whose page
    table is ``table [M]`` (physical page per virtual page; 0 =
    unallocated).  K/V for the chunk is scattered through the table
    (pad positions land in the trash page), then each chunk query
    attends to EVERY earlier virtual position — prior chunks' pages,
    prefix-cache pages, and the in-chunk causal prefix — gathered
    through the same table.  Returns (logits at the chunk's last real
    position [1, vocab] f32, updated cache).

    ``start`` and ``true_len`` are TRACED: one compile covers every
    chunk of every prompt — a request resuming at position k*P after
    a prefix-cache hit runs the same program as one starting at 0.
    This is the chunked-prefill entry: a long prompt costs several
    SMALL dispatches interleaved with decode ticks instead of one
    prompt-wide dispatch that blocks the pool (head-of-line TTFT).
    """
    b, c = tokens.shape
    if b != 1:
        raise ValueError(f"prefill chunks are per-request, got batch {b}")
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    p_tok = cache["k"].shape[2]
    m = table.shape[0]
    length = m * p_tok
    quantized = "k_scale" in cache
    reps = h // kv
    start = jnp.asarray(start, jnp.int32)
    true_len = jnp.asarray(true_len, jnp.int32)
    offs = jnp.arange(c, dtype=jnp.int32)
    abs_pos = start + offs                       # [c] virtual positions
    positions = abs_pos[None, :]                 # [1, c]
    vpage = jnp.minimum(abs_pos // p_tok, m - 1)
    # pad positions (>= true_len) scatter into the trash page: their
    # K/V must never land in a real page a later chunk would attend to
    phys = jnp.where(offs < true_len, table[vpage], 0)
    slot_off = abs_pos % p_tok
    # causal across the whole virtual sequence: key position <= query
    # position — covers prior chunks, cached prefix pages, and the
    # in-chunk prefix in one mask; unallocated pages sit past every
    # valid query and mask out
    valid = (
        lax.broadcasted_iota(jnp.int32, (c, length), 1)
        <= abs_pos[:, None]
    )                                            # [c, L]
    x = params["embed"][tokens].astype(config.dtype)

    def layer_fn(x, inputs):
        if quantized:
            layer, ck, cv, cks, cvs = inputs
        else:
            layer, ck, cv = inputs
            cks = cvs = None
        normed = rms_norm(x, layer["attn_norm"])
        q, k_new, v_new = _project_kv(config, layer, normed, positions)
        if quantized:
            kq, ks_new = _quantize_kv(k_new)
            vq, vs_new = _quantize_kv(v_new)
            ck = ck.at[phys, slot_off].set(kq[0])
            cv = cv.at[phys, slot_off].set(vq[0])
            cks = cks.at[phys, slot_off].set(ks_new[0])
            cvs = cvs.at[phys, slot_off].set(vs_new[0])
        else:
            ck = ck.at[phys, slot_off].set(k_new[0])
            cv = cv.at[phys, slot_off].set(v_new[0])
        # gather the request's whole virtual sequence through the
        # table (scatter-then-gather: in-chunk keys ride the same
        # path as prior pages — one attention covers both)
        k_all = ck[table].reshape(1, length, kv, hd)
        v_all = cv[table].reshape(1, length, kv, hd)
        qg = (q.astype(jnp.float32) * hd ** -0.5).reshape(
            1, c, kv, reps, hd
        )
        scores = jnp.einsum(
            "bqkrd,blkd->bqkrl", qg, k_all.astype(jnp.float32)
        )
        if quantized:
            ks_all = cks[table].reshape(1, length, kv)
            vs_all = cvs[table].reshape(1, length, kv)
            scores = scores * ks_all.transpose(0, 2, 1)[:, None, :, None, :]
        scores = jnp.where(valid[None, :, None, None, :], scores, _NEG)
        probs = jax.nn.softmax(scores, axis=-1)
        if quantized:
            probs = probs * vs_all.transpose(0, 2, 1)[:, None, :, None, :]
        attn = jnp.einsum(
            "bqkrl,blkd->bqkrd", probs, v_all.astype(jnp.float32)
        ).astype(config.dtype)
        x = x + attn.reshape(1, c, h * hd) @ dq(layer["wo"], x.dtype)
        x, _moe_aux = _ffn_block(config, layer, x, decode=True)
        if quantized:
            return x, (ck, cv, cks, cvs)
        return x, (ck, cv)

    if quantized:
        x, (ck, cv, cks, cvs) = lax.scan(
            layer_fn, x,
            (params["layers"], cache["k"], cache["v"],
             cache["k_scale"], cache["v_scale"]),
        )
        new_cache = {"k": ck, "v": cv, "k_scale": cks, "v_scale": cvs}
    else:
        x, (ck, cv) = lax.scan(
            layer_fn, x, (params["layers"], cache["k"], cache["v"])
        )
        new_cache = {"k": ck, "v": cv}
    x = rms_norm(x, params["final_norm"])
    x_last = lax.dynamic_index_in_dim(
        x, true_len - 1, axis=1, keepdims=False
    )
    logits = jnp.einsum(
        "bd,vd->bv", x_last.astype(jnp.float32),
        params["embed"].astype(jnp.float32),
    )
    return logits, new_cache


def paged_decode_step(
    config: TransformerConfig,
    params: Params,
    cache: Dict[str, jax.Array],
    token: jax.Array,
    pos: jax.Array,
    tables: jax.Array,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One autoregressive step over the whole pool, KV indirected
    through per-row page tables: ``token [S]`` at per-row positions
    ``pos [S]``, ``tables [S, M]`` mapping each row's virtual pages to
    arena pages -> (logits [S, vocab] f32, updated cache).

    The row's new K/V is scattered to ``(tables[s, pos // P],
    pos % P)`` — inactive rows (all-zero tables) write identical
    values into the trash page — and attention gathers each row's
    pages back into virtual order, so the masked-softmax math is
    element-for-element the slot pool's with ``max_len = M * P``."""
    b = token.shape[0]
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    p_tok = cache["k"].shape[2]
    m = tables.shape[1]
    length = m * p_tok
    x = params["embed"][token][:, None, :].astype(config.dtype)
    pos = jnp.asarray(pos, jnp.int32)
    positions = pos[:, None]
    rows = jnp.arange(b)
    vpage = jnp.minimum(pos // p_tok, m - 1)
    phys = tables[rows, vpage]                   # [b]
    slot_off = pos % p_tok
    valid = (
        lax.broadcasted_iota(jnp.int32, (1, 1, length), 2)
        <= pos[:, None, None]
    )                                            # [b, 1, L]
    quantized = "k_scale" in cache
    reps = h // kv

    def layer_fn(x, inputs):
        if quantized:
            layer, ck, cv, cks, cvs = inputs
        else:
            layer, ck, cv = inputs
            cks = cvs = None
        normed = rms_norm(x, layer["attn_norm"])
        q, k_new, v_new = _project_kv(config, layer, normed, positions)
        if quantized:
            kq, ks_new = _quantize_kv(k_new)
            vq, vs_new = _quantize_kv(v_new)
            ck = ck.at[phys, slot_off].set(kq[:, 0])
            cv = cv.at[phys, slot_off].set(vq[:, 0])
            cks = cks.at[phys, slot_off].set(ks_new[:, 0])
            cvs = cvs.at[phys, slot_off].set(vs_new[:, 0])
        else:
            ck = ck.at[phys, slot_off].set(k_new[:, 0])
            cv = cv.at[phys, slot_off].set(v_new[:, 0])
        k_all = ck[tables].reshape(b, length, kv, hd)
        v_all = cv[tables].reshape(b, length, kv, hd)
        qg = (q.astype(jnp.float32) * hd ** -0.5).reshape(
            b, kv, reps, hd
        )
        scores = jnp.einsum(
            "bkrd,blkd->bkrl", qg, k_all.astype(jnp.float32)
        )
        if quantized:
            ks_all = cks[tables].reshape(b, length, kv)
            vs_all = cvs[tables].reshape(b, length, kv)
            scores = scores * ks_all.transpose(0, 2, 1)[:, :, None, :]
        scores = jnp.where(valid[:, :, None, :], scores, _NEG)
        probs = jax.nn.softmax(scores, axis=-1)
        if quantized:
            probs = probs * vs_all.transpose(0, 2, 1)[:, :, None, :]
        attn = jnp.einsum(
            "bkrl,blkd->bkrd", probs, v_all.astype(jnp.float32)
        ).astype(config.dtype)
        x = x + attn.reshape(b, 1, h * hd) @ dq(layer["wo"], x.dtype)
        x, _moe_aux = _ffn_block(config, layer, x, decode=True)
        if quantized:
            return x, (ck, cv, cks, cvs)
        return x, (ck, cv)

    if quantized:
        x, (ck, cv, cks, cvs) = lax.scan(
            layer_fn, x,
            (params["layers"], cache["k"], cache["v"],
             cache["k_scale"], cache["v_scale"]),
        )
        new_cache = {"k": ck, "v": cv, "k_scale": cks, "v_scale": cvs}
    else:
        x, (ck, cv) = lax.scan(
            layer_fn, x, (params["layers"], cache["k"], cache["v"])
        )
        new_cache = {"k": ck, "v": cv}
    x = rms_norm(x, params["final_norm"])
    logits = jnp.einsum(
        "bd,vd->bv", x[:, 0].astype(jnp.float32),
        params["embed"].astype(jnp.float32),
    )
    return logits, new_cache


def generate(
    config: TransformerConfig,
    params: Params,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature=0.0,
    key: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
    true_len: Optional[jax.Array] = None,
    kv_dtype: str = "native",
) -> jax.Array:
    """Autoregressive continuation: prompt [b, s] -> tokens
    [b, max_new_tokens].  temperature 0 = greedy; otherwise softmax
    sampling with ``key``.  Jit-friendly end to end, ONE compile
    covering every prompt CONTENT, LENGTH (``true_len``: right-padded
    prompts, traced — a scalar, or a [b] vector for MIXED per-row
    lengths so one dispatch serves heterogeneous requests), and
    TEMPERATURE (traced operand — a server must not recompile per
    requested temperature).

    ``kv_dtype="int8"`` stores the cache quantized per vector:
    decode streams half the cache bytes per step, roughly doubling
    the HBM-bound throughput ceiling, at ~0.4%/element quantization
    error (tests/test_decode.py holds logits agreement)."""
    b, s = prompt.shape
    total = max_len if max_len is not None else s + max_new_tokens
    if total < s + max_new_tokens:
        # dynamic_update_slice CLAMPS out-of-range writes, which would
        # silently corrupt the last cache slot instead of failing
        raise ValueError(
            f"max_len {total} cannot hold prompt {s} + "
            f"{max_new_tokens} new tokens"
        )
    if key is None:
        from jax.core import Tracer

        if isinstance(temperature, Tracer):
            # a TRACED temperature could be > 0 at runtime; silently
            # "sampling" with a fixed default key would look stochastic
            # while returning identical tokens every call
            raise ValueError("a traced temperature needs a PRNG key")
        if float(temperature) > 0.0:  # concrete scalars/arrays coerce
            raise ValueError("sampling (temperature > 0) needs a PRNG key")
    logits, cache = prefill(
        config, params, prompt, total, true_len, kv_dtype=kv_dtype
    )
    key = key if key is not None else jax.random.key(0)
    temp = jnp.asarray(temperature, jnp.float32)

    def pick(logits, key):
        # both branches are a few FLOPs on [b, vocab]; selecting
        # beats a cond because temperature stays a traced operand
        sampled = jax.random.categorical(
            key, logits / jnp.maximum(temp, 1e-6), axis=-1
        )
        greedy = jnp.argmax(logits, axis=-1)
        return jnp.where(temp > 0.0, sampled, greedy).astype(jnp.int32)

    # split once up front: the prefill pick and the scan step keys must
    # be derived from DISTINCT keys, or the first sampled token's
    # randomness correlates with the step keys (PRNG key reuse)
    first_key, rest_key = jax.random.split(key)
    first = pick(logits, first_key)
    start = (
        jnp.asarray(true_len, jnp.int32) if true_len is not None
        else jnp.int32(s)
    )

    def step(carry, step_key):
        token, pos, cache = carry
        logits, cache = decode_step(config, params, cache, token, pos)
        nxt = pick(logits, step_key)
        return (nxt, pos + 1, cache), token

    keys = jax.random.split(rest_key, max_new_tokens)
    (_, _, _), out = lax.scan(
        step,
        (first, start, cache),
        keys,
        length=max_new_tokens,
    )
    return out.swapaxes(0, 1)  # [b, max_new_tokens]
