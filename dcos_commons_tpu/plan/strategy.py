"""Rollout strategies: which children of an element may work now.

Reference: scheduler/plan/strategy/ — SerialStrategy, ParallelStrategy,
CanaryStrategy.java:30-58 (N manual proceed() calls then delegate),
DependencyStrategy + DependencyStrategyHelper (arbitrary DAG),
RandomStrategy, StrategyGenerator (YAML names "serial", "parallel",
"serial-canary", "parallel-canary", "random").

Note (SURVEY.md section 2): these are *service-rollout* strategies.
ML tensor parallelism lives in dcos_commons_tpu/parallel/.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Sequence, Set

from dcos_commons_tpu.plan.element import Element


class Strategy:
    """Yields the children eligible to work, given exclusion assets."""

    interruptible = True

    def __init__(self) -> None:
        self._interrupted = False
        self._lock = threading.Lock()

    def candidates(
        self, children: Sequence[Element], dirty_assets: Set[str]
    ) -> List[Element]:
        if self._interrupted:
            return []
        return self._candidates(children, dirty_assets)

    def _candidates(
        self, children: Sequence[Element], dirty_assets: Set[str]
    ) -> List[Element]:
        raise NotImplementedError

    # interrupt/proceed (reference: Interruptible on strategies)

    def interrupt(self) -> None:
        if self.interruptible:
            self._interrupted = True

    def proceed(self) -> None:
        self._interrupted = False

    def is_interrupted(self) -> bool:
        return self._interrupted


def _eligible(child: Element, dirty_assets: Set[str]) -> bool:
    from dcos_commons_tpu.plan.step import Step  # avoid import cycle

    if child.is_complete or child.is_interrupted():
        return False
    if isinstance(child, Step) and child.get_asset_names() & dirty_assets:
        return False
    return True


class SerialStrategy(Strategy):
    """One child at a time, in order (reference: SerialStrategy.java)."""

    def _candidates(self, children, dirty_assets):
        for child in children:
            if child.is_complete:
                continue
            if _eligible(child, dirty_assets):
                return [child]
            return []  # blocked child gates everything after it
        return []


class ParallelStrategy(Strategy):
    """All incomplete children at once (reference: ParallelStrategy.java)."""

    def _candidates(self, children, dirty_assets):
        return [c for c in children if _eligible(c, dirty_assets)]


class CanaryStrategy(Strategy):
    """Deploy a canary, wait for operator confirmation, then the rest.

    Reference: CanaryStrategy.java:30-58 — starts interrupted; each
    ``proceed()`` releases one child until ``canary_count`` children
    have been individually released, after which the delegate strategy
    governs the remainder.  YAML "serial-canary" / "parallel-canary".
    """

    def __init__(self, delegate: Optional[Strategy] = None, canary_count: int = 1):
        super().__init__()
        self._delegate = delegate or SerialStrategy()
        self._canary_count = canary_count
        self._proceeds = 0

    def _candidates(self, children, dirty_assets):
        with self._lock:
            released = self._proceeds
        if released == 0:
            return []
        if released <= self._canary_count:
            # canary phase: only the first `released` children may work
            eligible = [
                c for c in children[:released] if _eligible(c, dirty_assets)
            ]
            return eligible[:1] if isinstance(self._delegate, SerialStrategy) \
                else eligible
        return self._delegate.candidates(children, dirty_assets)

    def proceed(self) -> None:
        with self._lock:
            if self._proceeds <= self._canary_count:
                self._proceeds += 1
        self._interrupted = False

    def interrupt(self) -> None:
        self._interrupted = True

    def is_interrupted(self) -> bool:
        # before first proceed the canary reads as interrupted/waiting
        return self._interrupted or self._proceeds == 0


class DependencyStrategy(Strategy):
    """Arbitrary DAG: a child runs once all its dependencies complete.

    Reference: DependencyStrategy + DependencyStrategyHelper.
    ``edges`` maps child name -> list of prerequisite child names.
    """

    def __init__(self, edges: Dict[str, List[str]]):
        super().__init__()
        self._edges = {k: list(v) for k, v in edges.items()}

    def _candidates(self, children, dirty_assets):
        by_name = {c.name: c for c in children}
        out = []
        for child in children:
            if not _eligible(child, dirty_assets):
                continue
            deps = self._edges.get(child.name, [])
            unknown = [d for d in deps if d not in by_name]
            if unknown:
                continue  # mis-specified dependency: never a candidate
            if all(by_name[d].is_complete for d in deps):
                out.append(child)
        return out


class RandomStrategy(Strategy):
    """Random order, one at a time (reference: RandomStrategy.java)."""

    def __init__(self, rng: Optional[random.Random] = None):
        super().__init__()
        self._rng = rng or random.Random()

    def _candidates(self, children, dirty_assets):
        eligible = [c for c in children if _eligible(c, dirty_assets)]
        return [self._rng.choice(eligible)] if eligible else []


def strategy_for_name(name: str, canary_count: int = 1) -> Strategy:
    """Reference: strategy/StrategyFactory + StrategyGenerator YAML names."""
    name = (name or "serial").strip().lower()
    if name == "serial":
        return SerialStrategy()
    if name == "parallel":
        return ParallelStrategy()
    if name in ("serial-canary", "canary"):
        return CanaryStrategy(SerialStrategy(), canary_count)
    if name == "parallel-canary":
        return CanaryStrategy(ParallelStrategy(), canary_count)
    if name == "random":
        return RandomStrategy()
    raise ValueError(f"unknown strategy {name!r}")
