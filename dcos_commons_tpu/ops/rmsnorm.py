"""Fused RMSNorm kernel.

One VMEM round-trip instead of XLA's usual norm decomposition; rows
stream through the grid in (block_rows, d_model) tiles (VPU work, no
MXU).  f32 statistics regardless of input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    normed = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (normed * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps", "block_rows", "interpret"))
def _pallas_rms_norm(x, w, eps, block_rows, interpret):
    from jax.experimental import pallas as pl

    rows, d = x.shape
    grid = (rows // block_rows,)
    return pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        interpret=interpret,
    )(x, w)


def _reference(x, w, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    out = x32 * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)
    return out.astype(x.dtype)


@functools.lru_cache(maxsize=None)
def _make_rms_norm(eps, block_rows, force_pallas, interpret):
    """Differentiable: Pallas forward, backward via the reference VJP
    (the recompute is one fused elementwise pass — cheap)."""

    @jax.custom_vjp
    def norm(x, w):
        rows = x.shape[0]
        use_pallas = (
            force_pallas or interpret or jax.default_backend() == "tpu"
        )
        if use_pallas and rows % block_rows == 0:
            return _pallas_rms_norm(x, w, eps, block_rows, interpret)
        return _reference(x, w, eps)

    def fwd(x, w):
        return norm(x, w), (x, w)

    def bwd(residuals, g):
        x, w = residuals
        _, vjp = jax.vjp(lambda x_, w_: _reference(x_, w_, eps), x, w)
        return vjp(g)

    norm.defvjp(fwd, bwd)
    return norm


def rms_norm(
    x: jax.Array,
    w: jax.Array,
    eps: float = 1e-6,
    block_rows: int = 256,
    force_pallas: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """RMSNorm over the last axis; any leading shape. Differentiable."""
    shape = x.shape
    flat = x.reshape(-1, shape[-1])
    out = _make_rms_norm(eps, block_rows, force_pallas, interpret)(flat, w)
    return out.reshape(shape)
