"""Dispatch-per-group serving micro-batcher (LEGACY for serving).

One decode step costs nearly the same wall time for 1 or N rows, so
concurrent clients that would otherwise serialize behind the chip are
collected into ONE generate dispatch.  Grouping is by temperature
only (one traced scalar per batch); prompt LENGTHS mix freely because
the compiled function takes a per-row true_len vector
(models/decode.py).

The serve workers no longer use this path: the continuous-batching
slot engine (dcos_commons_tpu/serve/) subsumed it — per-step
admission into a persistent KV slot pool instead of whole-generate
dispatches — and inherits the liveness rules below (FIFO admission,
queue-timeout removal, idle callback).  This class remains as the
honest baseline ``bench_continuous_serve`` measures against, the
generic micro-batching utility, and the home of ``QueueTimeoutError``
(the saturation signal both paths raise and HTTP handlers map to
503).

Liveness rules this class guarantees (the engine inherits them —
the two servers previously diverged and each copy had its own bug):

* FIFO with head-always-dispatches: the oldest pending item is ALWAYS
  in the dispatched group, so a request whose key matches nothing
  (or that repeatedly loses capacity races) cannot starve behind a
  stream of mergeable peers.
* Abandoned work never reaches the chip: a submit() that times out
  removes its item from the queue — a wedged dispatch must not leave
  a backlog of dead requests consuming group capacity on recovery.
* Idle callback: an SPMD gang must keep meeting in collectives even
  with no traffic (followers park in the broadcast); ``on_idle``
  fires every ``idle_every_s`` while the queue is empty, OUTSIDE the
  queue lock.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

import numpy as np


class QueueTimeoutError(RuntimeError):
    """A request expired waiting for chip capacity (the batcher's
    queue timeout, or the serving engine's admission queue).  This
    is server SATURATION, not caller error: HTTP handlers map it to
    503 so load generators and clients can tell overload apart from a
    400 bad request.

    ``kind`` names the starved resource so operators can tell
    saturation-by-memory from saturation-by-compute in the 503 body
    and the split timeout counters (serve/engine.py stats):

    * ``kv-page-budget`` — the request's worst-case KV page need
      never fit the paged arena's budget (memory saturation: add
      pages/HBM, shrink MAX_LEN, or rely on prefix caching);
    * ``kv-slot`` — no decode row freed up (concurrency saturation);
    * ``stalled`` — admitted but the pool produced no new token for a
      full window (compute saturation or a wedged device).
    """

    def __init__(self, message: str = "", kind: str = "kv-slot"):
        super().__init__(message)
        self.kind = kind


class WorkItem:
    __slots__ = ("rows", "n", "temp", "done", "result", "error")

    def __init__(self, rows, n, temp):
        self.rows = rows          # list[list[int]], already validated
        self.n = n                # per-item reply slice length
        self.temp = temp
        self.done = threading.Event()
        self.result = None        # list[list[int]] once served
        self.error = None


class MicroBatcher:
    """Collect concurrent requests into one dispatch.

    ``run_group(items)`` fills each item's ``result`` (or raises — the
    error fans out to the whole group).  A window (seconds) after the
    first arrival lets concurrent clients join the batch; a FULL batch
    dispatches immediately.
    """

    def __init__(
        self,
        run_group: Callable[[List[WorkItem]], None],
        capacity: int,
        window_s: float,
        queue_timeout_s: float = 600.0,
        on_idle: Optional[Callable[[], None]] = None,
        idle_every_s: float = 0.05,
    ):
        self._run_group = run_group
        self._capacity = capacity
        self._window_s = window_s
        self._queue_timeout_s = queue_timeout_s
        self._on_idle = on_idle
        self._idle_every_s = idle_every_s
        self._cv = threading.Condition()
        self._pending: List[WorkItem] = []
        self._thread = threading.Thread(
            target=self._loop, name="microbatch", daemon=True
        )
        self._thread.start()

    def submit(self, item: WorkItem):
        with self._cv:
            self._pending.append(item)
            self._cv.notify()
        if not item.done.wait(timeout=self._queue_timeout_s):
            with self._cv:
                # abandoned work must not reach the chip later: a
                # wedged dispatch would otherwise leave a backlog of
                # dead requests ahead of live ones on recovery
                try:
                    self._pending.remove(item)
                except ValueError:
                    pass  # already grouped: the result will be dropped
            raise QueueTimeoutError(
                "generate timed out in the batch queue"
            )
        if item.error is not None:
            raise item.error
        return item.result

    def _rows_pending(self) -> int:
        return sum(len(item.rows) for item in self._pending)

    def _loop(self):
        while True:
            with self._cv:
                while not self._pending:
                    if self._on_idle is None:
                        self._cv.wait()
                    else:
                        self._cv.wait(timeout=self._idle_every_s)
                        if not self._pending:
                            break  # fire on_idle OUTSIDE the lock
                if not self._pending:
                    idle = True
                    group = []
                else:
                    idle = False
                    if self._window_s > 0:
                        # recruit peers for up to the window — but a
                        # FULL batch dispatches immediately (the window
                        # is only paid when it can still buy merging)
                        import time

                        deadline = time.monotonic() + self._window_s
                        while self._rows_pending() < self._capacity:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                break
                            self._cv.wait(timeout=remaining)
                    if not self._pending:
                        continue  # sole item timed out and removed itself
                    # the head ALWAYS dispatches: grouping by key
                    # equality alone would starve a head whose key
                    # never equals itself (e.g. a NaN temperature that
                    # slipped past validation) and stall every request
                    # queued behind it.  Rejected peers KEEP their
                    # positions — they become the head soon.
                    head = self._pending[0]
                    group, rest, used = [head], [], len(head.rows)
                    for item in self._pending[1:]:
                        if (
                            item.temp == head.temp
                            and used + len(item.rows) <= self._capacity
                        ):
                            group.append(item)
                            used += len(item.rows)
                        else:
                            rest.append(item)
                    self._pending = rest
            if idle:
                try:
                    self._on_idle()
                except Exception:  # noqa: BLE001, sdklint: disable=swallowed-exception — idle hook must not kill serving
                    pass
                continue
            try:
                self._run_group(group)
            except Exception as e:  # noqa: BLE001 — fan the error out
                for item in group:
                    item.error = e
            for item in group:
                item.done.set()


def pack_mixed_rows(group: List[WorkItem], batch: int, prompt_len: int):
    """Right-pad a group's rows into one [batch, prompt_len] prompt
    plus the per-row true_len vector (unused slots get length 1 so
    their discarded computation stays well-formed).  Returns
    (prompt, lens, rows_used)."""
    prompt = np.zeros((batch, prompt_len), np.int32)
    lens = np.ones((batch,), np.int32)
    i = 0
    for item in group:
        for row in item.rows:
            prompt[i, : len(row)] = row
            lens[i] = len(row)
            i += 1
    return prompt, lens, i


def unpack_results(group: List[WorkItem], out) -> None:
    """De-interleave one dispatch's [batch, new_tokens] output back
    into each item's result, sliced to its requested length."""
    i = 0
    for item in group:
        item.result = [
            [int(t) for t in out[i + r, : item.n]]
            for r in range(len(item.rows))
        ]
        i += len(item.rows)
