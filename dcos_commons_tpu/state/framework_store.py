"""FrameworkStore: the control plane's own registration identity.

Reference: state/FrameworkStore.java — stores the Mesos FrameworkID so
a restarted scheduler re-registers as the same framework.  In the TPU
rebuild the analogue is the framework instance id plus the coordinator
address it allocated for `jax.distributed` rendezvous — both must
survive scheduler restart so running pods keep their rendezvous point.
"""

from __future__ import annotations

import json
import uuid
from typing import Optional

from dcos_commons_tpu.storage import Persister, PersisterError


class FrameworkStore:
    ID_PATH = "/framework-id"
    COORD_PATH = "/coordinator-address"

    def __init__(self, persister: Persister) -> None:
        self._persister = persister

    def store_framework_id(self, framework_id: str) -> None:
        self._persister.set(self.ID_PATH, framework_id.encode("utf-8"))

    def fetch_framework_id(self) -> Optional[str]:
        raw = self._persister.get_or_none(self.ID_PATH)
        return raw.decode("utf-8") if raw is not None else None

    def get_or_create_framework_id(self) -> str:
        existing = self.fetch_framework_id()
        if existing:
            return existing
        framework_id = uuid.uuid4().hex
        self.store_framework_id(framework_id)
        return framework_id

    def clear_framework_id(self) -> None:
        """Reference: uninstall DeregisterStep clears the FrameworkID."""
        try:
            self._persister.recursive_delete(self.ID_PATH)
        except PersisterError:
            pass

    # -- coordinator addresses (per pod-type) ------------------------

    def store_coordinator_address(self, pod_type: str, address: str) -> None:
        addrs = self._fetch_addrs()
        addrs[pod_type] = address
        self._persister.set(
            self.COORD_PATH, json.dumps(addrs, sort_keys=True).encode("utf-8")
        )

    def fetch_coordinator_address(self, pod_type: str) -> Optional[str]:
        return self._fetch_addrs().get(pod_type)

    def _fetch_addrs(self) -> dict:
        raw = self._persister.get_or_none(self.COORD_PATH)
        return json.loads(raw.decode("utf-8")) if raw is not None else {}
