"""Multi-service: one framework hosting N services.

Reference: scheduler/multi/ — MultiServiceEventClient (fan-out of
offers/statuses to per-service clients, auto-uninstall of removed
services, MultiServiceEventClient.java:48,169-290),
MultiServiceManager (add/remove/lookup), ServiceStore (persisted specs
for dynamic add via HTTP), OfferDiscipline/ParallelFootprintDiscipline
(bound how many services grow footprint at once,
OfferDiscipline.java:11-33), MultiServiceRunner.
"""

from dcos_commons_tpu.multi.discipline import (
    AnyFootprintDiscipline,
    ParallelFootprintDiscipline,
)
from dcos_commons_tpu.multi.scheduler import MultiServiceScheduler
from dcos_commons_tpu.multi.store import ServiceStore

__all__ = [
    "AnyFootprintDiscipline",
    "ParallelFootprintDiscipline",
    "MultiServiceScheduler",
    "ServiceStore",
]
