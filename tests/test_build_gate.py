"""Build gate: every source file compiles and every module imports.

Reference: the root build's lint/style gates (checkstyle/findbugs in
build.gradle) — the cheap CI tripwire that catches a broken file
before any test exercises it.  Python's analogue: byte-compile every
source file (syntax) and import every library module (broken imports,
circular imports, missing deps) — modules only exercised by slow e2e
paths would otherwise fail late or not at all.
"""

import importlib
import os
import py_compile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _source_files():
    roots = ("dcos_commons_tpu", "frameworks", "tests")
    out = []
    for root in roots:
        for dirpath, dirs, files in os.walk(os.path.join(REPO, root)):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            out += [
                os.path.join(dirpath, f) for f in files
                if f.endswith(".py")
            ]
    out += [
        os.path.join(REPO, f)
        for f in ("bench.py", "__graft_entry__.py")
    ]
    return sorted(out)


def test_every_source_file_compiles(tmp_path):
    failures = []
    for i, path in enumerate(_source_files()):
        try:
            py_compile.compile(
                path, doraise=True, cfile=str(tmp_path / f"{i}.pyc")
            )
        except py_compile.PyCompileError as e:
            failures.append(str(e))
    assert not failures, "\n".join(failures)


def _library_modules():
    pkg_root = os.path.join(REPO, "dcos_commons_tpu")
    for dirpath, dirs, files in os.walk(pkg_root):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in files:
            if not f.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, f), REPO)
            mod = rel[:-3].replace(os.sep, ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            yield mod


@pytest.mark.parametrize("module", sorted(set(_library_modules())))
def test_library_module_imports(module):
    importlib.import_module(module)
