"""T1/L1: per-host agents — launch, kill, observe tasks.

The reference splits this between Mesos agents (launching containers)
and the sdk/bootstrap Go binary running inside each sandbox (DNS wait,
config render, CA install — sdk/bootstrap/main.go:65-98).  The TPU
rebuild owns both halves: an Agent launches worker processes on a host
and provisions the sandbox (env, config templates, libtpu/JAX env),
and reports TaskStatus transitions back to the scheduler.

LocalProcessAgent runs tasks as real subprocesses on this machine —
the integration substrate (every host in the simulated fleet maps to a
sandbox directory).  A production deployment runs one agent per TPU VM
speaking the same interface over DCN; the scheduler does not care.
"""

from dcos_commons_tpu.agent.base import Agent
from dcos_commons_tpu.agent.local import LocalProcessAgent


def __getattr__(name):
    # daemon/remote pull in http machinery; import lazily so the core
    # package stays light for workload-only users
    if name in ("AgentDaemon",):
        from dcos_commons_tpu.agent.daemon import AgentDaemon

        return AgentDaemon
    if name in ("RemoteAgentClient", "RemoteFleet"):
        from dcos_commons_tpu.agent import remote

        return getattr(remote, name)
    raise AttributeError(name)


__all__ = [
    "Agent",
    "AgentDaemon",
    "LocalProcessAgent",
    "RemoteAgentClient",
    "RemoteFleet",
]
