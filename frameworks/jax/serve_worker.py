"""Inference serving task: the flagship behind an HTTP endpoint.

The scheduler deploys this like any other task (svc_serve.yml): it
builds the model, warms the KV-cache generate path (one compile), then
serves POST /generate on the scheduler-assigned port — discoverable
via /v1/endpoints and the VIP.  Readiness: the task's readiness check
passes once the warmup file exists, so the deploy plan completes only
when the server can actually answer.

Request:  {"tokens": [[...]], "max_new_tokens": N, "temperature": T}
Response: {"tokens": [[...]]} — the continuations only.

Concurrency: with SERVE_BATCH > 1 the server MICRO-BATCHES — a decode
step costs nearly the same wall time for 1 or 64 rows, so concurrent
single-prompt clients that would otherwise serialize behind the chip
are collected for MICROBATCH_WINDOW_MS and answered by ONE generate.
MIXED prompt lengths merge too: the compiled function takes a traced
PER-ROW true_len vector (models/decode.py), so heterogeneous clients
share one dispatch — only the temperature groups requests (it is one
traced scalar for the whole batch).
"""

import json
import math
import os
import sys
import threading
import time

import numpy as np
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))


class _WorkItem:
    __slots__ = ("rows", "n", "temp", "done", "result", "error")

    def __init__(self, rows, n, temp):
        self.rows = rows          # list[list[int]], already validated
        self.n = n                # per-item reply slice length
        self.temp = temp
        self.done = threading.Event()
        self.result = None        # list[list[int]] once served
        self.error = None


class _MicroBatcher:
    """Collect concurrent requests into one generate call.

    Groupable = same temperature (ONE traced scalar for the whole
    batch); prompt LENGTHS mix freely — the compiled function takes a
    per-row true_len vector.  Items keep FIFO order; a window (ms)
    after the first arrival lets concurrent clients join the batch —
    the latency cost is the window, the win is that N clients share
    one chip dispatch.
    """

    def __init__(
        self, run_group, capacity: int, window_s: float,
        queue_timeout_s: float = 600.0,
    ):
        self._run_group = run_group   # fn(items) -> None (fills results)
        self._capacity = capacity
        self._window_s = window_s
        self._queue_timeout_s = queue_timeout_s
        self._cv = threading.Condition()
        self._pending = []
        self._thread = threading.Thread(
            target=self._loop, name="microbatch", daemon=True
        )
        self._thread.start()

    def submit(self, item: _WorkItem):
        with self._cv:
            self._pending.append(item)
            self._cv.notify()
        if not item.done.wait(timeout=self._queue_timeout_s):
            with self._cv:
                # abandoned work must not reach the chip later: a
                # wedged generate would otherwise leave a backlog of
                # dead requests ahead of live ones on recovery
                try:
                    self._pending.remove(item)
                except ValueError:
                    pass  # already grouped: the result will be dropped
            raise RuntimeError("generate timed out in the batch queue")
        if item.error is not None:
            raise item.error
        return item.result

    def _rows_pending(self) -> int:
        return sum(len(item.rows) for item in self._pending)

    def _loop(self):
        while True:
            with self._cv:
                while not self._pending:
                    self._cv.wait()
                if self._window_s > 0:
                    # recruit peers for up to the window — but a FULL
                    # batch dispatches immediately (the window is only
                    # paid when it can still buy merging)
                    deadline = time.monotonic() + self._window_s
                    while self._rows_pending() < self._capacity:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(timeout=remaining)
                if not self._pending:
                    continue  # sole item timed out and removed itself
                # the head ALWAYS dispatches: grouping by key equality
                # alone would starve a head whose key never equals
                # itself (e.g. a NaN temperature that slipped past
                # validation) and stall every request queued behind it
                head = self._pending[0]
                group, rest, used = [head], [], len(head.rows)
                for item in self._pending[1:]:
                    if (
                        item.temp == head.temp
                        and used + len(item.rows) <= self._capacity
                    ):
                        group.append(item)
                        used += len(item.rows)
                    else:
                        rest.append(item)
                self._pending = rest
            try:
                self._run_group(group)
            except Exception as e:  # noqa: BLE001 — fan the error out
                for item in group:
                    item.error = e
            for item in group:
                item.done.set()


def main() -> int:
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from dcos_commons_tpu.models import (
        TransformerConfig,
        generate,
        init_params,
    )
    from dcos_commons_tpu.utils import (
        enable_compilation_cache,
        restore_checkpoint,
    )

    enable_compilation_cache()
    config = TransformerConfig(
        vocab=int(os.environ.get("VOCAB", "8192")),
        d_model=int(os.environ.get("D_MODEL", "512")),
        n_layers=int(os.environ.get("N_LAYERS", "4")),
        n_heads=8,
        n_kv_heads=8,
        d_ff=int(os.environ.get("D_FF", "1408")),
        max_seq=int(os.environ.get("SEQ_LEN", "1024")),
        dtype=jnp.bfloat16 if os.environ.get(
            "JAX_PLATFORMS"
        ) != "cpu" else jnp.float32,
        remat=False,
    )
    max_len = int(os.environ.get("MAX_LEN", "256"))
    batch = int(os.environ.get("SERVE_BATCH", "1"))
    new_tokens = int(os.environ.get("MAX_NEW_TOKENS", "32"))

    params = init_params(config, jax.random.key(0))
    ckpt_dir = os.environ.get("CHECKPOINT_DIR", "")
    if ckpt_dir:
        # serve the TRAINED weights when a checkpoint tree exists
        # (the train pod's orbax-style output); params-only restore
        state, step = restore_checkpoint(ckpt_dir, {"params": params})
        if step is not None:
            params = state["params"]
            print(f"restored checkpoint step {step}", flush=True)

    # ONE compile covers every request: static (batch, prompt_len)
    # shapes with prompts RIGHT-padded and the true length TRACED
    # (causal attention means real tokens never see the padding, and
    # decode overwrites/masks the pad slots); temperature is a traced
    # operand too — novel temperatures must not recompile
    prompt_len = max_len - new_tokens
    # KV_DTYPE=int8 halves the cache bytes per decode step: the lever
    # for large serving batches on a full chip (models/decode.py)
    kv_dtype = os.environ.get("KV_DTYPE", "native")
    gen = jax.jit(lambda p, t, key, temp, n: generate(
        config, p, t, max_new_tokens=new_tokens, max_len=max_len,
        temperature=temp, key=key, true_len=n, kv_dtype=kv_dtype,
    ))
    lock = threading.Lock()

    def run_group(items):
        """ONE generate for a compatible group of requests — mixed
        prompt lengths ride the per-row true_len vector."""
        if len(items) > 1:
            print(
                f"microbatch: {len(items)} requests / "
                f"{sum(len(i.rows) for i in items)} rows in one generate",
                flush=True,
            )
        temp = items[0].temp
        padded = np.zeros((batch, prompt_len), np.int32)
        # unused batch slots still flow through the compiled fn: a
        # length of 1 keeps their (discarded) computation well-formed
        lens = np.ones((batch,), np.int32)
        i = 0
        for item in items:
            for row in item.rows:
                padded[i, : len(row)] = row
                lens[i] = len(row)
                i += 1
        # fresh entropy per batch: hashing only the prompt made
        # temperature>0 replies deterministic per process
        seed = int.from_bytes(os.urandom(4), "little")
        with lock:  # one generate at a time per chip
            out = gen(
                params, jnp.asarray(padded),
                jax.random.key(seed),
                jnp.float32(temp),
                jnp.asarray(lens),
            )
        # ONE bulk device->host fetch, then slice in numpy: per-element
        # int(out[i, j]) would be a separate transfer each (~100ms over
        # a TPU relay — 256 of them turned a 1.5s generate into a 36s
        # reply)
        host_out = np.asarray(jax.device_get(out))
        i = 0
        for item in items:
            item.result = [
                [int(t) for t in host_out[i + r, : item.n]]
                for r in range(len(item.rows))
            ]
            i += len(item.rows)

    window_s = float(os.environ.get("MICROBATCH_WINDOW_MS", "5")) / 1e3
    # with a 1-row server there is nothing to batch: the direct path
    # keeps zero added latency (and bit-identical single-client flow)
    queue_timeout_s = float(os.environ.get("SERVE_QUEUE_TIMEOUT_S", "600"))
    batcher = (
        _MicroBatcher(
            run_group, capacity=batch, window_s=window_s,
            queue_timeout_s=queue_timeout_s,
        )
        if batch > 1 else None
    )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            if self.path != "/generate":
                self.send_error(404)
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(length))
                rows = body["tokens"]
                if len(rows) > batch:
                    raise ValueError(
                        f"{len(rows)} prompts > server batch {batch}; "
                        "split the request"
                    )
                lens = {len(row) for row in rows}
                if len(lens) > 1:
                    raise ValueError(
                        "all prompts in one request must share a length"
                    )
                true_len = max(lens, default=0)
                if true_len < 1:
                    raise ValueError("prompts must be non-empty")
                if true_len > prompt_len:
                    # refuse, don't silently continue a DIFFERENT
                    # (truncated) prompt
                    raise ValueError(
                        f"prompt length {true_len} exceeds the server's "
                        f"context {prompt_len}"
                    )
                temp = float(body.get("temperature", 0.0))
                if not math.isfinite(temp) or temp < 0.0:
                    # json.loads accepts NaN/Infinity: a NaN group key
                    # is never equal to itself and must not reach the
                    # batcher (or the chip, where it poisons sampling)
                    raise ValueError(
                        f"temperature must be finite and >= 0, got {temp}"
                    )
                n = int(body.get("max_new_tokens", new_tokens))
                if n < 1:
                    raise ValueError(
                        f"max_new_tokens must be >= 1, got {n}"
                    )
                n = min(n, new_tokens)
                clean_rows = [
                    [int(t) % config.vocab for t in row] for row in rows
                ]
                item = _WorkItem(clean_rows, n, temp)
                if batcher is not None:
                    result = batcher.submit(item)
                else:
                    run_group([item])
                    result = item.result
                payload = json.dumps({"tokens": result}).encode()
                self.send_response(200)
            except Exception as e:  # noqa: BLE001 — surface to client
                payload = json.dumps({"error": str(e)}).encode()
                self.send_response(400)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    # a RELAUNCH reuses the sandbox: a stale ready file from the
    # previous incarnation must not pass readiness while we are cold
    try:
        os.remove("ready")
    except OSError:
        pass
    # bind BEFORE warming and only then write the readiness file — a
    # bind failure (port collision) must fail readiness, not pass it
    port = int(os.environ.get("PORT_HTTP", "0"))
    server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    warm = jnp.zeros((batch, prompt_len), jnp.int32)
    out = gen(
        params, warm, jax.random.key(0), jnp.float32(0.0),
        jnp.full((batch,), prompt_len, jnp.int32),
    )
    jax.block_until_ready(out)
    with open("ready", "w") as f:
        f.write("warm\n")
    print(
        f"warm: serving generate({batch}x{prompt_len}->{new_tokens}) "
        f"on {server.server_address[1]}",
        flush=True,
    )
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
