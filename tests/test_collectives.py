"""Collective benchmark: bandwidth math on the CPU mesh + the sidecar
plan wiring in frameworks/jax/svc.yml.

Reference analogue: the cassandra backup/restore sidecar plans are the
shape (frameworks/cassandra sidecar plans); the bandwidth axis itself
is TPU green-field (BASELINE.json north star: pjit allreduce
GB/s/chip).
"""

import os

import jax
from jax.sharding import Mesh

from dcos_commons_tpu.offer.inventory import make_test_fleet
from dcos_commons_tpu.parallel.collectives import (
    collective_bandwidth,
    single_chip_rooflines,
)
from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    ExpectLaunchedTasks,
    ExpectNoLaunches,
    ExpectPlanStatus,
    PlanStart,
    SendTaskFinished,
    SendTaskRunning,
    ServiceTestRunner,
)

JAX_SVC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "frameworks", "jax", "svc.yml",
)


def test_collective_bandwidth_on_virtual_mesh():
    """All four collectives run, chain, and report positive bandwidth
    on the 8-device CPU mesh (correctness now, line rate on HW)."""
    mesh = Mesh(jax.devices(), ("ici",))
    report = collective_bandwidth(mesh, "ici", payload_mb=0.5, iters=2)
    assert report["axis_size"] == 8.0
    for name in ("psum", "all_gather", "reduce_scatter", "ppermute"):
        assert report[f"{name}_gbps_per_chip"] > 0, report


def test_single_chip_rooflines_report():
    report = single_chip_rooflines(
        payload_mb=4.0, iters=2, chain_floor=2, matmul_dim=256
    )
    assert report["hbm_copy_gbps"] > 0
    assert report["matmul_bf16_tflops"] > 0


def test_collective_bandwidth_single_device_degenerates():
    mesh = Mesh(jax.devices()[:1], ("ici",))
    report = collective_bandwidth(mesh, "ici", payload_mb=0.5, iters=2)
    assert report["axis_size"] == 1.0
    assert "psum_gbps_per_chip" not in report


def test_jax_svc_collectives_sidecar_plan():
    """frameworks/jax svc.yml: deploy launches ONLY the workers (one
    gang step); `plan start collectives` then launches the ONCE
    collective-bench task on every gang member."""
    with open(JAX_SVC) as f:
        yaml_text = f.read()
    hosts = make_test_fleet(host_grid=(2, 2), chip_block=(2, 2))
    runner = ServiceTestRunner(yaml_text, hosts=hosts)
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks(*[f"trainer-{i}-worker" for i in range(4)]),
    ])
    for i in range(4):
        runner.run([SendTaskRunning(f"trainer-{i}-worker")])
    runner.run([
        ExpectDeploymentComplete(),
        AdvanceCycles(2),
        ExpectNoLaunches(),  # sidecar interrupted until started
    ])
    runner.run([
        PlanStart("collectives"),
        AdvanceCycles(1),
        ExpectLaunchedTasks(
            *[f"trainer-{i}-collective-bench" for i in range(4)]
        ),
    ])
    for i in range(4):
        runner.run([SendTaskFinished(f"trainer-{i}-collective-bench")])
    runner.run([ExpectPlanStatus("collectives", Status.COMPLETE)])
    # the workers kept running through the bench
    for i in range(4):
        assert len(runner.world.agent.launches_of(f"trainer-{i}-worker")) == 1


def test_gang_sidecar_group_gets_own_rendezvous():
    """The collectives sidecar on a gang pod rendezvous like the main
    gang: every bench task carries the SAME coordinator address (a
    fresh port, not the trainer's) and its own worker id — without
    this, each bench task measures a single chip instead of the slice.
    """
    with open(JAX_SVC) as f:
        yaml_text = f.read()
    hosts = make_test_fleet(host_grid=(2, 2), chip_block=(2, 2))
    runner = ServiceTestRunner(yaml_text, hosts=hosts)
    runner.run([AdvanceCycles(1)])
    for i in range(4):
        runner.run([SendTaskRunning(f"trainer-{i}-worker")])
    runner.run([
        ExpectDeploymentComplete(),
        PlanStart("collectives"),
        AdvanceCycles(1),
    ])
    agent = runner.world.agent
    coords, worker_ids = set(), set()
    trainer_coord = agent.task_info_of("trainer-0-worker").env[
        "COORDINATOR_ADDRESS"
    ]
    for i in range(4):
        info = agent.task_info_of(f"trainer-{i}-collective-bench")
        assert info is not None, f"bench task {i} not launched"
        coords.add(info.env.get("COORDINATOR_ADDRESS"))
        worker_ids.add(info.env.get("TPU_WORKER_ID"))
    assert len(coords) == 1 and None not in coords
    assert coords != {trainer_coord}, "bench group must not reuse the trainer port"
    assert worker_ids == {"0", "1", "2", "3"}
