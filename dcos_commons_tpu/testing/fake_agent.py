"""FakeAgent: the scripted stand-in for the whole fleet.

Plays the role the mocked SchedulerDriver plays in the reference's sim
harness (reference: sdk/testing/.../ServiceTestRunner.java wires a
Mockito SchedulerDriver; launches/kills are captured, statuses are
injected by `SendTaskStatus` ticks).  Nothing actually runs: launches
are recorded, kills are recorded (and by default acknowledged with a
TASK_KILLED status, since that is what a healthy agent would report),
and tests inject every other status transition explicitly.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from dcos_commons_tpu.common import TaskInfo, TaskState, TaskStatus


class FakeAgent:
    def __init__(self, auto_ack_kills: bool = True):
        self.auto_ack_kills = auto_ack_kills
        # full launch history, in order (never pruned: tests assert on it)
        self.launched: List[TaskInfo] = []
        # kill-call history (task ids, duplicates possible via retries)
        self.kills: List[str] = []
        # last grace period passed to kill() per task id
        self.kill_graces: Dict[str, float] = {}
        self.checks: Dict[str, Dict[str, object]] = {}
        self.payloads: Dict[str, Dict[str, object]] = {}
        # artifact (uris:) entries per launched task id
        self.launch_uris: Dict[str, List[dict]] = {}
        self._active: Dict[str, TaskInfo] = {}
        self._queue: List[TaskStatus] = []
        self._acked_kills: Set[str] = set()
        self.launch_rlimits: Dict[str, list] = {}
        self._lock = threading.RLock()

    # -- Agent interface ---------------------------------------------

    def launch(self, task_infos: List[TaskInfo]) -> None:
        for info in task_infos:
            self.launch_one(info)

    def launch_one(self, info: TaskInfo, readiness=None, health=None,
                   templates=None, files=None, secret_env=None,
                   kill_grace_s: float = 5.0, uris=None,
                   rlimits=None) -> None:
        with self._lock:
            if info.task_id in self._active:
                return  # idempotent, like the real agent
            self._active[info.task_id] = info
            self.launched.append(info)
            self.launch_uris[info.task_id] = list(uris or [])
            self.launch_rlimits[info.task_id] = list(rlimits or [])
            self.checks[info.task_id] = {
                "readiness": readiness,
                "health": health,
            }
            # recorded for Expect assertions (secret files, TLS PEMs)
            self.payloads[info.task_id] = {
                "templates": templates or [],
                "files": files or [],
                "secret_env": dict(secret_env or {}),
            }

    def kill(self, task_id: str, grace_period_s: float = 0.0) -> None:
        with self._lock:
            self.kills.append(task_id)
            self.kill_graces[task_id] = grace_period_s
            if task_id not in self._active:
                return
            if self.auto_ack_kills and task_id not in self._acked_kills:
                self._acked_kills.add(task_id)
                self.send(
                    TaskStatus(
                        task_id=task_id,
                        state=TaskState.KILLED,
                        message="killed by scheduler",
                        agent_id=self._active[task_id].agent_id,
                    )
                )

    def active_task_ids(self) -> Set[str]:
        with self._lock:
            return set(self._active)

    def poll(self) -> List[TaskStatus]:
        with self._lock:
            out = list(self._queue)
            self._queue.clear()
            return out

    # -- scripting surface -------------------------------------------

    def send(self, status: TaskStatus) -> None:
        """Queue a status for the scheduler's next poll; terminal
        statuses also remove the task from the active set (the process
        is gone).  Registered status listeners are notified so an
        event-driven scheduler loop wakes immediately."""
        with self._lock:
            self._queue.append(status)
            if status.state.is_terminal:
                self._active.pop(status.task_id, None)
            listeners = list(getattr(self, "_status_listeners", []))
        for listener in listeners:
            try:
                listener()
            except Exception:  # sdklint: disable=swallowed-exception — same contract as Agent._notify_status: a broken listener must not break intake
                pass

    def add_status_listener(self, listener) -> None:
        """Event-driven wake hook (same contract as Agent's)."""
        with self._lock:
            if not hasattr(self, "_status_listeners"):
                self._status_listeners = []
            self._status_listeners.append(listener)

    def task_id_of(self, task_name: str) -> Optional[str]:
        """Most recent launched task id for a task full-name."""
        with self._lock:
            for info in reversed(self.launched):
                if info.name == task_name:
                    return info.task_id
            return None

    def task_info_of(self, task_name: str) -> Optional[TaskInfo]:
        with self._lock:
            for info in reversed(self.launched):
                if info.name == task_name:
                    return info
            return None

    def launches_of(self, task_name: str) -> List[TaskInfo]:
        with self._lock:
            return [i for i in self.launched if i.name == task_name]

    def killed_names(self) -> List[str]:
        from dcos_commons_tpu.common import task_name_of

        out = []
        with self._lock:
            for task_id in self.kills:
                try:
                    out.append(task_name_of(task_id))
                except ValueError:
                    pass
        return out

    def fail_host(self, host_id: str) -> List[str]:
        """Preemption semantics: every task process on ``host_id``
        dies SILENTLY — no terminal status is ever reported (the
        machine is gone, nothing is left to report it).  Returns the
        reaped task ids.  Detection is the control plane's job: the
        preempt verb / agent plane synthesizes the TASK_LOSTs."""
        with self._lock:
            gone = [
                task_id
                for task_id, info in self._active.items()
                if info.agent_id == host_id
            ]
            for task_id in gone:
                self._active.pop(task_id, None)
            return gone

    def shutdown(self) -> None:
        with self._lock:
            self._active.clear()
            self._queue.clear()
