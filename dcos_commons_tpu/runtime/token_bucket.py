"""Token bucket rate limiter.

Reference: framework/TokenBucket.java — bounds revive calls so a
flapping work-set cannot hammer the master; we use it to bound
full-inventory rescans and log storms.
"""

from __future__ import annotations

import threading
import time


class TokenBucket:
    def __init__(self, capacity: int = 256, refill_interval_s: float = 5.0,
                 clock=time.monotonic):
        if capacity < 1 or refill_interval_s <= 0:
            raise ValueError("bad token bucket parameters")
        self._capacity = capacity
        self._tokens = capacity
        self._interval = refill_interval_s
        self._clock = clock
        self._last_refill = clock()
        self._lock = threading.Lock()

    def try_acquire(self) -> bool:
        with self._lock:
            now = self._clock()
            refills = int((now - self._last_refill) / self._interval)
            if refills > 0:
                self._tokens = min(self._capacity, self._tokens + refills)
                self._last_refill += refills * self._interval
            if self._tokens > 0:
                self._tokens -= 1
                return True
            return False
