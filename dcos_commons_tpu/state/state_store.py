"""StateStore: CRUD for TaskInfo/TaskStatus/properties/goal overrides.

Reference: state/StateStore.java:58,213-569 and
state/GoalStateOverride.java (the PAUSED state machine behind
``pod pause``/``resume``, http/queries/PodQueries.java:183-203).

Layout under the service namespace:
    /tasks/<task_name>/info        TaskInfo JSON
    /tasks/<task_name>/status      TaskStatus JSON
    /tasks/<task_name>/override    goal-state override JSON
    /properties/<key>              raw bytes
"""

from __future__ import annotations

import enum
import json
import threading
from typing import Dict, List, Optional

from dcos_commons_tpu.common import TaskInfo, TaskState, TaskStatus
from dcos_commons_tpu.storage import Persister, PersisterError, SetOp
from dcos_commons_tpu.storage.persister import namespace_root


class StateStoreException(Exception):
    pass


class GoalStateOverride(enum.Enum):
    """Reference: state/GoalStateOverride.java — NONE or PAUSED."""

    NONE = "NONE"
    PAUSED = "PAUSED"


class OverrideProgress(enum.Enum):
    """Progress of applying an override (relaunch w/ sleep cmd)."""

    COMPLETE = "COMPLETE"
    PENDING = "PENDING"
    IN_PROGRESS = "IN_PROGRESS"


class StateStore:
    def __init__(self, persister: Persister, namespace: str = "") -> None:
        self._persister = persister
        # namespacing supports multi-service mode, where each service
        # gets its own subtree (reference: SchedulerBuilder namespacing,
        # scheduler/multi/).
        self._root = namespace_root(namespace)
        self._lock = threading.RLock()
        # decode cache: path -> (raw bytes, decoded object).  The
        # persister read stays authoritative (correct under external
        # writers — HA failover, multi mode); only the JSON decode is
        # skipped, and only when the fetched bytes are EQUAL to the
        # cached ones.  Fetched objects are treated as immutable
        # everywhere (with_label copies), so sharing is safe.  At
        # fleet scale the recovery scan fetches every task each cycle:
        # decoding ~1000 identical JSON blobs per cycle was the
        # scheduler loop's single largest cost.
        self._decode_cache: Dict[str, tuple] = {}
        # task-subtree change stamp for generation-stamped readers
        # (the /v1/endpoints discovery contract, ISSUE 12): every
        # info/status/override mutation bumps it, so a quiet fleet's
        # endpoint poll is one compare.  Per-OBJECT counter + epoch,
        # the ReservationLedger discipline: a rebuilt store (failover,
        # live update) re-bases counters under a fresh epoch so stale
        # stamps can never alias
        import uuid as _uuid

        self._task_mutation = 0
        self._task_epoch = _uuid.uuid4().hex[:12]

    @property
    def task_generation(self) -> str:
        """Opaque change stamp of the task subtree (epoch-qualified
        mutation counter): equal stamps guarantee an identical task/
        status/override set, so endpoint discovery can skip rebuilds."""
        with self._lock:
            return f"{self._task_epoch}.{self._task_mutation}"

    def _bump_task_generation_locked(self) -> None:
        self._task_mutation += 1

    @property
    def persister(self) -> Persister:
        return self._persister

    def _task_path(self, task_name: str, leaf: str = "") -> str:
        if not task_name or "/" in task_name:
            raise StateStoreException(f"invalid task name: {task_name!r}")
        base = f"{self._root}/tasks/{task_name}"
        return f"{base}/{leaf}" if leaf else base

    # -- TaskInfo -----------------------------------------------------

    def store_tasks(self, infos: List[TaskInfo]) -> None:
        """Atomically store TaskInfos (reference: StateStore.storeTasks).

        Written transactionally so the launch WAL semantics hold: either
        every task of a gang-scheduled pod is recorded or none is.
        """
        with self._lock:
            ops = [
                SetOp(self._task_path(info.name, "info"), info.to_bytes())
                for info in infos
            ]
            self._persister.apply(ops)
            self._bump_task_generation_locked()

    def _decode(self, path: str, raw: bytes, decoder):
        with self._lock:
            hit = self._decode_cache.get(path)
            if hit is not None and hit[0] == raw:
                return hit[1]
        obj = decoder(raw)
        with self._lock:
            self._decode_cache[path] = (raw, obj)
        return obj

    def fetch_task(self, task_name: str) -> Optional[TaskInfo]:
        path = self._task_path(task_name, "info")
        raw = self._persister.get_or_none(path)
        if raw is None:
            return None
        return self._decode(path, raw, TaskInfo.from_bytes)

    def fetch_task_names(self) -> List[str]:
        return self._persister.get_children_or_empty(f"{self._root}/tasks")

    def fetch_tasks(self) -> List[TaskInfo]:
        tasks = []
        for name in self.fetch_task_names():
            info = self.fetch_task(name)
            if info is not None:
                tasks.append(info)
        return tasks

    # -- TaskStatus ---------------------------------------------------

    def store_status(self, task_name: str, status: TaskStatus) -> bool:
        """Reference: StateStore.storeStatus (StateStore.java:257).

        The reference validates that the status belongs to the stored
        task-id; stale updates from older launches (normal after a
        relaunch) are dropped rather than crashing the status fan-in.
        Returns False when the update was dropped as stale.
        """
        with self._lock:
            info = self.fetch_task(task_name)
            if info is not None and info.task_id and status.task_id != info.task_id:
                return False
            self._persister.set(
                self._task_path(task_name, "status"), status.to_bytes()
            )
            self._bump_task_generation_locked()
            return True

    def fetch_status(self, task_name: str) -> Optional[TaskStatus]:
        path = self._task_path(task_name, "status")
        raw = self._persister.get_or_none(path)
        if raw is None:
            return None
        return self._decode(path, raw, TaskStatus.from_bytes)

    def fetch_statuses(self) -> Dict[str, TaskStatus]:
        out: Dict[str, TaskStatus] = {}
        for name in self.fetch_task_names():
            status = self.fetch_status(name)
            if status is not None:
                out[name] = status
        return out

    def store_launch(self, infos: List[TaskInfo]) -> None:
        """Atomically WAL a gang launch: every info + a seeded STAGING
        status land in ONE persister transaction, so a crash can never
        leave a pod half-recorded (reference: PersistentLaunchRecorder
        via DefaultScheduler.java:454-455).
        """
        ops = []
        for info in infos:
            ops.append(SetOp(self._task_path(info.name, "info"), info.to_bytes()))
            status = TaskStatus(
                task_id=info.task_id,
                state=TaskState.STAGING,
                agent_id=info.agent_id,
                message="launch recorded (WAL)",
            )
            ops.append(SetOp(self._task_path(info.name, "status"), status.to_bytes()))
        with self._lock:
            self._persister.apply(ops)
            self._bump_task_generation_locked()

    # -- task removal (decommission / GC) ----------------------------

    def clear_task(self, task_name: str) -> None:
        """Reference: StateStore.clearTask, used by EraseTaskStateStep."""
        try:
            self._persister.recursive_delete(self._task_path(task_name))
        except PersisterError:
            pass
        with self._lock:
            # keep the decode cache bounded: removed tasks never
            # come back under the same bytes-validated entries
            for leaf in ("info", "status"):
                self._decode_cache.pop(
                    self._task_path(task_name, leaf), None
                )
            self._bump_task_generation_locked()

    # -- goal-state overrides (pod pause/resume) ----------------------

    def store_goal_override(
        self,
        task_name: str,
        override: GoalStateOverride,
        progress: OverrideProgress,
    ) -> None:
        payload = json.dumps(
            {"override": override.value, "progress": progress.value}
        ).encode("utf-8")
        self._persister.set(self._task_path(task_name, "override"), payload)
        with self._lock:
            self._bump_task_generation_locked()

    def fetch_goal_override(
        self, task_name: str
    ) -> tuple[GoalStateOverride, OverrideProgress]:
        raw = self._persister.get_or_none(self._task_path(task_name, "override"))
        if raw is None:
            return (GoalStateOverride.NONE, OverrideProgress.COMPLETE)
        data = json.loads(raw.decode("utf-8"))
        return (
            GoalStateOverride(data["override"]),
            OverrideProgress(data["progress"]),
        )

    # -- properties ---------------------------------------------------

    def store_property(self, key: str, value: bytes) -> None:
        _validate_property_key(key)
        self._persister.set(f"{self._root}/properties/{key}", value)

    def fetch_property(self, key: str) -> Optional[bytes]:
        _validate_property_key(key)
        return self._persister.get_or_none(f"{self._root}/properties/{key}")

    def fetch_property_keys(self) -> List[str]:
        return self._persister.get_children_or_empty(f"{self._root}/properties")

    def clear_property(self, key: str) -> None:
        _validate_property_key(key)
        try:
            self._persister.recursive_delete(f"{self._root}/properties/{key}")
        except PersisterError:
            pass

    # -- deployment-completed bit ------------------------------------

    # Reference: StateStoreUtils.setDeploymentWasCompleted — records
    # that the initial deploy finished so scheduler restarts pick the
    # *update* plan rather than re-deploying (SchedulerBuilder.java:644).
    _DEPLOY_COMPLETED = "deployment-completed"

    def set_deployment_completed(self) -> None:
        self.store_property(self._DEPLOY_COMPLETED, b"true")

    def deployment_was_completed(self) -> bool:
        return self.fetch_property(self._DEPLOY_COMPLETED) == b"true"


def _validate_property_key(key: str) -> None:
    if not key or "/" in key:
        raise StateStoreException(f"invalid property key: {key!r}")
