"""Multi-host SHARDED SERVING gang end to end with real processes.

The serving half of the flagship at gang scale, driven for real: a
tp=2 serving gang deploys over agent daemon processes, each worker a
REAL ``frameworks/jax`` serve_gang_worker that rendezvouses via
jax.distributed and holds HALF the tensor-parallel-sharded model;
worker 0 answers POST /generate by broadcasting each request so the
whole gang executes ONE pjit'd generate.  Killing a daemon flips the
WHOLE gang to recovery; the replacement gang re-rendezvouses off the
dead host and greedy replies are TOKEN-IDENTICAL before and after —
sharded serving survives host loss with no answer drift.

Reference bar: sim-level behavior coverage for every workload shape
(sdk/testing/.../ServiceTestRunner.java:38); the reference never
serves models, so the gang/SPMD serving shape is the TPU-first
addition this test pins down.
"""

import json
import os
import urllib.request

import pytest

from dcos_commons_tpu.testing.integration import (
    AgentProcess,
    SchedulerProcess,
    reap_orphan_tasks,
    wait_for,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_topology(path, agents):
    """One slice, a 2x2 host grid of 1-chip hosts: the 1x2 gang fits
    in either column, so losing one host leaves a full column free."""
    grids = [(0, 0), (0, 1), (1, 0), (1, 1)]
    lines = ["hosts:"]
    for agent, (gx, gy) in zip(agents, grids):
        lines += [
            f"  - host_id: {agent.host_id}",
            f"    agent_url: {agent.url}",
            "    hostname: 127.0.0.1",
            "    slice_id: s0",
            "    generation: v5e",
            f"    grid: [{gx}, {gy}]",
            "    chip_block: [1, 1]",
            "    cpus: 4.0",
            "    memory_mb: 8192",
        ]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def _post(port, payload, timeout=90):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


@pytest.mark.slow
@pytest.mark.parametrize("quant", ["native", "int8"])
def test_sharded_serving_gang_failover_token_identical(tmp_path, quant):
    agents = [
        AgentProcess(f"s{i}", str(tmp_path / f"agent-{i}"), REPO)
        for i in range(4)
    ]
    svc = tmp_path / "svc.yml"
    with open(
        os.path.join(REPO, "frameworks", "jax", "svc_serve_gang.yml")
    ) as f:
        svc.write_text(f.read())
    topology = tmp_path / "topology.yml"
    _write_topology(str(topology), agents)
    scheduler = SchedulerProcess(
        str(svc), str(topology), str(tmp_path / "sched"),
        env={
            "ENABLE_BACKOFF": "false",
            "PERMANENT_FAILURE_TIMEOUT_S": "1",
            "JAX_FRAMEWORK_DIR": os.path.join(REPO, "frameworks", "jax"),
            "TASKCFG_ALL_JAX_PLATFORMS": "cpu",
            "TASKCFG_ALL_REPO_ROOT": REPO,
            # tiny flagship: 2-process Gloo mesh compiles in seconds
            "VOCAB": "64",
            "D_MODEL": "32",
            "N_LAYERS": "2",
            "D_FF": "64",
            "SEQ_LEN": "64",
            "MAX_LEN": "48",
            "MAX_NEW_TOKENS": "8",
            "SERVE_BATCH": "2",
            # parametrized: "native" covers the operator-default gang;
            # "int8" runs the FULL serving quantization stack sharded
            # (weights quantize AFTER placement — GSPMD-derived int8 +
            # scale shardings — and the cache stores int8).  Every
            # assertion below is served-vs-served self-consistency, so
            # both gangs must hold them all, across failover
            "WEIGHT_DTYPE": quant,
            "KV_DTYPE": quant,
        },
        repo_root=REPO,
    )
    try:
        client = scheduler.client()
        client.wait_for_completed_deployment(timeout_s=240)

        def gang_infos():
            return {
                i["name"]: i
                for idx in (0, 1)
                for i in client.get(f"/v1/pod/server-{idx}/info")
            }

        infos = gang_infos()
        assert set(infos) == {"server-0-api", "server-1-api"}
        port = int(infos["server-0-api"]["env"]["PORT_HTTP"])

        # the sharded gang answers; greedy is deterministic
        first = _post(port, {"tokens": [[1, 2, 3, 4]], "max_new_tokens": 8})
        assert len(first["tokens"][0]) == 8
        assert first == _post(
            port, {"tokens": [[1, 2, 3, 4]], "max_new_tokens": 8}
        )
        # concurrent MIXED-length clients: each gets its own correct
        # greedy continuation (the gang micro-batches them into shared
        # dispatches via the per-row true_len broadcast)
        import threading

        prompts = [[1, 2, 3, 4], [9, 8], [5, 6, 7, 2, 1]]
        sequential = [
            _post(port, {"tokens": [p], "max_new_tokens": 8})["tokens"][0]
            for p in prompts
        ]
        concurrent = [None] * len(prompts)
        conc_errors = []

        def one_client(i):
            try:
                concurrent[i] = _post(
                    port, {"tokens": [prompts[i]], "max_new_tokens": 8}
                )["tokens"][0]
            except Exception as e:  # noqa: BLE001
                conc_errors.append(e)

        threads = [
            threading.Thread(target=one_client, args=(i,))
            for i in range(len(prompts))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not conc_errors, conc_errors
        assert concurrent == sequential
        # ONE multi-row request with MIXED lengths pins the per-row
        # lens path deterministically (the concurrent phase above only
        # merges when thread timing races the requests into one tick)
        mixed = _post(
            port,
            {"tokens": [prompts[0], prompts[1]], "max_new_tokens": 8},
        )
        assert mixed["tokens"] == [sequential[0], sequential[1]]
        # worker 0's log proves the request ran the GANG path
        rank0_host = infos["server-0-api"]["agent_id"]
        rank0_agent = next(a for a in agents if a.host_id == rank0_host)
        stdout = os.path.join(
            rank0_agent.workdir, "sandboxes", "server-0-api", "stdout"
        )
        with open(stdout, errors="replace") as f:
            log = f.read()
        # the request ran the GANG path: a tp-sharded server over the
        # union of both processes' devices (device count per process
        # follows the test env's virtual-device flag)
        assert "serving sharded generate" in log and " tp=" in log

        # kill the host serving worker 1: ONE host loss must flip the
        # WHOLE gang to recovery (SPMD serving cannot limp on half a
        # model)
        old_ids = {n: i["task_id"] for n, i in infos.items()}
        victim_host = infos["server-1-api"]["agent_id"]
        victim = next(a for a in agents if a.host_id == victim_host)
        victim.kill()

        def gang_replaced():
            try:
                now = gang_infos()
            except Exception:
                return None
            if set(now) != set(old_ids):
                return None
            if any(now[n]["task_id"] == old_ids[n] for n in now):
                return None  # gang-atomic: BOTH workers replaced
            if any(i["agent_id"] == victim_host for i in now.values()):
                return None  # nothing lands on the dead host
            return now

        replaced = wait_for(gang_replaced, 180.0, interval_s=2.0,
                            what="whole serving gang replaced")

        # the REPLACEMENT gang serves the IDENTICAL greedy continuation
        new_port = int(replaced["server-0-api"]["env"]["PORT_HTTP"])

        def serves_again():
            try:
                return _post(
                    new_port,
                    {"tokens": [[1, 2, 3, 4]], "max_new_tokens": 8},
                    timeout=30,
                )
            except Exception:
                return None

        answer = wait_for(serves_again, 240.0, interval_s=3.0,
                          what="replacement gang serving")
        assert answer == first, (
            f"failover changed the greedy reply: {first} -> {answer}"
        )
    finally:
        scheduler.terminate()
        for agent in agents:
            agent.stop()
        reap_orphan_tasks(agents)
