"""configcheck: whole-pipeline env/config contract analysis.

The SDK's config contract is a PIPELINE, not a file: package options
(``options.json``) render to an env map (``tools/options.py``), the
env map interpolates the service YAML's ``{{VAR:-default}}`` templates
(``specification/yaml_spec.py``), the rendered per-task ``env:`` block
rides the launch path into the worker process
(``offer/evaluate.py``), and the worker — or a scheduler-side consumer
reading the task's env, like the health plane's SLO watcher — finally
casts the string to a typed knob.  Each hop has its own defaulting
rule, so the same knob can hold FOUR different defaults (options,
template, YAML-only, in-code) that silently disagree: the
``microbatch_window_ms`` 5-vs-0 drift and the ``TPU_CHIPS_PER_HOST``
leak were both this bug class.  configcheck rebuilds the whole flow
graph statically and cross-checks every hop.

The graph has three sides:

(a) **Reads** — an AST pass over ``dcos_commons_tpu/`` and
    ``frameworks/`` harvests every env read with its inferred cast
    (the surrounding ``int()``/``float()``/bool-ish membership test /
    ``json.loads``) and in-code default (literal ``.get`` second arg
    or the ``... or <literal>`` fallback).  A read is any
    ``.get("X")``/``["X"]`` on ``os.environ`` or on a receiver named
    like an env-carrying parameter (``env``/``_env``/``task_env``) —
    which is how the blessed contract helpers
    (``models.config_from_env``, ``serve/paging.paged_config_from_env``,
    ``parallel/mesh.derive``, ``SchedulerConfig.from_env``) are
    modeled: a function whose env-like *parameter* is read becomes a
    helper, helpers passing that parameter to other helpers inherit
    their reads transitively, and a worker calling a helper with
    ``os.environ`` inherits the closure.  Files that read env keys
    *dynamically* (``env.get(knob)`` over a table, like the SLO
    watcher's SIGNALS rows) contribute their UPPER_SNAKE table
    constants as indirect reads.

(b) **Sets** — every ``env:`` key, ``{{VAR:-default}}`` template and
    ``{{#VAR}}`` section of each ``frameworks/*/*.yml``, rendered with
    the framework's real ``options.json`` defaults via the real
    renderer, joined per pod/task to the worker script its ``cmd``
    runs (shardcheck's script-basename keying, widened to every
    ``.py`` shipped in the framework dir).  The launch path's own
    injections (``offer/evaluate.py`` ``ENV_*`` contract,
    ``TpuSpec.mesh_env()``, port ``env-key``s, inline ``VAR=`` cmd
    assignments, the ambient sandbox vars) count as provided.

(c) **Options** — every ``options.json`` option and the env name it
    renders under.

Rules (YAML/inline-suppressible via ``# sdklint: disable=<rule>``;
options.json findings suppress via the schema's ``x-sdklint-disable``
list since JSON carries no comments):

- ``config-undeclared-read``   a joined worker script reads a var with
  NO default path at all (``env["X"]``) that neither the task env nor
  the launch path provides — a guaranteed KeyError at task runtime.
- ``config-dead-var``          a YAML ``env:`` key that nothing in the
  tree reads (directly, via a helper, or via a dynamic table).
- ``config-type-mismatch``     a rendered YAML value or a template
  default the read-site cast cannot parse (``int("abc")`` at launch).
- ``config-default-drift``     an in-code or template default that
  disagrees with the options.json default for the same env name — the
  microbatch bug class: which default applies depends on HOW you
  deploy.
- ``config-options-orphan``    an options.json option whose env name
  renders in no YAML of its framework: dead operator surface.

``--json`` emits trend keys ``config.env_vars`` (distinct vars in the
graph), ``config.flows`` (joined YAML-env-to-worker-read edges) and
``config.per_rule`` so the bench trajectory tracks coverage.  The
``--docs`` flag renders the graph to ``docs/config-reference.md``.
"""

from __future__ import annotations

import ast
import json as _json
import os
import re
from dataclasses import dataclass, field, replace
from typing import (
    Any,
    Dict,
    FrozenSet,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from dcos_commons_tpu.analysis.linter import (
    Finding,
    LintResult,
    Suppressions,
)

_VAR_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
# receivers whose .get("X")/["X"] counts as an env read: the process
# env itself plus the names env-carrying parameters conventionally
# take across the tree (contract helpers, scheduler-side task-env
# readers like ``info.env.get``)
_ENV_RECEIVERS = frozenset({"environ", "env", "_env", "task_env"})
# vars every task inherits outside the YAML env block: the agent's
# sandbox contract plus ambient toolchain switches the deploy wrapper
# exports (developer-guide §3)
_AMBIENT_VARS = frozenset({
    "SANDBOX", "REPO_ROOT", "JAX_PLATFORMS", "XLA_FLAGS",
    "PATH", "HOME", "PYTHONPATH",
})
# inline `VAR=value` assignments at the front of a task cmd
_CMD_ASSIGN_RE = re.compile(r"\b([A-Z][A-Z0-9_]*)=")
_SECTION_TAG_RE = re.compile(r"\{\{[#^/]([A-Za-z0-9_]+)\}\}")


@dataclass(frozen=True)
class EnvRead:
    """One harvested env read: where, how it's cast, what it defaults
    to when the var is absent."""

    var: str
    file: str                   # repo-relative posix path
    line: int
    cast: str = "str"           # int | float | bool | json | str
    default: Optional[str] = None
    # default applied via ``... or <literal>``: an EMPTY string also
    # falls back (the `{{VAR:-}}` template idiom pairs with this)
    or_default: bool = False
    # subscript read with no default path at all (env["X"])
    required: bool = False
    via: str = "direct"         # direct | helper:<name> | indirect
    comment: str = ""           # adjacent comment, for --docs


@dataclass
class _FuncInfo:
    """Per-function facts feeding the helper-closure resolution."""

    name: str
    args: FrozenSet[str]
    # env reads whose receiver is one of this function's own params
    param_reads: List[EnvRead] = field(default_factory=list)
    # (callee terminal name, params passed through) pass edges
    passes: List[Tuple[str, FrozenSet[str]]] = field(default_factory=list)


@dataclass
class FileHarvest:
    """Everything the AST pass learned about one .py file."""

    rel: str
    lines: List[str] = field(default_factory=list)
    suppressions: Suppressions = field(
        default_factory=lambda: Suppressions([])
    )
    reads: List[EnvRead] = field(default_factory=list)
    funcs: List[_FuncInfo] = field(default_factory=list)
    # helper names this file calls with a concrete env (os.environ)
    helper_calls: Set[str] = field(default_factory=set)
    # file contains a dynamic read (env.get(<name>)) — its UPPER_SNAKE
    # table constants were harvested as indirect reads
    dynamic: bool = False


def _terminal(node) -> str:
    """Terminal name of a dotted expression: os.environ -> 'environ',
    info.env -> 'env', env -> 'env'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _unwrap(node):
    """See through ``(env or {})``-style guards to the receiver."""
    while isinstance(node, ast.BoolOp) and node.values:
        node = node.values[0]
    return node


def _const_str(value) -> Optional[str]:
    """A literal default as the string the env would carry."""
    if value is None:
        return None
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)


def _infer_cast(node, parents) -> str:
    """The cast the read site applies: the enclosing int()/float()/
    bool()/json.loads() call, or a ``(not) in (...)`` membership test
    (the tree's bool idiom).  Climbs through ``or``-defaults."""
    cur = node
    for _ in range(5):
        par = parents.get(cur)
        if par is None:
            return "str"
        if isinstance(par, ast.BoolOp):
            cur = par
            continue
        if isinstance(par, ast.Call):
            if cur in par.args:
                name = _terminal(par.func)
                if name in ("int", "float", "bool"):
                    return name
                if name == "loads":
                    return "json"
            return "str"
        if isinstance(par, ast.Compare):
            if par.left is cur and par.ops and isinstance(
                par.ops[0], (ast.In, ast.NotIn)
            ):
                return "bool"
            return "str"
        return "str"
    return "str"


def _adjacent_comment(lines: Sequence[str], lineno: int) -> str:
    """The trailing comment on the read line, else the contiguous
    comment block directly above — the --docs description source."""
    if 1 <= lineno <= len(lines):
        text = lines[lineno - 1]
        if "#" in text:
            frag = text.split("#", 1)[1].strip()
            if frag and "sdklint:" not in frag:
                return frag
    out: List[str] = []
    i = lineno - 2
    while i >= 0 and lines[i].strip().startswith("#"):
        frag = lines[i].strip().lstrip("#").strip()
        if frag and "sdklint:" not in frag:
            out.insert(0, frag)
        i -= 1
    return " ".join(out)


def _harvest_file(path: str, rel: str) -> FileHarvest:
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    lines = source.splitlines()
    fh = FileHarvest(
        rel=rel, lines=lines, suppressions=Suppressions(lines)
    )
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        # the build gate (py_compile) owns syntax errors
        return fh

    parents: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent

    # function spans, innermost-wins lookup by line
    spans: List[Tuple[int, int, _FuncInfo]] = []
    infos: Dict[int, _FuncInfo] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            names = {x.arg for x in a.args + a.posonlyargs + a.kwonlyargs}
            if a.vararg:
                names.add(a.vararg.arg)
            if a.kwarg:
                names.add(a.kwarg.arg)
            info = _FuncInfo(name=node.name, args=frozenset(names))
            infos[id(node)] = info
            fh.funcs.append(info)
            spans.append(
                (node.lineno, node.end_lineno or node.lineno, info)
            )

    def enclosing(line: int) -> Optional[_FuncInfo]:
        best: Optional[Tuple[int, _FuncInfo]] = None
        for lo, hi, info in spans:
            if lo <= line <= hi and (best is None or lo > best[0]):
                best = (lo, info)
        return best[1] if best else None

    def add_read(node, var: str, receiver: str, cast: str,
                 default: Optional[str], or_default: bool,
                 required: bool) -> None:
        read = EnvRead(
            var=var, file=rel, line=node.lineno, cast=cast,
            default=default, or_default=or_default, required=required,
            comment=_adjacent_comment(lines, node.lineno),
        )
        fh.reads.append(read)
        enc = enclosing(node.lineno)
        if enc is not None and receiver in enc.args:
            enc.param_reads.append(read)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = _terminal(node.func)
            recv = ""
            if isinstance(node.func, ast.Attribute):
                recv = _terminal(_unwrap(node.func.value))
            is_get = fname == "get" and recv in _ENV_RECEIVERS
            is_getenv = fname == "getenv"
            if is_get or is_getenv:
                receiver = recv if is_get else "environ"
                arg0 = node.args[0] if node.args else None
                if isinstance(arg0, ast.Constant) and isinstance(
                    arg0.value, str
                ) and _VAR_RE.match(arg0.value):
                    default, or_default = None, False
                    if len(node.args) >= 2:
                        if isinstance(node.args[1], ast.Constant):
                            default = _const_str(node.args[1].value)
                    else:
                        par = parents.get(node)
                        if isinstance(par, ast.BoolOp) and isinstance(
                            par.op, ast.Or
                        ) and par.values and par.values[0] is node \
                                and len(par.values) > 1 and isinstance(
                                    par.values[1], ast.Constant):
                            default = _const_str(par.values[1].value)
                            or_default = default is not None
                    add_read(
                        node, arg0.value, receiver,
                        _infer_cast(node, parents), default,
                        or_default, required=False,
                    )
                elif isinstance(arg0, ast.Name) and is_get:
                    # table-driven read (SIGNALS rows): the file's
                    # UPPER_SNAKE tuple constants become indirect reads
                    fh.dynamic = True
            elif fname and fname != "get":
                # helper call / pass-through edge detection
                envish: List[str] = []
                args = list(node.args) + [
                    kw.value for kw in node.keywords
                ]
                for arg in args:
                    u = _unwrap(arg)
                    if isinstance(u, ast.Attribute) \
                            and u.attr == "environ":
                        envish.append("__environ__")
                    elif isinstance(u, ast.Name) \
                            and u.id in _ENV_RECEIVERS:
                        envish.append(u.id)
                if envish:
                    enc = enclosing(node.lineno)
                    enc_args = enc.args if enc else frozenset()
                    passed = frozenset(
                        e for e in envish if e in enc_args
                    )
                    if passed and enc is not None:
                        enc.passes.append((fname, passed))
                    if "__environ__" in envish or any(
                        e not in enc_args for e in envish
                        if e != "__environ__"
                    ):
                        fh.helper_calls.add(fname)
        elif isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            recv = _terminal(_unwrap(node.value))
            if recv in _ENV_RECEIVERS and isinstance(
                node.slice, ast.Constant
            ) and isinstance(node.slice.value, str) \
                    and _VAR_RE.match(node.slice.value):
                add_read(
                    node, node.slice.value, recv,
                    _infer_cast(node, parents), None, False,
                    required=True,
                )

    if fh.dynamic:
        for node in ast.walk(tree):
            if isinstance(node, (ast.Tuple, ast.List)):
                for elt in node.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ) and "_" in elt.value \
                            and _VAR_RE.match(elt.value):
                        fh.reads.append(EnvRead(
                            var=elt.value, file=rel,
                            line=elt.lineno, via="indirect",
                            comment=_adjacent_comment(
                                lines, elt.lineno
                            ),
                        ))
    return fh


@dataclass
class Harvest:
    """The resolved read side of the flow graph."""

    files: Dict[str, FileHarvest] = field(default_factory=dict)
    # helper name -> reads reachable through its env parameter
    helpers: Dict[str, List[EnvRead]] = field(default_factory=dict)

    def reads_by_var(self) -> Dict[str, List[EnvRead]]:
        out: Dict[str, List[EnvRead]] = {}
        for rel in sorted(self.files):
            for read in self.files[rel].reads:
                out.setdefault(read.var, []).append(read)
        return out

    def vars_read(self) -> Set[str]:
        return {
            read.var
            for fh in self.files.values()
            for read in fh.reads
        }

    def script_reads(self, rel: str) -> List[EnvRead]:
        """A worker script's full read set: its own file reads plus
        the closure of every helper it calls with ``os.environ``."""
        fh = self.files.get(rel)
        if fh is None:
            return []
        out = list(fh.reads)
        seen = {(r.file, r.line, r.var) for r in out}
        for name in sorted(fh.helper_calls):
            for read in self.helpers.get(name, []):
                key = (read.file, read.line, read.var)
                if key not in seen:
                    seen.add(key)
                    out.append(replace(read, via=f"helper:{name}"))
        return out


def _resolve_helpers(
    files: Dict[str, FileHarvest]
) -> Dict[str, List[EnvRead]]:
    """Merge env-param reads by function name, then propagate along
    pass-through edges (``mesh_from_env(env)`` calling ``derive(env)``
    inherits derive's reads) to a fixpoint."""
    reads: Dict[str, Dict[Tuple[str, int, str], EnvRead]] = {}
    edges: Dict[str, Set[str]] = {}
    for fh in files.values():
        for info in fh.funcs:
            if info.param_reads:
                bucket = reads.setdefault(info.name, {})
                for r in info.param_reads:
                    bucket[(r.file, r.line, r.var)] = r
            for callee, _passed in info.passes:
                edges.setdefault(info.name, set()).add(callee)
    for _ in range(len(edges) + 2):
        changed = False
        for caller, callees in edges.items():
            bucket = reads.setdefault(caller, {})
            for callee in callees:
                if callee == caller:
                    continue
                for key, r in reads.get(callee, {}).items():
                    if key not in bucket:
                        bucket[key] = r
                        changed = True
        if not changed:
            break
    return {
        name: sorted(
            bucket.values(), key=lambda r: (r.file, r.line, r.var)
        )
        for name, bucket in reads.items()
        if bucket
    }


def harvest_tree(
    root: str,
    subdirs: Sequence[str] = ("dcos_commons_tpu", "frameworks"),
) -> Harvest:
    harvest = Harvest()
    for sub in subdirs:
        top = os.path.join(root, sub)
        for dirpath, dirs, names in os.walk(top):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for name in sorted(names):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                harvest.files[rel] = _harvest_file(path, rel)
    harvest.helpers = _resolve_helpers(harvest.files)
    return harvest


def runtime_provided_vars(root: str) -> FrozenSet[str]:
    """Vars the launch path injects beyond the YAML env block: the
    ``ENV_*`` contract constants of offer/evaluate.py (harvested, so
    the vocabulary can never drift from the launch code) plus the
    ambient sandbox set."""
    out = set(_AMBIENT_VARS)
    path = os.path.join(root, "dcos_commons_tpu", "offer", "evaluate.py")
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return frozenset(out)
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.startswith("ENV_") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out.add(node.value.value)
    return frozenset(out)


# -- the YAML / options side -------------------------------------------


def template_occurrences(
    lines: Sequence[str],
) -> List[Tuple[str, Optional[str], int, str]]:
    """Every ``{{VAR:-default}}`` / ``{{VAR}}`` / ``{{#VAR}}`` in a
    YAML, as (var, default-or-None, line, kind) — the same grammar the
    real renderer applies (yaml_spec._TEMPLATE_RE)."""
    from dcos_commons_tpu.specification.yaml_spec import _TEMPLATE_RE

    occ: List[Tuple[str, Optional[str], int, str]] = []
    for i, text in enumerate(lines, start=1):
        # ignore comment tails: a '#' at BOL or after whitespace
        code = re.split(r"(?:^|\s)#", text, 1)[0]
        for m in _TEMPLATE_RE.finditer(code):
            occ.append((m.group(1), m.group(2), i, "var"))
        for m in _SECTION_TAG_RE.finditer(code):
            occ.append((m.group(1), None, i, "section"))
    return occ


def _truthy(value: str) -> bool:
    # yaml_spec._truthy's vocabulary, shared with PREFIX_CACHE-style
    # "not in ('0', 'false')" reads
    return str(value).strip().lower() not in ("", "false", "0", "no")


def _defaults_equal(candidate: Optional[str], opt: Dict[str, Any]) -> bool:
    """Does a code/template default agree with the options default,
    normalized per the option's declared type?  Empty string counts
    as 0/false (the ``{{VAR:-}}`` + ``int(... or 0)`` idiom)."""
    if candidate is None or "default" not in opt:
        return True
    default = opt["default"]
    otype = opt.get("type")
    if otype == "boolean":
        return _truthy(candidate) == bool(default)
    if otype in ("integer", "number"):
        text = str(candidate).strip() or "0"
        try:
            return float(text) == float(default)
        except (TypeError, ValueError):
            return False
    return str(candidate) == str(default)


def _value_fails_cast(value: Any, read: EnvRead) -> bool:
    """Would this YAML string crash the read site's cast at launch?"""
    if read.cast not in ("int", "float", "json"):
        return False
    text = str(value)
    if text == "" and read.or_default:
        return False  # `... or default` readers fall back on empty
    try:
        if read.cast == "int":
            int(text)
        elif read.cast == "float":
            float(text)
        else:
            _json.loads(text)
    except (TypeError, ValueError):
        return True
    return False


def _make_anchor(lines: Sequence[str]):
    """Findings anchor to (and suppress at) the declaring ``<name>:``
    line, like speccheck's and shardcheck's."""
    def anchor(name: str) -> int:
        pattern = re.compile(rf"^\s*{re.escape(str(name))}\s*:")
        for i, text in enumerate(lines, start=1):
            if pattern.match(text):
                return i
        return 1
    return anchor


def _key_line(lines: Sequence[str], key: str, start: int) -> int:
    """The line declaring env key ``key`` at/after ``start`` (the pod
    anchor), so per-key findings suppress at their own line."""
    pattern = re.compile(rf"^\s*{re.escape(key)}\s*:")
    for i in range(max(start - 1, 0), len(lines)):
        if pattern.match(lines[i]):
            return i + 1
    return start


def _options_env_line(lines: Sequence[str], env_name: str) -> int:
    needle = f'"{env_name}"'
    for i, text in enumerate(lines, start=1):
        if '"env"' in text and needle in text:
            return i
    return 1


@dataclass
class ConfigResult(LintResult):
    """LintResult plus the flow-graph surfaces the CLI's trend keys
    and the --docs generator render from."""

    # var -> {type, default, options, set_by, read_by, description}
    env_vars: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    # joined YAML-env -> worker-script edges
    flows: List[Dict[str, str]] = field(default_factory=list)
    per_rule: Dict[str, int] = field(default_factory=dict)


def _yml_files(framework_dir: str) -> List[str]:
    return sorted(
        os.path.join(framework_dir, f)
        for f in os.listdir(framework_dir)
        if f.endswith(".yml")
    )


def analyze_framework(
    framework_dir: str,
    root: str,
    harvest: Harvest,
    runtime: FrozenSet[str],
    var_table: Dict[str, Dict[str, Any]],
    flows: List[Dict[str, str]],
) -> ConfigResult:
    from dcos_commons_tpu.specification.yaml_spec import from_yaml_file
    from dcos_commons_tpu.tools import options as options_mod

    result = ConfigResult()
    fw_rel = os.path.relpath(framework_dir, root).replace(os.sep, "/")
    disabled: Set[str] = set()
    schema = None
    options_env: Dict[str, str] = {}
    try:
        schema = options_mod.load_schema(framework_dir)
        if schema is not None:
            disabled = {
                str(r) for r in schema.get("x-sdklint-disable") or []
            }
            options_env = options_mod.render_options(schema, {})
    except options_mod.OptionsError:
        schema = None  # speccheck owns schema errors

    options_info: Dict[str, Dict[str, Any]] = {}
    options_rel = f"{fw_rel}/options.json"
    if schema is not None:
        with open(
            os.path.join(framework_dir, "options.json"),
            "r", encoding="utf-8",
        ) as f:
            opt_lines = f.read().splitlines()
        for section, option, opt in options_mod._iter_options(schema):
            env_name = opt.get("env") or options_mod.default_env_name(
                section, option
            )
            options_info[env_name] = {
                "section": section,
                "option": option,
                "opt": opt,
                "line": _options_env_line(opt_lines, env_name),
            }
        result.files_checked += 1

    scripts = sorted(
        f for f in os.listdir(framework_dir) if f.endswith(".py")
    )
    all_read_vars = harvest.vars_read()
    reads_by_var = harvest.reads_by_var()
    rendered_vars: Set[str] = set()

    def record_set(var: str, where: str, desc: str = "") -> None:
        info = var_table.setdefault(var, {
            "set_by": set(), "read_by": set(), "casts": set(),
            "code_defaults": set(), "options": "",
            "options_default": None, "options_type": "",
            "description": "",
        })
        info["set_by"].add(where)
        if desc and not info["description"]:
            info["description"] = desc

    for path in _yml_files(framework_dir):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
        suppressions = Suppressions(lines)
        anchor = _make_anchor(lines)
        result.files_checked += 1
        yml_findings: List[Finding] = []
        raw_text = "\n".join(lines)
        occurrences = template_occurrences(lines)
        occ_lines: Dict[str, Set[int]] = {}
        for var, _default, line, _kind in occurrences:
            occ_lines.setdefault(var, set()).add(line)

        for var, default, line, kind in occurrences:
            rendered_vars.add(var)
            info = options_info.get(var)
            if info is None or kind != "var":
                continue
            # drift only bites the env→code contract: a template that
            # feeds a harvested read can hand the worker a different
            # default per deploy mode.  Pure spec-field templates
            # (cpus/memory/count sizing) legitimately vary per
            # example YAML and are speccheck's domain.
            if default is not None and var in reads_by_var \
                    and not _defaults_equal(default, info["opt"]):
                yml_findings.append(Finding(
                    rel, line, "config-default-drift",
                    f"template default {{{{{var}:-{default}}}}} drifts "
                    f"from options.json {info['section']}."
                    f"{info['option']} default "
                    f"{info['opt'].get('default')!r} — a YAML-only "
                    "deploy and an options-rendered deploy disagree",
                ))
            if default is not None:
                for r in reads_by_var.get(var, []):
                    if _value_fails_cast(default, r):
                        yml_findings.append(Finding(
                            rel, line, "config-type-mismatch",
                            f"template default {{{{{var}:-{default}}}}} "
                            f"cannot pass the {r.cast}() cast at "
                            f"{r.file}:{r.line} — a YAML-only deploy "
                            "crashes the reader",
                        ))
                        break

        try:
            spec = from_yaml_file(path, options_env)
        except Exception:  # sdklint: disable=swallowed-exception — speccheck owns render/spec errors; configcheck only walks specs that render
            spec = None
        if spec is not None:
            for pod in spec.pods:
                pod_line = anchor(pod.type)
                mesh_keys = (
                    set(pod.tpu.mesh_env()) if pod.tpu else set()
                )
                for task in pod.tasks:
                    port_keys = {
                        p.env_key
                        for p in task.resources.ports if p.env_key
                    }
                    cmd_keys = set(
                        _CMD_ASSIGN_RE.findall(task.cmd or "")
                    )
                    provided = (
                        set(task.env) | mesh_keys | port_keys
                        | cmd_keys | runtime
                    )
                    script = next(
                        (s for s in scripts if s in (task.cmd or "")),
                        None,
                    )
                    script_rel = f"{fw_rel}/{script}" if script else ""
                    sreads = (
                        harvest.script_reads(script_rel)
                        if script else []
                    )
                    sreads_by_var: Dict[str, List[EnvRead]] = {}
                    for r in sreads:
                        sreads_by_var.setdefault(r.var, []).append(r)
                    seen_required: Set[str] = set()
                    for r in sreads:
                        if r.required and r.var not in provided \
                                and r.var not in seen_required:
                            seen_required.add(r.var)
                            yml_findings.append(Finding(
                                rel, pod_line,
                                "config-undeclared-read",
                                f"pod {pod.type!r} task "
                                f"{task.name!r}: {script} reads "
                                f"${r.var} ({r.file}:{r.line}) with "
                                "no default, but the task env does "
                                "not set it and the launch path does "
                                "not inject it",
                            ))
                    for key, value in task.env.items():
                        key_line = _key_line(lines, key, pod_line)
                        desc = _adjacent_comment(lines, key_line)
                        record_set(
                            key, f"{rel} pod {pod.type}", desc
                        )
                        readers = sreads_by_var.get(key, [])
                        if readers:
                            flows.append({
                                "yaml": rel,
                                "pod": pod.type,
                                "task": task.name,
                                "script": script_rel,
                                "var": key,
                            })
                        for r in readers:
                            if _value_fails_cast(value, r):
                                yml_findings.append(Finding(
                                    rel, key_line,
                                    "config-type-mismatch",
                                    f"pod {pod.type!r} env "
                                    f"{key}={value!r} cannot pass "
                                    f"the {r.cast}() cast at "
                                    f"{r.file}:{r.line}",
                                ))
                                break
                        # a var the YAML itself consumes elsewhere
                        # (a {{KEY}} template outside this env line,
                        # or a $KEY shell expansion in a cmd) is
                        # alive even with no Python reader
                        alive_in_yaml = bool(
                            occ_lines.get(key, set()) - {key_line}
                        ) or f"${key}" in raw_text \
                            or f"${{{key}}}" in raw_text
                        if key not in all_read_vars \
                                and not alive_in_yaml:
                            yml_findings.append(Finding(
                                rel, key_line, "config-dead-var",
                                f"pod {pod.type!r} sets env {key} "
                                "but nothing in the tree reads it "
                                "(directly, via a contract helper, "
                                "a dynamic table, or the YAML's own "
                                "templates/cmds)",
                            ))

        for f in yml_findings:
            if f.rule in disabled or "all" in disabled \
                    or suppressions.covers(f):
                result.suppressed.append(f)
            else:
                result.findings.append(f)

    # options side: orphans + code-default drift against the schema
    for env_name, info in sorted(options_info.items()):
        opt = info["opt"]
        record_set(
            env_name,
            f"{options_rel} {info['section']}.{info['option']}",
            str(opt.get("description", "")),
        )
        var_table[env_name]["options"] = (
            f"{info['section']}.{info['option']}"
        )
        var_table[env_name]["options_default"] = opt.get("default")
        var_table[env_name]["options_type"] = opt.get("type", "")
        if env_name not in rendered_vars:
            f = Finding(
                options_rel, info["line"], "config-options-orphan",
                f"option {info['section']}.{info['option']} renders "
                f"env {env_name}, which no {fw_rel} YAML template "
                "consumes — dead operator surface",
            )
            if f.rule in disabled or "all" in disabled:
                result.suppressed.append(f)
            else:
                result.findings.append(f)
        for r in reads_by_var.get(env_name, []):
            if r.default is None or r.via == "indirect":
                continue
            if not _defaults_equal(r.default, opt):
                f = Finding(
                    r.file, r.line, "config-default-drift",
                    f"in-code default {r.default!r} for {env_name} "
                    f"drifts from options.json {info['section']}."
                    f"{info['option']} default "
                    f"{opt.get('default')!r} — which default applies "
                    "depends on how the worker is launched",
                )
                fh = harvest.files.get(r.file)
                if f.rule in disabled or "all" in disabled or (
                    fh is not None and fh.suppressions.covers(f)
                ):
                    result.suppressed.append(f)
                else:
                    result.findings.append(f)

    result.findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return result


def _finalize_var_table(
    var_table: Dict[str, Dict[str, Any]],
) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for var in sorted(var_table):
        info = var_table[var]
        casts = info["casts"] - {"str"}
        if info["options_type"]:
            vtype = {
                "integer": "int", "number": "float",
                "boolean": "bool", "string": "str",
            }.get(info["options_type"], info["options_type"])
        elif casts:
            vtype = sorted(casts)[0]
        else:
            vtype = "str"
        if info["options_default"] is not None:
            default = _const_str(info["options_default"])
        elif len(info["code_defaults"]) == 1:
            default = next(iter(info["code_defaults"]))
        elif info["code_defaults"]:
            default = "varies: " + ", ".join(
                sorted(info["code_defaults"])
            )
        else:
            default = ""
        out[var] = {
            "type": vtype,
            "default": default,
            "options": info["options"],
            "set_by": sorted(info["set_by"]),
            "read_by": sorted(info["read_by"]),
            "description": info["description"],
        }
    return out


def analyze_all(root: str) -> ConfigResult:
    result = ConfigResult()
    harvest = harvest_tree(root)
    runtime = runtime_provided_vars(root)
    var_table: Dict[str, Dict[str, Any]] = {}
    flows: List[Dict[str, str]] = []

    frameworks_dir = os.path.join(root, "frameworks")
    if os.path.isdir(frameworks_dir):
        for name in sorted(os.listdir(frameworks_dir)):
            framework_dir = os.path.join(frameworks_dir, name)
            if not os.path.isdir(framework_dir):
                continue
            sub = analyze_framework(
                framework_dir, root, harvest, runtime,
                var_table, flows,
            )
            result.findings += sub.findings
            result.suppressed += sub.suppressed
            result.files_checked += sub.files_checked

    result.files_checked += len(harvest.files)
    for rel in sorted(harvest.files):
        for r in harvest.files[rel].reads:
            info = var_table.setdefault(r.var, {
                "set_by": set(), "read_by": set(), "casts": set(),
                "code_defaults": set(), "options": "",
                "options_default": None, "options_type": "",
                "description": "",
            })
            info["read_by"].add(f"{r.file}:{r.line}")
            info["casts"].add(r.cast)
            if r.default is not None:
                info["code_defaults"].add(r.default)
            if r.comment and not info["description"]:
                info["description"] = r.comment

    # dedup (two frameworks can re-report the same code-drift site)
    seen: Set[Tuple[str, int, str, str]] = set()
    deduped: List[Finding] = []
    for f in result.findings:
        key = (f.file, f.line, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            deduped.append(f)
    result.findings = sorted(
        deduped, key=lambda f: (f.file, f.line, f.rule)
    )
    result.flows = sorted(
        flows, key=lambda e: (e["yaml"], e["pod"], e["task"], e["var"])
    )
    result.env_vars = _finalize_var_table(var_table)
    result.per_rule = {rule: 0 for rule, _ in CONFIG_RULES}
    for f in result.findings:
        result.per_rule[f.rule] = result.per_rule.get(f.rule, 0) + 1
    return result


# -- docs generation (--docs) ------------------------------------------


def _first_sentence(text: str) -> str:
    text = " ".join(str(text).split())
    for sep in (". ", "; "):
        if sep in text:
            text = text.split(sep, 1)[0] + sep.strip()
            break
    return text.replace("|", "\\|")


def render_config_reference(result: ConfigResult) -> str:
    """The committed ``docs/config-reference.md``: one row per env
    var in the flow graph.  Deterministic (sorted, no timestamps) so
    the lint gate can assert the committed copy is current."""
    lines = [
        "# Config reference",
        "",
        "<!-- generated by `python -m dcos_commons_tpu.analysis "
        "config --docs`; do not edit by hand — the lint gate "
        "(tests/test_lint_gate.py) asserts this file matches the "
        "analyzer's output -->",
        "",
        f"Every environment variable configcheck's flow graph tracks "
        f"({len(result.env_vars)} vars, {len(result.flows)} joined "
        "YAML-env-to-worker edges) across the options.json → YAML "
        "template → task env → reader pipeline.  *Set by* lists the "
        "YAML pods / options that produce the var (empty = the "
        "process env or launch path provides it); *read by* lists "
        "every harvested read site.",
        "",
        "| Variable | Type | Default | Set by | Read by |"
        " Description |",
        "|---|---|---|---|---|---|",
    ]
    for var, info in sorted(result.env_vars.items()):
        set_by = "; ".join(info["set_by"]) or "(process env)"
        read_by = "; ".join(info["read_by"]) or "—"
        default = str(info["default"]).replace("|", "\\|")
        lines.append(
            f"| `{var}` | {info['type']} | {default or '—'} | "
            f"{set_by} | {read_by} | "
            f"{_first_sentence(info['description']) or '—'} |"
        )
    return "\n".join(lines) + "\n"


def write_config_reference(root: str, result: ConfigResult) -> str:
    path = os.path.join(root, "docs", "config-reference.md")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(render_config_reference(result))
    return path


CONFIG_RULES = (
    ("config-undeclared-read",
     "a joined worker script reads a var with no default that "
     "neither the task env nor the launch path provides"),
    ("config-dead-var",
     "a YAML env key nothing in the tree reads"),
    ("config-type-mismatch",
     "a YAML value or template default the read-site cast cannot "
     "parse"),
    ("config-default-drift",
     "an in-code or template default disagreeing with the "
     "options.json default for the same knob"),
    ("config-options-orphan",
     "an options.json option whose env name renders in no YAML of "
     "its framework"),
)


def config_rule_catalog() -> str:
    lines = ["configcheck rules (env/config contract):", ""]
    for rule_id, description in CONFIG_RULES:
        lines.append(f"  {rule_id}")
        lines.append(f"      {description}")
    return "\n".join(lines)
