"""Inference serving task: the flagship behind an HTTP endpoint.

The scheduler deploys this like any other task (svc_serve.yml): it
builds the model, warms the slot-pool decode path (two compiles —
prefill-into-slot and one pool decode step), then serves POST
/generate on the scheduler-assigned port — discoverable via
/v1/endpoints and the VIP.  Readiness: the task's readiness check
passes once the warmup file exists, so the deploy plan completes only
when the server can actually answer.

Request:  {"tokens": [[...]], "max_new_tokens": N, "temperature": T,
           "eos": E?}
Response: {"tokens": [[...]]} — the continuations only (cut at E when
          the row produced it).
Errors:   400 = caller error (bad prompt/params); 503 = server
          saturation (the request timed out waiting for a KV slot) —
          load generators must be able to tell these apart.

Concurrency: CONTINUOUS BATCHING over a persistent PAGED KV arena
(dcos_commons_tpu/serve/, ISSUE 11): KV memory is a fixed budget of
KV_PAGE_TOKENS-sized pages with per-request page tables — a short
reply holds exactly the pages its tokens need instead of stranding a
MAX_LEN row, admission is page-budgeted (a request enters only when
its worst-case page need fits, and the 503 body says whether memory
or compute saturated), prompts prefill PREFILL_CHUNK_TOKENS at a time
interleaved with decode ticks (a long prompt no longer blocks the
tick it rides), and fully-prefilled prompt pages are shared read-only
across requests with the same prefix (prefix caching — the system-
prompt multiplier).  KV_PAGE_TOKENS=0 falls back to the PR 6 slot
pool (SERVE_SLOTS x MAX_LEN rows).  Mixed prompt lengths, requested
lengths AND temperatures still share one pool dispatch, and greedy
outputs are token-identical on both paths.  GET /stats exposes the
serving gauges (queue depth, KV occupancy, kv_pages_free,
prefix_cache_hit_rate, prefill_chunk_backlog, tokens/s); the same
snapshot lands in the sandbox for the scheduler's /v1/debug/serving.
"""

import json
import math
import os
import sys

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))

from dcos_commons_tpu.serve import (  # noqa: E402
    SERVESTATS_NAME,
    PagedEngine,
    SlotEngine,
    paged_config_from_env,
)
from dcos_commons_tpu.serve.migration import (  # noqa: E402
    HttpEngineClient,
    MigrationError,
    PrefillHandoff,
    SessionMigratedError,
    SessionSnapshot,
    drain_sessions,
)
from dcos_commons_tpu.utils.microbatch import (  # noqa: E402
    MicroBatcher,
    QueueTimeoutError,
    WorkItem,
)

# back-compat aliases (unit tests drive the legacy batcher through
# this module's names; the slot engine subsumed it for serving)
_MicroBatcher = MicroBatcher
_WorkItem = WorkItem


def main() -> int:
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from dcos_commons_tpu.metrics.registry import Metrics
    from dcos_commons_tpu.models import config_from_env, init_params
    from dcos_commons_tpu.serve.pool import PagedPoolModel, PoolModel
    from dcos_commons_tpu.utils import (
        enable_compilation_cache,
        restore_checkpoint,
    )

    enable_compilation_cache()
    config = config_from_env(
        os.environ,
        dtype=jnp.bfloat16 if os.environ.get(
            "JAX_PLATFORMS"
        ) != "cpu" else jnp.float32,
        remat=False,
    )
    max_len = int(os.environ.get("MAX_LEN", "256"))
    # unset SERVE_BATCH means a bare/dev launch; fall back to one
    # request rather than the deploy default 8 (see options.json
    # serving.batch description)
    # sdklint: disable=config-default-drift — dev fallback
    batch = int(os.environ.get("SERVE_BATCH", "1"))
    # the slot POOL defaults to the request cap; SERVE_SLOTS decouples
    # them (more concurrent residents than any one request may carry);
    # "" and 0 both mean "use SERVE_BATCH" (the options.json default)
    slots = int(os.environ.get("SERVE_SLOTS") or 0) or batch
    new_tokens = int(os.environ.get("MAX_NEW_TOKENS", "32"))

    params = init_params(config, jax.random.key(0))
    ckpt_dir = os.environ.get("CHECKPOINT_DIR", "")
    if ckpt_dir:
        # serve the TRAINED weights when a checkpoint tree exists
        # (the train pod's orbax-style output); params-only restore
        state, step = restore_checkpoint(ckpt_dir, {"params": params})
        if step is not None:
            params = state["params"]
            print(f"restored checkpoint step {step}", flush=True)

    # WEIGHT_DTYPE=int8 stores the layer matmul weights quantized
    # (models/quantize.py): decode streams half the weight bytes per
    # step — the dominant HBM term at small serving batches
    if os.environ.get("WEIGHT_DTYPE", "native") == "int8":
        from dcos_commons_tpu.models import quantize_params_int8

        params = jax.device_put(quantize_params_int8(params))
        print("weights quantized to int8 (per-channel)", flush=True)

    # TWO compiles cover every request on EITHER path: the paged
    # arena's prefill-chunk + decode-step (page tables, start
    # positions, true lengths, temps, seeds all traced) or the legacy
    # slot pool's prefill-into-slot + decode-step — novel requests
    # never recompile.  KV_DTYPE=int8 halves the cache bytes per
    # decode step: the lever for many resident requests on a full
    # chip (models/decode.py)
    prompt_len = max_len - new_tokens
    kv_dtype = os.environ.get("KV_DTYPE", "native")
    queue_timeout_s = float(os.environ.get("SERVE_QUEUE_TIMEOUT_S", "600"))
    metrics = Metrics()
    stats_path = os.path.join(
        os.environ.get("SANDBOX", "."), SERVESTATS_NAME
    )
    paged = paged_config_from_env(os.environ)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            if self.path.split("?")[0] != "/stats":
                self.send_error(404)
                return
            payload = json.dumps(engine.stats()).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_POST(self):
            if self.path == "/migrate":
                self._do_migrate()
                return
            if self.path != "/generate":
                self.send_error(404)
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(length))
                if "collect" in body:
                    # the migration follow-up (router/core.py): the
                    # session moved HERE mid-generation and the
                    # router collects the finished reply by dest rid
                    result = [engine.collect(int(body["collect"]))]
                    payload = json.dumps({"tokens": result}).encode()
                    self.send_response(200)
                    self._finish(payload)
                    return
                rows = body["tokens"]
                if len(rows) > batch:
                    raise ValueError(
                        f"{len(rows)} prompts > server batch {batch}; "
                        "split the request"
                    )
                # rows may have MIXED lengths (per-row true_len); an
                # over-length prompt is refused, never silently
                # continued as a DIFFERENT (truncated) prompt
                if not rows:
                    raise ValueError("tokens must be non-empty")
                for row in rows:
                    if len(row) < 1:
                        raise ValueError("prompts must be non-empty")
                    if len(row) > prompt_len:
                        raise ValueError(
                            f"prompt length {len(row)} exceeds the "
                            f"server's context {prompt_len}"
                        )
                temp = float(body.get("temperature", 0.0))
                if not math.isfinite(temp) or temp < 0.0:
                    # json.loads accepts NaN/Infinity: a NaN must not
                    # reach the chip, where it poisons sampling
                    raise ValueError(
                        f"temperature must be finite and >= 0, got {temp}"
                    )
                n = int(body.get("max_new_tokens", new_tokens))
                if n < 1:
                    raise ValueError(
                        f"max_new_tokens must be >= 1, got {n}"
                    )
                n = min(n, new_tokens)
                eos = body.get("eos")
                if eos is not None:
                    eos = int(eos)
                    if not 0 <= eos < config.vocab:
                        raise ValueError(
                            f"eos must be in [0, {config.vocab}), got {eos}"
                        )
                clean_rows = [
                    [int(t) % config.vocab for t in row] for row in rows
                ]
                result = engine.submit(
                    clean_rows, n, temperature=temp, eos_id=eos
                )
                payload = json.dumps({"tokens": result}).encode()
                self.send_response(200)
            except SessionMigratedError as e:
                # a redirect, not a failure: the session finished on
                # another pod — 409 names it and the router follows
                # with a collect request (router/frontdoor.py)
                payload = json.dumps({
                    "error": str(e),
                    "rid": e.rid,
                    "migrated_to": e.moved_to,
                    "dest_rid": e.dest_rid,
                }).encode()
                self.send_response(409)
            except QueueTimeoutError as e:
                # saturation, NOT caller error: the request never got
                # a KV slot in time — clients/load generators back off
                payload = json.dumps({"error": str(e)}).encode()
                self.send_response(503)
            except Exception as e:  # noqa: BLE001 — surface to client
                payload = json.dumps({"error": str(e)}).encode()
                self.send_response(400)
            self._finish(payload)

        def _finish(self, payload: bytes) -> None:
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def _do_migrate(self) -> None:
            """The DCN lane's HTTP leg: this pod as a migration
            DESTINATION (serve/migration.py HttpEngineClient drives
            it verb by verb).  409 = the engine refused (budget,
            geometry, unknown rid) — the source aborts cleanly and
            resumes; 400 = malformed request."""
            length = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(length))
                verb = body.get("verb")
                if paged is None:
                    raise MigrationError(
                        "slot-pool pods cannot host migrations "
                        "(KV_PAGE_TOKENS=0)"
                    )
                if verb == "splice":
                    snap = SessionSnapshot.from_wire(body["snapshot"])
                    dest_rid = engine.splice(snap)
                    payload = json.dumps(
                        {"dest_rid": dest_rid}
                    ).encode()
                elif verb == "activate":
                    engine.activate(int(body["rid"]))
                    payload = json.dumps({"ok": True}).encode()
                elif verb == "abort":
                    engine.abort_splice(int(body["rid"]))
                    payload = json.dumps({"ok": True}).encode()
                elif verb == "drain":
                    # source-side one-shot: move every live session
                    # to the named peers (drain-with-migration — the
                    # front door's /drain?to= and the scale-in plan
                    # both drive this instead of waiting generations
                    # out).  Sessions that cannot move are reported
                    # ok=false and finish here under the legacy drain.
                    dests = {
                        str(peer): HttpEngineClient(str(peer),
                                                    str(addr))
                        for peer, addr in dict(
                            body.get("dests") or {}
                        ).items()
                    }
                    report = drain_sessions(
                        engine, dests,
                        log=lambda msg: print(msg, flush=True),
                    )
                    payload = json.dumps({"report": report}).encode()
                else:
                    raise ValueError(f"unknown migrate verb {verb!r}")
                self.send_response(200)
            except MigrationError as e:
                payload = json.dumps({"error": str(e)}).encode()
                self.send_response(409)
            except Exception as e:  # noqa: BLE001 — surface to client
                payload = json.dumps({"error": str(e)}).encode()
                self.send_response(400)
            self._finish(payload)

    # a RELAUNCH reuses the sandbox: a stale ready file from the
    # previous incarnation must not pass readiness while we are cold
    try:
        os.remove("ready")
    except OSError:
        pass
    # bind BEFORE building the engine: the port actually bound is
    # annotated into the engine's very first stats snapshot, which is
    # what /v1/endpoints advertises for `advertise: true` ports — and
    # a hard bind failure still fails readiness, not the first client
    port = int(os.environ.get("PORT_HTTP", "0"))
    try:
        server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    except OSError:
        # the scheduler-assigned port is taken on this machine (a
        # simulated fleet runs many "hosts" on one box): bind an
        # ephemeral port and ADVERTISE it instead of crash-looping
        server = ThreadingHTTPServer(("0.0.0.0", 0), Handler)
        print(
            f"port {port} in use; bound {server.server_address[1]} "
            "instead (advertised via servestats)",
            flush=True,
        )
    bound_port = int(server.server_address[1])

    if paged is not None:
        # the paged arena (ISSUE 11): page-budgeted admission,
        # chunked prefill, prefix caching — the serving default.
        # SERVE_ROLE (ISSUE 16) declares this pod's place in a
        # disaggregated topology; a prefill pod with SERVE_DECODE_PODS
        # peers hands finished prompts to the decode pool over the
        # /migrate lane, and degrades to unified when it cannot.
        role = (os.environ.get("SERVE_ROLE") or "").strip() or "unified"
        handoff = None
        if role == "prefill":
            decode_pods = {}
            for item in os.environ.get("SERVE_DECODE_PODS",
                                       "").split(","):
                if "=" not in item:
                    continue
                peer, addr = item.split("=", 1)
                peer, addr = peer.strip(), addr.strip()
                if peer and addr:
                    decode_pods[peer] = HttpEngineClient(peer, addr)
            if decode_pods:
                handoff = PrefillHandoff(
                    lambda: decode_pods,
                    log=lambda msg: print(msg, flush=True),
                )
        pool = PagedPoolModel(
            config, params, slots, max_len, paged.page_tokens,
            paged.pages, paged.chunk_tokens, kv_dtype=kv_dtype,
        )
        engine = PagedEngine(
            pool.prefill_chunk, pool.decode, slots, max_len,
            prompt_len,
            page_tokens=paged.page_tokens, pages=paged.pages,
            chunk_tokens=paged.chunk_tokens,
            prefix_cache=paged.prefix_cache,
            queue_timeout_s=queue_timeout_s, stats_path=stats_path,
            role=role, read_page=pool.export_page,
            write_page=pool.import_page, handoff=handoff,
            log=lambda msg: print(msg, flush=True),
            extra_stats={"http_port": bound_port},
        )
    else:
        # KV_PAGE_TOKENS=0: the PR 6 slot pool, kept as the
        # operator's escape hatch and the bench baseline
        pool = PoolModel(
            config, params, slots, max_len, kv_dtype=kv_dtype
        )
        engine = SlotEngine(
            pool.prefill, pool.decode, slots, max_len, prompt_len,
            queue_timeout_s=queue_timeout_s, stats_path=stats_path,
            log=lambda msg: print(msg, flush=True),
            extra_stats={"http_port": bound_port},
        )
    engine.register_metrics(metrics)
    if paged is not None:
        pool.warm()
        shape = (
            f"paged KV: {paged.pages} pages x {paged.page_tokens} "
            f"tokens, {slots} rows, chunk {paged.chunk_tokens}, "
            f"prefix cache {'on' if paged.prefix_cache else 'off'}"
        )
    else:
        pool.warm(prompt_len)
        shape = f"slot pool: {slots} slots x {max_len}"
    with open("ready", "w") as f:
        f.write("warm\n")
    print(
        f"warm: continuous batching ({shape}) "
        f"(prompts<={prompt_len}, <={new_tokens} new) on "
        f"{server.server_address[1]}",
        flush=True,
    )
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
