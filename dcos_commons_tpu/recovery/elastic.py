"""Elastic re-slicing: the gang that shrinks instead of waiting.

The reference SDK only ever recovers 1:1 — PERMANENT recovery
re-places the same footprint it lost (SURVEY section 7 stage 8).  A
TPU fleet loses capacity it cannot get back (preemption) or loses it
for a bounded window (maintenance), and a DP-sharded trainer can keep
making progress on a smaller mesh: params and optimizer state are
replicated over the ``dp`` axis, so restoring the newest fenced
checkpoint onto fewer hosts is a pure re-layout — same leaves, new
sharding (parallel/mesh.py ``elastic_reshard_ok`` is the worker-side
contract check).

Two pieces live here:

* :func:`decide_resize` — the PURE decision rule (plancheck-style
  verifiable, property-testable): when a full-size sub-slice cannot
  place, shrink only if (a) the pod opted in (``tpu: elastic:``),
  (b) enough placement attempts failed that "transient fragmentation"
  is off the table, (c) no maintenance window promises the capacity
  back, and (d) a clean smaller size exists — a DIVISOR of the full
  gang (so the global batch reshards evenly over the new ``dp``) at
  or above ``min_hosts``.
* :class:`ElasticGangStep` — the recovery plan's replace step: a
  DeploymentStep whose requirement starts at full size and re-scopes
  itself (smaller pod copy, scaled topology) when the rule says
  shrink.  Every re-scope is journaled; the step stays operator-
  interruptible like any other.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Callable, List, Optional

from dcos_commons_tpu.plan.backoff import Backoff
from dcos_commons_tpu.plan.step import (
    DeploymentStep,
    PodInstanceRequirement,
    RecoveryType,
)
from dcos_commons_tpu.specification.specs import PodSpec, task_full_name


@dataclass(frozen=True)
class ElasticPolicy:
    """Per-pod elastic-resize policy (from the ``tpu:`` YAML block)."""

    enabled: bool = False
    # never shrink below this many hosts (the operator's floor: a
    # 2-host trainer may be pointless for the workload)
    min_hosts: int = 1
    # full-size placement attempts (declined offer cycles) before the
    # rule considers the capacity really gone rather than fragmented
    shrink_after_declines: int = 3


@dataclass(frozen=True)
class ResizeDecision:
    target_hosts: int
    reason: str


def shrink_candidates(full_hosts: int, min_hosts: int) -> List[int]:
    """Descending proper divisors of ``full_hosts`` at or above
    ``min_hosts`` — the only sizes a DP-sharded trainer reshards onto
    cleanly (the global batch and the checkpoint's dp axis must
    divide)."""
    floor = max(1, int(min_hosts))
    return [
        k for k in range(full_hosts - 1, floor - 1, -1)
        if full_hosts % k == 0
    ]


def slice_shrink_candidates(
    full_hosts: int, min_hosts: int, host_quantum: int
) -> List[int]:
    """Descending whole-slice widths for a multi-slice gang: multiples
    of ``host_quantum`` (= hosts per slice) below ``full_hosts`` at or
    above ``min_hosts``.  Dropping whole slices shrinks ONLY the dcn
    axis — each surviving slice keeps its full ``topology`` rectangle,
    so the per-slice ICI layout (and every non-batch mesh axis) is
    untouched and the restore is a pure re-layout regardless of
    divisibility (``elastic_reshard_ok`` permits any dcn width)."""
    floor = max(1, int(min_hosts))
    q = max(1, int(host_quantum))
    return [
        k for k in range(full_hosts - q, 0, -q)
        if k >= floor
    ]


def decide_resize(
    current_hosts: int,
    full_hosts: int,
    declines: int,
    policy: ElasticPolicy,
    maintenance_returning: bool,
    host_quantum: int = 1,
) -> ResizeDecision:
    """The shrink-vs-wait rule.  PURE — no clocks, no inventory: the
    caller feeds observed facts, the rule returns the target size.

    ``maintenance_returning`` is True when any drained host has a
    FINITE maintenance window (the capacity comes back): waiting for
    the window beats training at half width and paying a second
    restart when it ends.  Preempted capacity never returns by
    contract, so a pure-preemption loss shrinks as soon as the
    decline budget is spent.

    ``host_quantum`` > 1 is the multi-slice gang case (quantum =
    hosts per slice): valid widths drop WHOLE slices — the dcn axis
    shrinks, each surviving slice keeps its topology — instead of the
    single-slice divisor rule.
    """
    if not policy.enabled:
        return ResizeDecision(current_hosts, "elastic disabled")
    if declines < max(1, policy.shrink_after_declines):
        return ResizeDecision(
            current_hosts,
            f"waiting: {declines}/{policy.shrink_after_declines} "
            "placement attempts",
        )
    if maintenance_returning:
        return ResizeDecision(
            current_hosts,
            "waiting: a maintenance window promises the capacity back",
        )
    # sizes strictly below the current target the checkpoint reshards
    # onto cleanly: divisors of the FULL gang (dp axis must divide) —
    # or whole-slice multiples when the gang spans slices (dcn axis)
    if host_quantum > 1:
        candidates = slice_shrink_candidates(
            full_hosts, policy.min_hosts, host_quantum
        )
    else:
        candidates = shrink_candidates(full_hosts, policy.min_hosts)
    for k in candidates:
        if k < current_hosts:
            kind = "slice(s)" if host_quantum > 1 else "hosts"
            width = k // host_quantum if host_quantum > 1 else k
            cur = (
                current_hosts // host_quantum
                if host_quantum > 1 else current_hosts
            )
            return ResizeDecision(
                k, f"shrinking {cur} -> {width} {kind}"
            )
    return ResizeDecision(
        current_hosts,
        f"no clean size between {policy.min_hosts} and "
        f"{current_hosts - 1} hosts",
    )


def shrink_topology(tpu, target_hosts: int) -> Optional[str]:
    """Scale a declared torus topology down to ``target_hosts`` hosts
    by halving dimensions largest-first; None when no clean rectangle
    exists (the caller must not shrink).  The result keeps every
    dimension a positive integer and the chip total exactly
    ``target_hosts * chips_per_host`` — what ``find_subslice`` needs
    to tile the smaller gang contiguously."""
    dims = list(tpu.topology_dims())
    if not dims:
        return ""
    want = target_hosts * tpu.chips_per_host
    have = 1
    for d in dims:
        have *= d
    while have > want:
        dims.sort(reverse=True)
        if have % 2 or dims[0] % 2:
            return None
        dims[0] //= 2
        have //= 2
    if have != want:
        return None
    return "x".join(str(d) for d in sorted(dims, reverse=True))


def shrunken_pod(pod: PodSpec, target_hosts: int) -> Optional[PodSpec]:
    """A copy of a gang pod scoped to ``target_hosts`` instances with
    a proportionally scaled topology; None when the topology cannot
    scale to that size.  The copy rides ONLY the recovery
    requirement — the service spec keeps the full-size pod, so a
    later `pod replace` (or a scheduler restart's update plan)
    restores full width when capacity returns."""
    if target_hosts >= pod.count:
        return pod
    if pod.tpu is None:
        return dataclasses.replace(pod, count=target_hosts)
    if pod.tpu.slices > 1:
        # multi-slice gangs shrink by WHOLE slices (ISSUE 20): the
        # per-slice topology is untouched — only `slices` (the dcn
        # axis) drops — so count must stay a multiple of
        # hosts-per-slice or the requirement could never satisfy
        # count == slices x hosts-per-slice
        hps = max(1, pod.count // pod.tpu.slices)
        if target_hosts % hps or target_hosts < hps:
            return None
        tpu = dataclasses.replace(pod.tpu, slices=target_hosts // hps)
        return dataclasses.replace(pod, count=target_hosts, tpu=tpu)
    topo = shrink_topology(pod.tpu, target_hosts)
    if topo is None:
        return None
    tpu = dataclasses.replace(pod.tpu, topology=topo)
    return dataclasses.replace(pod, count=target_hosts, tpu=tpu)


class ElasticGangStep(DeploymentStep):
    """The gang recovery plan's replace step.

    Starts as a PERMANENT whole-gang requirement.  Each declined offer
    cycle feeds :func:`decide_resize`; when the rule says shrink, the
    requirement is re-scoped in place to a smaller pod copy (count +
    topology scaled) and the next evaluation places the narrower gang.
    ``target_hosts`` is read by the trailing trim step to erase the
    surplus instances' state so recovery does not chase ghosts.

    ``maintenance_probe`` is a callable returning True while any
    drained host has a finite maintenance window (recovery manager
    closes it over the shared inventory)."""

    def __init__(
        self,
        name: str,
        pod: PodSpec,
        tasks: Optional[List[str]],
        backoff: Optional[Backoff],
        policy: ElasticPolicy,
        maintenance_probe: Optional[Callable[[], bool]] = None,
        journal=None,
    ):
        self._full_pod = pod
        self._tasks = list(tasks) if tasks is not None else None
        self._policy = policy
        self._maintenance_probe = maintenance_probe or (lambda: False)
        self.journal = journal
        self.target_hosts = pod.count
        self._declines = 0
        super().__init__(
            name,
            PodInstanceRequirement(
                pod=pod,
                instances=list(range(pod.count)),
                recovery_type=RecoveryType.PERMANENT,
                tasks_to_launch=list(self._tasks or []),
            ),
            backoff=backoff,
        )

    def update_offer_status(self, launched: bool) -> None:
        with self._lock:
            if launched:
                self._declines = 0
                return
            self._declines += 1
            # a multi-slice gang resizes in whole-slice steps: the
            # quantum pins valid widths to multiples of hosts-per-slice
            quantum = 1
            tpu = self._full_pod.tpu
            if tpu is not None and tpu.slices > 1:
                quantum = max(1, self._full_pod.count // tpu.slices)
            decision = decide_resize(
                self.target_hosts,
                self._full_pod.count,
                self._declines,
                self._policy,
                self._maintenance_probe(),
                host_quantum=quantum,
            )
            if decision.target_hosts >= self.target_hosts:
                return
            pod = shrunken_pod(self._full_pod, decision.target_hosts)
            if pod is None:
                return  # topology cannot scale to that size: keep waiting
            self._rescope_locked(pod, decision)

    def _rescope_locked(self, pod: PodSpec, decision: ResizeDecision) -> None:
        self.target_hosts = pod.count
        self._declines = 0
        self.requirement = PodInstanceRequirement(
            pod=pod,
            instances=list(range(pod.count)),
            recovery_type=RecoveryType.PERMANENT,
            tasks_to_launch=list(self._tasks or []),
        )
        # the status-routing map must match the new scope, or a
        # surplus instance's stale status could move this step
        self._spec_by_full = {
            task_full_name(pod.type, i, spec.name): spec
            for i in self.requirement.instances
            for spec in pod.tasks
            if spec.name in self.requirement.tasks_to_launch
        }
        self._expected = {}
        self._task_states = {}
        self._task_ready = {}
        if self.journal is not None:
            self.journal.append(
                "recovery",
                pod=pod.type,
                verb="elastic-shrink",
                hosts=pod.count,
                full=self._full_pod.count,
                topology=pod.tpu.topology if pod.tpu else "",
                slices=pod.tpu.slices if pod.tpu else 1,
                message=(
                    f"elastic re-slice: {decision.reason} "
                    f"(topology {pod.tpu.topology if pod.tpu else 'n/a'}"
                    + (
                        f" x {pod.tpu.slices} slice(s)"
                        if pod.tpu and pod.tpu.slices > 1 else ""
                    )
                    + ")"
                ),
            )

    def surplus_instances(self) -> List[int]:
        """Instances of the FULL gang the current scope dropped — the
        trim step erases their task state after the narrow gang is
        running."""
        with self._lock:
            return list(range(self.target_hosts, self._full_pod.count))
