"""frameworks/jax serving pod end to end: deploy -> warm -> generate.

A REAL serve_worker process deploys through the control plane; the
readiness check ("test -f ready") gates the deploy plan on the model
being warm, the VIP surfaces the backend, and POST /generate answers
with deterministic greedy continuations.  Train AND serve run through
one scheduler — the reference's model has no data plane at all
(SURVEY: "the workloads are whatever the service YAML launches").
"""

import json
import os
import time
import urllib.error
import urllib.request

from dcos_commons_tpu.agent import LocalProcessAgent
from dcos_commons_tpu.offer.inventory import TpuHost
from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
from dcos_commons_tpu.specification import from_yaml_file
from dcos_commons_tpu.storage import MemPersister

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_ENV = {
    "FRAMEWORK_NAME": "tiny-serve",
    "JAX_FRAMEWORK_DIR": os.path.join(REPO, "frameworks", "jax"),
    "VOCAB": "64",
    "D_MODEL": "32",
    "N_LAYERS": "2",
    "SEQ_LEN": "64",
    "MAX_LEN": "48",
    "MAX_NEW_TOKENS": "8",
    # exactness assertions below need batch 1 (the overflow 400) and
    # the exact cache; int8/batched serving has its own coverage
    "SERVE_BATCH": "1",
    "KV_DTYPE": "native",
}


def test_inference_pod_serves_generate(tmp_path):
    spec = from_yaml_file(
        os.path.join(REPO, "frameworks", "jax", "svc_serve.yml"), TINY_ENV
    )
    builder = SchedulerBuilder(
        spec,
        SchedulerConfig(
            sandbox_root=str(tmp_path / "sbx"), backoff_enabled=False
        ),
        MemPersister(),
    )
    from dcos_commons_tpu.offer.inventory import SliceInventory

    builder.set_inventory(SliceInventory([TpuHost(
        host_id="h0", hostname="127.0.0.1", generation="v5e",
        grid=(0, 0), chip_block=(1, 1), cpus=8.0, memory_mb=16384,
        # a high range other dev-box services are unlikely to hold
        # (port 10000 is taken on the CI host)
        ports=((23100, 23200),),
    )]))
    agent = LocalProcessAgent(str(tmp_path / "sbx"))
    builder.set_agent(agent)
    scheduler = builder.build()
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            scheduler.run_cycle()
            if scheduler.deploy_manager.get_plan().is_complete:
                break
            time.sleep(0.2)
        # readiness ("test -f ready") gates this: COMPLETE means WARM
        assert scheduler.deploy_manager.get_plan().is_complete, (
            open(tmp_path / "sbx" / "server-0-api" / "stderr").read()[-500:]
            if (tmp_path / "sbx" / "server-0-api" / "stderr").exists()
            else "no stderr"
        )
        info = scheduler.state_store.fetch_task("server-0-api")
        port = int(info.env["PORT_HTTP"])

        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=60) as resp:
                return json.loads(resp.read())

        out = post({"tokens": [[1, 2, 3, 4]], "max_new_tokens": 8})
        assert len(out["tokens"]) == 1
        assert len(out["tokens"][0]) == 8
        assert all(0 <= t < 64 for t in out["tokens"][0])
        # the SERVED continuation equals direct generate() on the
        # EXACT prompt — the right-pad + true_len path changes nothing
        import jax
        import jax.numpy as jnp

        from dcos_commons_tpu.models import (
            TransformerConfig,
            generate,
            init_params,
        )

        cfg = TransformerConfig(
            vocab=64, d_model=32, n_layers=2, n_heads=8, n_kv_heads=8,
            d_ff=1408, max_seq=64, dtype=jnp.float32, remat=False,
        )
        oracle = generate(
            cfg, init_params(cfg, jax.random.key(0)),
            jnp.asarray([[1, 2, 3, 4]], jnp.int32), max_new_tokens=8,
        )
        assert out["tokens"][0] == [int(t) for t in oracle[0]]
        # greedy is deterministic: same prompt, same continuation
        again = post({"tokens": [[1, 2, 3, 4]], "max_new_tokens": 8})
        assert again["tokens"] == out["tokens"]
        # a different prompt (almost surely) diverges
        other = post({"tokens": [[9, 8, 7, 6, 5]], "max_new_tokens": 8})
        assert len(other["tokens"][0]) == 8
        # malformed requests get clean 400s, never silent truncation:
        # batch overflow, over-length prompt, empty prompt
        for bad in (
            {"tokens": [[1], [2]]},                 # > server batch
            {"tokens": [list(range(41))]},          # > context (40)
            {"tokens": [[]]},                       # empty prompt
            # json.dumps emits bare NaN and the server's json.loads
            # accepts it: a NaN group key would stall the batcher
            {"tokens": [[1, 2]], "temperature": float("nan")},
            {"tokens": [[1, 2]], "temperature": float("inf")},
            {"tokens": [[1, 2]], "temperature": -1.0},
        ):
            try:
                post(bad)
                raise AssertionError(f"should have failed: {bad}")
            except urllib.error.HTTPError as e:
                assert e.code == 400, bad
        # VIP discovery lists the live backend
        from dcos_commons_tpu.http.api import SchedulerApi

        code, body = SchedulerApi(scheduler).get_endpoint("vip:inference")
        assert code == 200
        assert any(str(port) in addr for addr in body["address"])
    finally:
        agent.shutdown()


def test_continuous_batching_merges_concurrent_clients(tmp_path):
    """SERVE_BATCH > 1: concurrent single-prompt clients — of MIXED
    prompt lengths — share the slot pool (each rides its own slot,
    admitted mid-flight; per-row true_len/temperature/seed) with each
    client's own correct greedy continuation — concurrency must not
    change any answer.

    Runs the FULL serving quantization stack (int8 weights + int8 KV,
    models/quantize.py): every assertion here is served-vs-served
    self-consistency, so the quantized pod must hold them all."""
    import threading

    env = {
        **TINY_ENV, "SERVE_BATCH": "4",
        "WEIGHT_DTYPE": "int8", "KV_DTYPE": "int8",
    }
    spec = from_yaml_file(
        os.path.join(REPO, "frameworks", "jax", "svc_serve.yml"), env
    )
    builder = SchedulerBuilder(
        spec,
        SchedulerConfig(
            sandbox_root=str(tmp_path / "sbx"), backoff_enabled=False
        ),
        MemPersister(),
    )
    from dcos_commons_tpu.offer.inventory import SliceInventory

    builder.set_inventory(SliceInventory([TpuHost(
        host_id="h0", hostname="127.0.0.1", generation="v5e",
        grid=(0, 0), chip_block=(1, 1), cpus=8.0, memory_mb=16384,
        ports=((23100, 23200),),
    )]))
    agent = LocalProcessAgent(str(tmp_path / "sbx"))
    builder.set_agent(agent)
    scheduler = builder.build()
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            scheduler.run_cycle()
            if scheduler.deploy_manager.get_plan().is_complete:
                break
            time.sleep(0.2)
        assert scheduler.deploy_manager.get_plan().is_complete
        info = scheduler.state_store.fetch_task("server-0-api")
        port = int(info.env["PORT_HTTP"])

        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=60) as resp:
                return json.loads(resp.read())

        # sequential oracle answers, one per distinct prompt —
        # DELIBERATELY mixed lengths: heterogeneous clients must merge
        prompts = [[1, 2, 3], [4, 5], [7, 8, 9, 6, 2], [3]]
        expected = [
            post({"tokens": [p], "max_new_tokens": 6})["tokens"][0]
            for p in prompts
        ]
        # now the same four prompts CONCURRENTLY: same answers
        results = [None] * len(prompts)
        errors = []

        def client(i):
            try:
                results[i] = post(
                    {"tokens": [prompts[i]], "max_new_tokens": 6}
                )["tokens"][0]
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(len(prompts))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert results == expected
        # ONE multi-row MIXED-length request pins the per-row lens
        # path deterministically (concurrent merging above depends on
        # thread timing)
        mixed = post({
            "tokens": [prompts[0], prompts[1]], "max_new_tokens": 6,
        })
        assert mixed["tokens"] == [expected[0], expected[1]]
        # the worker's log shows concurrent rows sharing the pool
        stdout_path = tmp_path / "sbx" / "server-0-api" / "stdout"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if "continuous-batch:" in stdout_path.read_text():
                break
            time.sleep(0.2)
        assert "continuous-batch:" in stdout_path.read_text(), (
            "concurrent clients never shared a pool decode step"
        )
        # the serving gauges are live: /stats on the worker reports
        # the pool shape and the tokens the run produced
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/stats", method="GET"
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            stats = json.loads(resp.read())
        assert stats["slots"] == 4
        assert stats["requests_completed"] >= 7
        assert stats["tokens_out"] >= 7 * 6
        assert 0.0 <= stats["kv_occupancy"] <= 1.0
        # and the SCHEDULER sees them: the worker mirrors the gauges
        # to its sandbox, the agent surfaces the file, and
        # /v1/debug/serving merges per task
        from dcos_commons_tpu.http.api import SchedulerApi

        def scheduler_sees():
            code, body = SchedulerApi(scheduler).debug_serving()
            assert code == 200
            return body["serving"].get("server-0-api")

        deadline = time.monotonic() + 15
        merged = scheduler_sees()
        while (not merged or merged.get("requests_completed", 0) < 7) \
                and time.monotonic() < deadline:
            time.sleep(0.5)  # the worker rewrites servestats ~1/s
            merged = scheduler_sees()
        assert merged and merged["slots"] == 4
        assert merged["requests_completed"] >= 7
    finally:
        agent.shutdown()


def _load_serve_worker_module():
    """Import serve_worker WITHOUT running main() (no jax needed:
    model imports live inside main)."""
    import importlib.util

    path = os.path.join(REPO, "frameworks", "jax", "serve_worker.py")
    spec = importlib.util.spec_from_file_location("serve_worker_ut", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_microbatcher_head_always_dispatches():
    """A head whose group key never equals itself (NaN temperature)
    must still dispatch — grouping by key equality alone would starve
    it AND every request queued behind it until the queue timeout
    (advisor r4).  The handler rejects NaN, so this guards the batcher
    itself against any future non-self-equal key."""
    import threading

    sw = _load_serve_worker_module()
    groups = []

    def run_group(items):
        groups.append(items)
        for item in items:
            item.result = [[0] * item.n for _ in item.rows]

    batcher = sw._MicroBatcher(
        run_group, capacity=4, window_s=0.0, queue_timeout_s=5.0
    )
    poison = sw._WorkItem([[1, 2]], 4, float("nan"))
    normal = sw._WorkItem([[3, 4]], 4, 0.0)
    threads = [
        threading.Thread(target=batcher.submit, args=(item,))
        for item in (poison, normal)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert poison.done.is_set(), "NaN-keyed head never dispatched"
    assert normal.done.is_set(), "request behind the NaN head starved"
    # the NaN item formed its own group; it never merged with normal
    assert all(
        len({id(i) for i in g} & {id(poison), id(normal)}) <= 1
        or len(g) == 1
        for g in groups
    )


def test_microbatcher_queue_timeout_configurable():
    """SERVE_QUEUE_TIMEOUT_S plumbs through: a submit against a
    wedged run_group raises after the configured timeout, not 600s."""
    import threading

    sw = _load_serve_worker_module()
    wedge = threading.Event()

    def run_group(items):
        wedge.wait(30)  # simulate a wedged generate

    batcher = sw._MicroBatcher(
        run_group, capacity=2, window_s=0.0, queue_timeout_s=0.3
    )
    item = sw._WorkItem([[1]], 2, 0.0)
    t0 = time.monotonic()
    try:
        batcher.submit(item)
        raise AssertionError("submit should have timed out")
    except RuntimeError as e:
        assert "timed out" in str(e)
    assert time.monotonic() - t0 < 5.0
    wedge.set()


def test_microbatcher_fifo_and_idle_callback():
    """Shared-batcher liveness (advisor r5): a temp-mismatched head
    keeps its queue position and dispatches next (no back-requeue
    starvation), and on_idle fires between requests without stealing
    work — the gang server's followers depend on both."""
    import threading
    import time as _time

    from dcos_commons_tpu.utils.microbatch import MicroBatcher, WorkItem

    served_groups = []
    idle_calls = []

    def run_group(items):
        served_groups.append([item.temp for item in items])
        for item in items:
            item.result = [[0] * item.n for _ in item.rows]

    batcher = MicroBatcher(
        run_group, capacity=4, window_s=0.0, queue_timeout_s=5.0,
        on_idle=lambda: idle_calls.append(1), idle_every_s=0.01,
    )
    deadline = _time.monotonic() + 5
    while not idle_calls and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert idle_calls, "on_idle never fired while the queue was idle"
    # an odd-temperature item arriving FIRST is served before a stream
    # of mergeable peers that arrive behind it
    odd = WorkItem([[1]], 2, 0.7)
    peers = [WorkItem([[2]], 2, 0.0) for _ in range(4)]
    threads = [
        threading.Thread(target=batcher.submit, args=(item,))
        for item in [odd] + peers
    ]
    for t in threads:
        t.start()
        _time.sleep(0.005)  # preserve arrival order
    for t in threads:
        t.join(timeout=10)
    assert odd.done.is_set() and odd.error is None
    assert served_groups[0][0] == 0.7, (
        f"head lost its position: {served_groups}"
    )
