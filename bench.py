"""Benchmark: the BASELINE.md headline on real TPU hardware.

Phase 1 — BASELINE.json configs through the real control plane with a
real process-launching agent:
  #1 frameworks/helloworld simple.yml single-pod deploy
  #2 frameworks/helloworld max_per_host.yml (constraint respected)
  #3 frameworks/jax svc_mnist.yml — a REAL JAX training subprocess on
     the TPU; install -> plan COMPLETE wall-clock is the headline.
The reference publishes no numbers (BASELINE.md), so vs_baseline is
measured against the 60 s target budget recorded there (>1.0 = faster
than budget).

Phase 2 (extras) — flagship transformer train-step throughput on the
chip (tokens/s + model FLOPs utilisation), the forward-looking perf
number the multi-host pod scales from.

Prints exactly ONE JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

DEPLOY_BUDGET_S = 60.0


def _run_deploy(yaml_path: str, env: dict, hosts, budget_s: float = 600.0):
    """Deploy one service YAML through the full control plane with a
    real process-launching agent; returns (elapsed, completed,
    scheduler, agent, workdir)."""
    import tempfile

    from dcos_commons_tpu.agent import LocalProcessAgent
    from dcos_commons_tpu.offer.inventory import SliceInventory
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.storage import FileWalPersister

    workdir = tempfile.mkdtemp(prefix="bench-")
    from dcos_commons_tpu.specification import from_yaml_file

    spec = from_yaml_file(yaml_path, env)
    builder = SchedulerBuilder(
        spec,
        SchedulerConfig(
            sandbox_root=os.path.join(workdir, "sandboxes"),
            backoff_enabled=False,
        ),
        FileWalPersister(os.path.join(workdir, "state"), fsync=False),
    )
    builder.set_inventory(SliceInventory(list(hosts)))
    agent = LocalProcessAgent(os.path.join(workdir, "sandboxes"))
    builder.set_agent(agent)
    scheduler = builder.build()

    t0 = time.monotonic()
    deadline = t0 + budget_s
    completed = False
    while time.monotonic() < deadline:
        scheduler.run_cycle()
        if scheduler.deploy_manager.get_plan().is_complete:
            completed = True
            break
        time.sleep(0.1)
    elapsed = time.monotonic() - t0
    return elapsed, completed, scheduler, agent, workdir


def _cpu_hosts(n: int):
    from dcos_commons_tpu.offer.inventory import TpuHost

    return [
        TpuHost(host_id=f"host-{i}", cpus=8.0, memory_mb=16384)
        for i in range(n)
    ]


def bench_helloworld() -> dict:
    """BASELINE configs #1 and #2: helloworld CPU deploys through the
    control plane (reference: frameworks/helloworld simple +
    MAX_PER_HOST scenarios)."""
    import shutil

    results = {}
    # config 1: single-pod deploy
    elapsed, completed, scheduler, agent, workdir = _run_deploy(
        os.path.join(REPO, "frameworks/helloworld/simple.yml"),
        {"SLEEP_DURATION": "1000"},
        _cpu_hosts(1),
        budget_s=60.0,
    )
    results["helloworld_simple_deploy_s"] = round(elapsed, 3)
    results["helloworld_simple_completed"] = completed
    agent.shutdown()
    shutil.rmtree(workdir, ignore_errors=True)

    # config 2: 3 instances, max-per-host:1 over 3 hosts
    elapsed, completed, scheduler, agent, workdir = _run_deploy(
        os.path.join(REPO, "frameworks/helloworld/max_per_host.yml"),
        {"SLEEP_DURATION": "1000"},
        _cpu_hosts(3),
        budget_s=60.0,
    )
    placed_hosts = set()
    for info in scheduler.state_store.fetch_tasks():
        placed_hosts.add(info.labels.get("offer_hostname", info.agent_id))
    results["helloworld_max_per_host_deploy_s"] = round(elapsed, 3)
    results["helloworld_max_per_host_completed"] = completed
    results["helloworld_max_per_host_distinct_hosts"] = len(placed_hosts)
    agent.shutdown()
    shutil.rmtree(workdir, ignore_errors=True)
    return results


def bench_deploy() -> dict:
    """Control-plane deploy of the single-chip MNIST service."""
    import shutil

    from dcos_commons_tpu.offer.inventory import TpuHost

    host = TpuHost(
        host_id="tpu-host-0",
        slice_id="bench-slice",
        generation="v5e",
        grid=(0, 0),
        chip_block=(1, 1),
        cpus=8.0,
        memory_mb=32768,
    )
    elapsed, completed, scheduler, agent, workdir = _run_deploy(
        os.path.join(REPO, "frameworks/jax/svc_mnist.yml"),
        {
            "JAX_FRAMEWORK_DIR": os.path.join(REPO, "frameworks/jax"),
            "TRAIN_STEPS": os.environ.get("BENCH_MNIST_STEPS", "30"),
        },
        [host],
    )
    status = scheduler.state_store.fetch_status("mnist-0-train")
    agent.shutdown()
    result = {
        "deploy_wall_clock_s": round(elapsed, 3),
        "deploy_completed": completed,
        "task_state": status.state.value if status else None,
    }
    stdout = os.path.join(workdir, "sandboxes", "mnist-0-train", "stdout")
    if os.path.exists(stdout):
        with open(stdout) as f:
            lines = f.read().strip().splitlines()
        if lines:
            result["task_log_tail"] = lines[-1]
    shutil.rmtree(workdir, ignore_errors=True)
    return result


def bench_transformer() -> dict:
    """Flagship train-step throughput on the attached chip."""
    import jax
    import jax.numpy as jnp
    import optax

    from dcos_commons_tpu.models import TransformerConfig, init_params, make_train_step
    from dcos_commons_tpu.utils import param_count, synthetic_tokens

    # chip-scale flagship (v5e, 16 GB): 872M params fills the MXU;
    # full-layer remat + FA2 backward kernels + 1024/512 attention
    # tiles measured best in the round-2 block sweeps
    config = TransformerConfig(
        vocab=32768,
        d_model=2048,
        n_layers=12,
        n_heads=16,
        n_kv_heads=16,
        d_ff=8192,
        max_seq=2048,
        dtype=jnp.bfloat16,
        remat=True,
        attn_block_q=1024,
        attn_block_k=512,
    )
    batch = int(os.environ.get("BENCH_BATCH", "16"))
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    params = init_params(config, jax.random.key(0))
    optimizer = optax.adamw(3e-4)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(config, optimizer, donate=True)
    tokens, targets = synthetic_tokens(
        jax.random.key(1), batch, config.max_seq, config.vocab
    )
    t0 = time.monotonic()
    params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
    jax.block_until_ready((params, opt_state, loss))
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(steps):
        params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
    # block on the WHOLE output tree: on asynchronous backends waiting
    # only on the scalar loss under-counts the step time.  On the axon
    # relay platform block_until_ready alone returns early, so ALSO
    # force a device->host transfer of a value that depends on the
    # final params (the next step's loss) before stopping the clock.
    jax.block_until_ready((params, opt_state, loss))
    _, _, sync_loss = step_fn(params, opt_state, tokens, targets)
    float(jax.device_get(sync_loss))
    dt = time.monotonic() - t0
    steps += 1  # the sync step is a real timed step too
    tokens_per_s = batch * config.max_seq * steps / dt
    n_params = param_count(params)
    flops_per_token = 6 * n_params  # fwd+bwd dense estimate
    achieved_tflops = tokens_per_s * flops_per_token / 1e12
    device = jax.devices()[0]
    peak_tflops = _peak_bf16_tflops(device)
    return {
        "platform": device.platform,
        "device_kind": getattr(device, "device_kind", "?"),
        "transformer_params_m": round(n_params / 1e6, 1),
        "compile_s": round(compile_s, 2),
        "tokens_per_s": round(tokens_per_s, 1),
        "achieved_tflops": round(achieved_tflops, 2),
        "mfu": round(achieved_tflops / peak_tflops, 4) if peak_tflops else None,
        "final_loss": round(float(loss), 4),
    }


def _peak_bf16_tflops(device) -> float:
    """Per-chip bf16 peak by device kind; 0 disables the MFU extra."""
    kind = getattr(device, "device_kind", "").lower()
    for token, peak in (
        ("v6e", 918.0), ("v6", 918.0), ("v5p", 459.0), ("v5e", 197.0),
        ("v5 lite", 197.0), ("lite", 197.0), ("v4", 275.0),
    ):
        if token in kind:
            return peak
    return 197.0 if device.platform in ("tpu", "axon") else 0.0


def bench_rooflines() -> dict:
    """Chip rooflines + (multi-chip only) ICI collective bandwidth —
    the BASELINE north-star measurement path.  On the single bench
    chip the collective section reports the rooflines the multi-chip
    GB/s numbers will sit under."""
    import jax

    from dcos_commons_tpu.parallel.collectives import (
        collective_bandwidth,
        single_chip_rooflines,
    )

    out = dict(single_chip_rooflines(payload_mb=128.0, iters=10))
    devices = jax.devices()
    if len(devices) >= 2:
        from jax.sharding import Mesh

        mesh = Mesh(devices, ("ici",))
        for key, value in collective_bandwidth(
            mesh, "ici", payload_mb=32.0, iters=10
        ).items():
            out[f"ici_{key}"] = value
    return out


def main() -> None:
    extras = {}
    try:
        extras.update(bench_helloworld())
    except Exception as e:
        extras["helloworld_error"] = repr(e)[:200]
    deploy = bench_deploy()
    extras.update(deploy)
    try:
        extras.update(bench_rooflines())
    except Exception as e:
        extras["roofline_error"] = repr(e)[:200]
    try:
        extras.update(bench_transformer())
    except Exception as e:  # deploy result still stands alone
        extras["transformer_error"] = repr(e)[:200]
    value = deploy["deploy_wall_clock_s"]
    print(
        json.dumps(
            {
                "metric": "jax_mnist_deploy_plan_wall_clock",
                "value": value,
                "unit": "s",
                "vs_baseline": round(DEPLOY_BUDGET_S / max(value, 1e-9), 3)
                if deploy["deploy_completed"]
                else 0.0,
                "extras": extras,
            },
            sort_keys=True,
        )
    )


if __name__ == "__main__":
    main()
