"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding
(dp/tp/sp) is exercised without TPU hardware, mirroring how the
reference tests multi-node scheduling without a Mesos cluster
(reference: sdk/testing/ServiceTestRunner.java runs the full scheduler
against MemPersister + a mocked driver).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
