from dcos_commons_tpu.data.loader import (
    DevicePrefetcher,
    TokenDataset,
    list_shards,
    write_token_shard,
)

__all__ = [
    "DevicePrefetcher",
    "TokenDataset",
    "list_shards",
    "write_token_shard",
]
