"""Query logic behind every /v1 route (transport-free).

Reference: http/queries/*.java — the reference splits Jersey resource
classes (transport) from query logic classes; this module is the query
half, returning ``(http_status, jsonable_body)`` tuples so both the
HTTP server and in-process callers (tests, CLI fallback) share one
implementation.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from dcos_commons_tpu.debug.trackers import serialize_plan
from dcos_commons_tpu.specification.specs import task_full_name
from dcos_commons_tpu.state.state_store import (
    GoalStateOverride,
    OverrideProgress,
)

Response = Tuple[int, Any]


class SchedulerApi:
    def __init__(self, scheduler):
        self._scheduler = scheduler

    def set_scheduler(self, scheduler) -> None:
        """Swap the backing scheduler (live options update rebuilds it
        in-process; the HTTP server and its routes stay up)."""
        self._scheduler = scheduler

    def _nudge(self) -> None:
        """Wake an event-driven scheduler loop after a mutation so the
        verb takes effect at evaluation speed, not heartbeat speed."""
        nudge = getattr(self._scheduler, "nudge", None)
        if callable(nudge):
            nudge()

    def _journal_verb(self, verb: str, **attrs) -> None:
        """Operator verbs land in the durable event journal — flushed
        INLINE (unlike cycle-batched events): the operator's interrupt
        must survive a crash that happens before the next cycle."""
        journal = getattr(self._scheduler, "journal", None)
        if journal is None:
            return
        journal.append("operator", verb=verb, **attrs)
        journal.flush()

    # -- health (reference: http/endpoints/HealthResource.java) -------

    def health(self) -> Response:
        plans = self._scheduler.plans()
        statuses = {name: p.get_status().value for name, p in plans.items()}
        has_errors = any(p.has_errors() for p in plans.values())
        deployed = all(
            p.is_complete for n, p in plans.items()
            if n in ("deploy", "update")
        )
        fatal = getattr(self._scheduler, "fatal_error", None)
        healthy = not has_errors and fatal is None
        body = {
            "healthy": healthy,
            "deployed": deployed,
            "plans": statuses,
        }
        if fatal is not None:
            body["fatal_error"] = fatal
        return (200 if healthy else 503), body

    # -- plans (reference: http/queries/PlansQueries.java:47-231) -----

    def list_plans(self) -> Response:
        return 200, sorted(self._scheduler.plans().keys())

    def get_plan(self, plan_name: str) -> Response:
        plan = self._scheduler.plan(plan_name)
        if plan is None:
            return 404, {"message": f"no plan named {plan_name}"}
        body = serialize_plan(plan)
        # the reference returns 202 while a plan is in progress and 200
        # once complete (PlansQueries.getPlanInfo)
        code = 200 if plan.is_complete else 202
        return code, body

    def _plan_element(
        self, plan_name: str, phase: Optional[str], step: Optional[str]
    ):
        plan = self._scheduler.plan(plan_name)
        if plan is None:
            return None, (404, {"message": f"no plan named {plan_name}"})
        if phase is None:
            return plan, None
        phase_el = plan.phase(phase)
        if phase_el is None:
            return None, (404, {"message": f"no phase {phase}"})
        if step is None:
            return phase_el, None
        for s in phase_el.steps:
            if s.name == step or s.id == step:
                return s, None
        return None, (404, {"message": f"no step {step}"})

    def _plan_verb(
        self,
        plan_name: str,
        phase: Optional[str],
        step: Optional[str],
        verb: str,
    ) -> Response:
        element, error = self._plan_element(plan_name, phase, step)
        if error is not None:
            return error
        getattr(element, verb)()
        self._journal_verb(verb, plan=plan_name, phase=phase, step=step)
        self._nudge()
        return 200, {"message": f"{verb} invoked", "plan": plan_name}

    def plan_interrupt(self, plan_name, phase=None, step=None) -> Response:
        return self._plan_verb(plan_name, phase, step, "interrupt")

    def plan_continue(self, plan_name, phase=None, step=None) -> Response:
        return self._plan_verb(plan_name, phase, step, "proceed")

    def plan_restart(self, plan_name, phase=None, step=None) -> Response:
        return self._plan_verb(plan_name, phase, step, "restart")

    def plan_force_complete(self, plan_name, phase=None, step=None) -> Response:
        return self._plan_verb(plan_name, phase, step, "force_complete")

    def plan_start(self, plan_name, env=None) -> Response:
        """Reference: PlansQueries.start (PlansQueries.java:47-231) —
        restart + proceed, with an optional ``{"env": {...}}`` body
        merged into every task the plan launches (what makes sidecar
        plans like backup/restore operable: snapshot name, target
        location)."""
        element, error = self._plan_element(plan_name, None, None)
        if error is not None:
            return error
        if env:
            if not isinstance(env, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in env.items()
            ):
                return 400, {"message": "env must be a {str: str} object"}
            setter = getattr(element, "set_env_overrides", None)
            if setter is None:
                return 409, {
                    "message": f"plan {plan_name} cannot take env overrides"
                }
            setter(env)
        element.restart()
        element.proceed()
        self._journal_verb("start", plan=plan_name)
        self._nudge()
        return 200, {
            "message": "started", "plan": plan_name,
            "env": sorted(env) if env else [],
        }

    def plan_stop(self, plan_name) -> Response:
        """Reference: PlansQueries.stop — interrupt + restart."""
        element, error = self._plan_element(plan_name, None, None)
        if error is not None:
            return error
        element.interrupt()
        element.restart()
        self._journal_verb("stop", plan=plan_name)
        self._nudge()
        return 200, {"message": "stopped", "plan": plan_name}

    # -- pods (reference: http/queries/PodQueries.java:69-263) --------

    def list_pods(self) -> Response:
        names = []
        for pod in self._scheduler.spec.pods:
            for i in range(pod.count):
                names.append(f"{pod.type}-{i}")
        return 200, names

    def pod_statuses(self) -> Response:
        statuses = self._scheduler.state_store.fetch_statuses()
        body = []
        for pod in self._scheduler.spec.pods:
            instances = []
            for i in range(pod.count):
                tasks = []
                for task_spec in pod.tasks:
                    full = task_full_name(pod.type, i, task_spec.name)
                    status = statuses.get(full)
                    info = self._scheduler.state_store.fetch_task(full)
                    shown = status.state.value if status else None
                    # a PAUSED override rewrites the shown state
                    # (reference: PodQueries surfacing PAUSING/PAUSED
                    # instead of the raw Mesos state)
                    override, progress = (
                        self._scheduler.state_store.fetch_goal_override(full)
                    )
                    if override is GoalStateOverride.PAUSED:
                        shown = (
                            "PAUSED"
                            if progress is OverrideProgress.COMPLETE
                            else "PAUSING"
                        )
                    tasks.append(
                        {
                            "name": full,
                            "id": info.task_id if info else None,
                            "status": shown,
                            "ready": status.ready if status else False,
                        }
                    )
                instances.append({"name": f"{pod.type}-{i}", "tasks": tasks})
            body.append({"name": pod.type, "instances": instances})
        return 200, {"service": self._scheduler.spec.name, "pods": body}

    def pod_status(self, pod_instance: str) -> Response:
        pod_type, index, error = self._parse_instance(pod_instance)
        if error:
            return error
        code, body = self.pod_statuses()
        for pod in body["pods"]:
            for instance in pod["instances"]:
                if instance["name"] == pod_instance:
                    return 200, instance
        return 404, {"message": f"no pod instance {pod_instance}"}

    def pod_info(self, pod_instance: str) -> Response:
        pod_type, index, error = self._parse_instance(pod_instance)
        if error:
            return error
        pod = self._scheduler.spec.pod(pod_type)
        out = []
        for task_spec in pod.tasks:
            full = task_full_name(pod_type, index, task_spec.name)
            info = self._scheduler.state_store.fetch_task(full)
            if info is not None:
                out.append(info.to_dict())
        return 200, out

    def pod_restart(self, pod_instance: str) -> Response:
        return self._pod_restart(pod_instance, replace=False)

    def pod_replace(self, pod_instance: str) -> Response:
        return self._pod_restart(pod_instance, replace=True)

    def _pod_restart(self, pod_instance: str, replace: bool) -> Response:
        pod_type, index, error = self._parse_instance(pod_instance)
        if error:
            return error
        killed = self._scheduler.restart_pod(pod_type, index, replace=replace)
        self._flush_journal()  # the scheduler verb journaled; make it durable now
        return 200, {"pod": pod_instance, "tasks": killed}

    def _flush_journal(self) -> None:
        journal = getattr(self._scheduler, "journal", None)
        if journal is not None:
            journal.flush()

    def pod_pause(self, pod_instance: str, tasks=None) -> Response:
        pod_type, index, error = self._parse_instance(pod_instance)
        if error:
            return error
        touched = self._scheduler.pause_pod(pod_type, index, tasks)
        if not touched:
            # no-op transition rejected (reference: PodQueries refuses
            # invalid override transitions)
            return 409, {"message": f"{pod_instance} is already paused"}
        self._flush_journal()
        return 200, {"pod": pod_instance, "tasks": touched}

    def pod_resume(self, pod_instance: str, tasks=None) -> Response:
        pod_type, index, error = self._parse_instance(pod_instance)
        if error:
            return error
        touched = self._scheduler.resume_pod(pod_type, index, tasks)
        if not touched:
            return 409, {"message": f"{pod_instance} is not paused"}
        self._flush_journal()
        return 200, {"pod": pod_instance, "tasks": touched}

    def pod_scale(self, pod_type: str, body: Optional[dict] = None) -> Response:
        """Operator scale verb (``POST /v1/pod/<type>/scale`` with
        ``{"count": N}``): rides the autoscale plan machinery — the
        action is visible, journaled, and interruptible under the
        ``autoscale`` plan, and the single-flight rule applies (409
        while another scale action for the pod is in flight)."""
        try:
            self._scheduler.spec.pod(pod_type)
        except Exception:
            return 404, {"message": f"no pod type {pod_type}"}
        count = (body or {}).get("count")
        if not isinstance(count, int) or isinstance(count, bool):
            return 400, {"message": "body must be {\"count\": <int>}"}
        try:
            phase = self._scheduler.scale_pod(pod_type, count)
        except RuntimeError as e:
            return 409, {"message": str(e)}
        except ValueError as e:
            return 400, {"message": str(e)}
        self._flush_journal()
        return 200, {
            "pod": pod_type,
            "count": count,
            "plan": "autoscale",
            "phase": phase.name,
        }

    def pod_scale_abandon(self, pod_type: str) -> Response:
        """Drop an in-flight scale action for the pod: the persisted
        count settles to deployed reality and the direction's
        cooldown latches (journaled as ``stage=abandoned``)."""
        try:
            self._scheduler.spec.pod(pod_type)
        except Exception:
            return 404, {"message": f"no pod type {pod_type}"}
        if not self._scheduler.abandon_scale(pod_type):
            return 409, {
                "message": f"no in-flight scale action for {pod_type}"
            }
        self._flush_journal()
        return 200, {"pod": pod_type, "abandoned": True}

    def _parse_instance(self, pod_instance: str):
        pod_type, sep, index = pod_instance.rpartition("-")
        if not sep or not index.isdigit():
            return None, None, (
                400,
                {"message": f"expected <pod>-<index>, got {pod_instance!r}"},
            )
        try:
            self._scheduler.spec.pod(pod_type)
        except Exception:
            return None, None, (404, {"message": f"no pod type {pod_type}"})
        return pod_type, int(index), None

    # -- configs (reference: http/queries/ConfigQueries.java) ---------

    def list_configs(self) -> Response:
        store = self._scheduler.config_store
        if store is None:
            return 503, {"message": "no config store"}
        return 200, store.list_ids()

    def get_config(self, config_id: str) -> Response:
        store = self._scheduler.config_store
        if store is None:
            return 503, {"message": "no config store"}
        data = store.fetch(config_id)
        if data is None:
            return 404, {"message": f"no config {config_id}"}
        return 200, data

    def target_config_id(self) -> Response:
        store = self._scheduler.config_store
        if store is None:
            return 503, {"message": "no config store"}
        target = store.get_target_config()
        if target is None:
            return 404, {"message": "no target config"}
        return 200, target

    def target_config(self) -> Response:
        code, target = self.target_config_id()
        if code != 200:
            return code, target
        return self.get_config(target)

    # -- state (reference: http/queries/StateQueries.java) ------------

    def state_properties(self) -> Response:
        return 200, self._scheduler.state_store.fetch_property_keys()

    def state_property(self, key: str) -> Response:
        value = self._scheduler.state_store.fetch_property(key)
        if value is None:
            return 404, {"message": f"no property {key}"}
        try:
            return 200, value.decode("utf-8")
        except UnicodeDecodeError:
            return 200, value.hex()

    _FILE_PREFIX = "file."

    def state_files(self) -> Response:
        """Reference: StateQueries.java:78 — operator-managed files in
        the state store (small configs/keytabs an operator stages for
        tasks or tooling to read back)."""
        keys = self._scheduler.state_store.fetch_property_keys()
        return 200, sorted(
            k[len(self._FILE_PREFIX):] for k in keys
            if k.startswith(self._FILE_PREFIX)
        )

    def state_file_get(self, name: str) -> Response:
        import base64 as _b64

        value = self._scheduler.state_store.fetch_property(
            self._FILE_PREFIX + name
        )
        if value is None:
            return 404, {"message": f"no file {name}"}
        return 200, {
            "name": name,
            "content": _b64.b64encode(value).decode("ascii"),
        }

    def state_file_put(self, name: str, body: dict) -> Response:
        import base64 as _b64

        content = (body or {}).get("content")
        if not isinstance(content, str):
            return 400, {"message": "body must be {\"content\": b64}"}
        try:
            value = _b64.b64decode(content, validate=True)
        except Exception:
            return 400, {"message": "content is not valid base64"}
        if len(value) > 1 << 20:
            # the state tree is replicated + snapshotted: it is for
            # small operator files, not artifact storage (uris: is)
            return 413, {"message": "file too large (1 MiB cap)"}
        from dcos_commons_tpu.state.state_store import StateStoreException

        try:
            self._scheduler.state_store.store_property(
                self._FILE_PREFIX + name, value
            )
        except StateStoreException as e:
            # key validation: the CLIENT's name is bad.  Persister/IO
            # failures propagate to the dispatcher's 500 path — a
            # store outage is not a malformed request.
            return 400, {"message": str(e)}
        return 200, {"name": name, "bytes": len(value)}

    def state_framework_id(self) -> Response:
        store = self._scheduler.framework_store
        if store is None:
            return 503, {"message": "no framework store"}
        framework_id = store.fetch_framework_id()
        if framework_id is None:
            return 404, {"message": "not registered"}
        return 200, framework_id

    def state_zones(self) -> Response:
        """Host -> zone map of the current inventory (reference:
        StateQueries zone files)."""
        return 200, {
            h.host_id: h.zone for h in self._scheduler.inventory.hosts()
        }

    # -- hosts (ISSUE 13: preemption & maintenance verbs) -------------

    def list_hosts(self) -> Response:
        """Per-host lifecycle state (up/down/preempted/maintenance)
        plus maintenance windows — the operator's drain dashboard."""
        inventory = self._scheduler.inventory
        if not hasattr(inventory, "host_states"):
            return 200, {"hosts": {}}
        return 200, {"hosts": inventory.host_states()}

    def host_drain(self, host_id: str, body: Optional[dict] = None) -> Response:
        if self._scheduler.inventory.host(host_id) is None:
            return 404, {"message": f"no host {host_id}"}
        try:
            window_s = float((body or {}).get("window_s", 0) or 0)
        except (TypeError, ValueError):
            return 400, {"message": "window_s must be a number"}
        changed = self._scheduler.drain_host(host_id, window_s=window_s)
        self._flush_journal()
        return 200, {
            "host": host_id,
            "state": "maintenance",
            "changed": changed,
            "window_s": window_s,
        }

    def host_preempt(self, host_id: str) -> Response:
        if self._scheduler.inventory.host(host_id) is None:
            return 404, {"message": f"no host {host_id}"}
        lost = self._scheduler.preempt_host(host_id)
        self._flush_journal()
        return 200, {
            "host": host_id,
            "state": "preempted",
            "tasks_lost": lost,
        }

    def host_up(self, host_id: str) -> Response:
        if self._scheduler.inventory.host(host_id) is None:
            return 404, {"message": f"no host {host_id}"}
        changed = self._scheduler.undrain_host(host_id)
        self._flush_journal()
        return 200, {"host": host_id, "state": "up", "changed": changed}

    # -- endpoints (reference: http/endpoints/EndpointsResource) ------

    def endpoints_generation(self) -> str:
        """Change stamp of the endpoint surface: reservations (ports
        move with claims) + the task subtree (launches, statuses,
        pause overrides — and advertised ports, which only change
        across a relaunch, i.e. a task mutation).  A router polling
        discovery compares this and skips the rebuild on a quiet
        fleet (the PR 9 generation discipline, ISSUE 12)."""
        ledger = self._scheduler.ledger
        store = self._scheduler.state_store
        task_gen = getattr(store, "task_generation", "")
        return f"{ledger.epoch}.{ledger.generation}/{task_gen}"

    def _assemble_endpoints(self):
        """One walk building both surfaces: port name -> ["host:port",
        ...] (plus TPU coordinator addresses under "coordinator"),
        and per-endpoint BACKEND rows carrying the task, its state,
        and whether it is draining — what a routing tier needs beyond
        bare addresses.

        Cost per call: O(THIS service's tasks) store reads (the same
        order as pod_statuses), plus one agent servestats read per
        ``advertise: true`` task — serve pods only, so a router's
        per-second discovery poll stays bounded by the serve pod
        count, never the fleet.  The stamp skips the ROUTER-side
        rebuild; caching the assembly scheduler-side would need the
        advertised ports folded into the base counters first (they
        are read live, outside them)."""
        out: Dict[str, List[str]] = {}
        backends: Dict[str, List[Dict[str, Any]]] = {}
        ledger = self._scheduler.ledger
        store = self._scheduler.state_store
        hosts = {h.host_id: h for h in self._scheduler.inventory.hosts()}
        port_reader = getattr(
            self._scheduler.agent, "advertised_port_of", None
        )
        # instances an ACTIVE pod-level teardown (surplus
        # decommission or autoscale scale-in) is about to kill: their
        # rows flip draining:true while task AND host still look
        # healthy, so the router drain-grace elapses before the kill
        # step fires (ISSUE 15 satellite — host-level drain alone
        # missed pod-granular teardowns)
        drain_reader = getattr(
            self._scheduler, "draining_instances", None
        )
        draining_pods = drain_reader() if callable(drain_reader) else set()
        for info in store.fetch_tasks():
            host = hosts.get(info.agent_id)
            hostname = host.hostname if host else info.agent_id
            pod = None
            for p in self._scheduler.spec.pods:
                if p.type == info.pod_type:
                    pod = p
            if pod is None:
                continue
            # full names are <pod>-<index>-<task> and TASK names may
            # themselves contain dashes (server-a): strip the known
            # prefix instead of splitting on the last dash
            prefix = f"{info.pod_type}-{info.pod_index}-"
            try:
                task_spec = pod.task(
                    info.name[len(prefix):]
                    if info.name.startswith(prefix)
                    else info.name.rsplit("-", 1)[-1]
                )
            except Exception:
                task_spec = None
            status = store.fetch_status(info.name)
            override, _progress = store.fetch_goal_override(info.name)
            state = status.state.value if status else None
            ready = bool(status.ready) if status else False
            # a backend is DRAINING when it should receive no new
            # requests: paused (decommission/pause rides the override),
            # not running, not yet warm — or its HOST is leaving
            # (maintenance drain, mark_down, preemption).  The host
            # check is what makes `host drain` stop the routing tier
            # BEFORE any kill fires: the task is still RUNNING and
            # ready, but its machine is going away (ISSUE 13
            # satellite — previously only the task-level signals were
            # consulted, so a pre-kill drain never surfaced)
            host_state = getattr(
                self._scheduler.inventory, "host_state", lambda _h: "up"
            )(info.agent_id)
            draining = (
                override is not GoalStateOverride.NONE
                or state != "TASK_RUNNING"
                or not ready
                or host_state not in ("up", "")
                or f"{info.pod_type}-{info.pod_index}" in draining_pods
            )
            advertised: Optional[int] = None
            advertised_read = False
            reservations = list(ledger.for_task(info.name))
            for reservation in reservations:
                port_specs = (
                    task_spec.resources.ports if task_spec is not None else []
                )
                for port_spec, port in zip(port_specs, reservation.ports):
                    if port_spec.advertise and callable(port_reader):
                        # the worker's actually-bound port (servestats
                        # annotation) wins over the reserved one: the
                        # listing names what is DIALABLE.  One read
                        # per task, shared by its advertise ports.
                        if not advertised_read:
                            advertised_read = True
                            try:
                                advertised = port_reader(
                                    info.name, agent_id=info.agent_id
                                )
                            except OSError:
                                advertised = None
                        if advertised:
                            port = advertised
                    address = f"{hostname}:{port}"
                    # serving role rides discovery (ISSUE 16): the
                    # router learns prefill/decode capacity from the
                    # same poll that hands it addresses — no extra
                    # round trip before the first placement decision
                    role = info.env.get("SERVE_ROLE", "")
                    out.setdefault(port_spec.name, []).append(address)
                    backends.setdefault(port_spec.name, []).append({
                        "address": address,
                        "task": info.name,
                        "state": state,
                        "ready": ready,
                        "draining": draining,
                        "role": role,
                    })
                    if port_spec.vip:
                        # VIP discovery (reference: NamedVIPEvaluation
                        # Stage + EndpointUtils VIP listing): clients
                        # resolve the stable VIP name to the live
                        # backend set; "web:80" lists under "vip:web"
                        vip_name = port_spec.vip.split(":", 1)[0]
                        out.setdefault(f"vip:{vip_name}", []).append(
                            address
                        )
                        backends.setdefault(f"vip:{vip_name}", []).append({
                            "address": address,
                            "task": info.name,
                            "state": state,
                            "ready": ready,
                            "draining": draining,
                            "role": role,
                        })
            # stable DNS-style names (reference: DiscoveryInfo +
            # EndpointUtils listing <task>.<svc>.<tld> names; the
            # `discovery: prefix:` override renames the task part, and
            # `service-tld:` the suffix — custom_tld.yml analogue).
            # Wiring the names into a resolver is the fleet's job; the
            # listing is the contract.
            tld = self._scheduler.spec.service_tld
            if tld and task_spec is not None:
                if task_spec.discovery_prefix:
                    disc_name = (
                        f"{task_spec.discovery_prefix}-{info.pod_index}"
                    )
                else:
                    disc_name = info.name
                dns_name = (
                    f"{disc_name}.{self._scheduler.spec.name}.{tld}"
                )
                entries = out.setdefault("dns", [])
                for reservation in reservations:
                    for port in reservation.ports:
                        entry = f"{dns_name}:{port}"
                        if entry not in entries:
                            entries.append(entry)
                if not any(
                    e.startswith(dns_name + ":") for e in entries
                ):
                    if dns_name not in entries:
                        entries.append(dns_name)
            coord = info.env.get("COORDINATOR_ADDRESS")
            if coord:
                entries = out.setdefault("coordinator", [])
                if coord not in entries:
                    entries.append(coord)
        if self._scheduler.spec.web_url:
            # web-url.yml analogue: the service's UI advertised with
            # its endpoints (reference: webui_url in FrameworkInfo)
            out.setdefault("web", []).append(self._scheduler.spec.web_url)
        return out, backends

    def _endpoint_map(self) -> Dict[str, List[str]]:
        """port name -> ["host:port", ...] (the original surface)."""
        return self._assemble_endpoints()[0]

    def list_endpoints(self) -> Response:
        return 200, sorted(self._endpoint_map().keys())

    def get_endpoint(self, name: str) -> Response:
        """One endpoint's addresses, its backend rows (task, state,
        draining — the routing tier's discovery contract), and the
        generation stamp a poller compares to skip quiet refreshes."""
        import hashlib as _hashlib
        import json as _json

        entries, backends = self._assemble_endpoints()
        addresses = entries.get(name)
        if addresses is None:
            return 404, {"message": f"no endpoint {name}"}
        body: Dict[str, Any] = {
            "name": name,
            "address": sorted(addresses),
        }
        rows = backends.get(name)
        if rows:
            body["backends"] = sorted(
                rows, key=lambda r: (r["task"], r["address"])
            )
        # the stamp covers exactly what a poller CONSUMES: the base
        # task/reservation generations plus a fingerprint of this
        # endpoint's assembled surface.  Advertised ports are read
        # live (outside the base counters), so without the
        # fingerprint a transiently-failed servestats read could
        # hand out a wrong address that an equal stamp then caches
        # at the router until unrelated churn
        surface = _hashlib.sha256(_json.dumps(
            [body["address"], body.get("backends", [])],
            sort_keys=True,
        ).encode("utf-8")).hexdigest()[:12]
        body["generation"] = f"{self.endpoints_generation()}+{surface}"
        return 200, body

    # -- artifacts (reference: http/endpoints/ArtifactResource:50) ----

    def artifact_template(
        self, config_id: str, pod_type: str, task_name: str, template_name: str
    ) -> Response:
        """Serve a config template's raw content for the given stored
        configuration (tasks pull these at bootstrap and render them
        against their env, sdk/bootstrap/main.go:291-376)."""
        store = self._scheduler.config_store
        if store is None:
            return 503, {"message": "no config store"}
        data = store.fetch(config_id)
        if data is None:
            return 404, {"message": f"no config {config_id}"}
        from dcos_commons_tpu.specification.specs import ServiceSpec

        spec = ServiceSpec.from_dict(data)
        try:
            task_spec = spec.pod(pod_type).task(task_name)
        except Exception:
            return 404, {"message": f"no task {pod_type}/{task_name}"}
        for template_path, dest in task_spec.config_templates:
            if os.path.basename(template_path) == template_name or \
                    dest == template_name:
                try:
                    with open(template_path, "r") as f:
                        return 200, f.read()
                except OSError as e:
                    return 500, {"message": f"cannot read template: {e}"}
        return 404, {"message": f"no template {template_name}"}

    # -- debug (reference: debug/*.java, /v1/debug) -------------------

    def debug_offers(self) -> Response:
        """Offer outcomes PLUS the fleet-scale evaluation state: the
        dirty-set size and cache hit rates of the incremental snapshot
        sync, index cardinalities, the requirement-memo/index counters
        and this service's suppress state — the first read in a
        slow-cycle triage (operations-guide)."""
        counters = self._scheduler.metrics.counters()
        evaluation: Dict[str, Any] = {}
        inventory = getattr(self._scheduler, "inventory", None)
        if inventory is not None and hasattr(inventory, "debug_stats"):
            evaluation = inventory.debug_stats()
        evaluation["counters"] = {
            key: counters[key]
            for key in (
                "offers.index.hit", "offers.index.scan",
                "offers.eval.shortcircuit", "offers.evaluated",
                "offers.declined", "suppresses", "revives",
            )
            if key in counters
        }
        # multi-service offer discipline: which services the fan-out
        # loop is currently skipping (attached by MultiServiceScheduler)
        discipline = getattr(self._scheduler, "offer_discipline", None)
        if callable(discipline):
            evaluation["discipline"] = discipline()
        return 200, {
            "outcomes": self._scheduler.outcome_tracker.to_json(),
            "evaluation": evaluation,
        }

    def debug_plans(self) -> Response:
        return 200, {
            name: serialize_plan(plan)
            for name, plan in self._scheduler.plans().items()
        }

    def debug_task_statuses(self) -> Response:
        from dcos_commons_tpu.debug.trackers import TaskStatusesTracker

        return 200, TaskStatusesTracker(self._scheduler.state_store).to_json()

    def debug_reservations(self) -> Response:
        from dcos_commons_tpu.debug.trackers import TaskReservationsTracker

        return 200, TaskReservationsTracker(self._scheduler.ledger).to_json()

    def debug_trace(self, fmt: Optional[str] = None) -> Response:
        """The traceview flight recorder: one causal timeline from
        offer intake through launch, status arrival, plan-step
        transition, and (via sandbox steplogs) the workers' own step
        loops.  ``?fmt=chrome`` returns Perfetto-loadable trace-event
        JSON (pid = service, tid lanes per pod); default is a plain-
        text timeline."""
        from dcos_commons_tpu.trace.export import to_chrome, to_text

        tracer = getattr(self._scheduler, "tracer", None)
        if tracer is None:
            return 503, {"message": "no trace recorder"}
        steplogs = self._collect_steplogs()
        service = self._scheduler.spec.name
        if fmt == "chrome":
            return 200, to_chrome(tracer, service=service,
                                  steplogs=steplogs)
        if fmt not in (None, "", "text"):
            return 400, {"message": f"unknown trace format {fmt!r} "
                                    "(expected 'chrome' or 'text')"}
        # journal events (operator verbs, failovers, detector alerts)
        # render into the text timeline on a `journal` lane, so the
        # ssh-and-curl view shows causes next to the spans they caused
        journal = getattr(self._scheduler, "journal", None)
        events = journal.events() if journal is not None else None
        return 200, to_text(tracer, service=service, steplogs=steplogs,
                            events=events)

    def debug_health(self, metric: Optional[str] = None) -> Response:
        """The fleet health plane: detector states (straggler scores,
        suspect hosts, SLO breaches), journal stats, recent alerts,
        and the bounded metric history (summary rows by default;
        ``?metric=<name>`` returns that metric's full timestamped
        series with the derived rate for counters)."""
        health = getattr(self._scheduler, "health", None)
        if health is None:
            return 200, {"enabled": False}
        return 200, health.describe(self._scheduler, metric=metric)

    def debug_events(self, since: Optional[str] = None,
                     kind: Optional[str] = None) -> Response:
        """The durable event journal: operator verbs, plan-step
        transitions, failovers/lease epochs, admission rejections,
        recovery actions, detector alerts.  ``?since=<seq>`` resumes a
        cursor (seqs are monotonic ACROSS failovers); ``?kind=`` filters
        (e.g. ``alert``)."""
        journal = getattr(self._scheduler, "journal", None)
        if journal is None:
            return 200, {"enabled": False, "events": [], "seq": 0}
        try:
            since_seq = int(since) if since else 0
        except ValueError:
            return 400, {"message": f"bad since cursor {since!r}"}
        return 200, {
            "events": journal.events(
                since=since_seq, kinds=[kind] if kind else None
            ),
            "seq": journal.last_seq,
            "journal": journal.describe(),
        }

    def debug_ha(self) -> Response:
        """HA control-plane state: leader identity + lease expiry (the
        record in the replicated tree), this scheduler's lease epoch
        and failover count, fenced-write rejections, per-standby
        replication watermarks, and the last re-hydration report.
        The failover runbook (docs/operations-guide.md) reads this
        before and after a manual promotion."""
        ha = getattr(self._scheduler, "ha_state", None)
        if ha is None:
            body: Dict[str, Any] = {"enabled": False}
            report = getattr(self._scheduler, "last_rehydration", None)
            if report is not None:
                body["last_rehydration"] = report
            return 200, body
        return 200, ha.describe(refresh=True)

    def debug_serving(self) -> Response:
        """Per-pod serving load: each serve worker mirrors its engine
        gauges (queue depth, active slots, KV occupancy, tokens/s,
        TTFT percentiles) to its sandbox; this merges them per task —
        the signal a load-driven scale-out plan reads (ROADMAP item
        2), and the place an operator checks which pod is saturating
        before the 503s start."""
        reader = getattr(self._scheduler.agent, "serving_stats_of", None)
        if not callable(reader):
            return 200, {"serving": {}}
        out: Dict[str, dict] = {}
        for info in self._scheduler.state_store.fetch_tasks():
            try:
                stats = reader(info.name)
            except OSError:
                continue
            if stats:
                out[info.name] = stats
        return 200, {"serving": out}

    def debug_router(self) -> Response:
        """Serving-front-door state: every router task's gauge
        snapshot (pod set size, draining/failed counts, affinity hit
        rate, retries/failovers, latency percentiles — router/core.py
        ``stats()``), split out of the serving merge by the
        ``router_pods`` marker key, plus the endpoint generation the
        routers' discovery is tracking.  The prefix-affinity triage
        surface (operations-guide "Serving front door")."""
        reader = getattr(self._scheduler.agent, "serving_stats_of", None)
        routers: Dict[str, dict] = {}
        if callable(reader):
            for info in self._scheduler.state_store.fetch_tasks():
                try:
                    stats = reader(info.name)
                except OSError:
                    continue
                if isinstance(stats, dict) and "router_pods" in stats:
                    routers[info.name] = stats
        return 200, {
            "routers": routers,
            "endpoints_generation": self.endpoints_generation(),
        }

    def _collect_steplogs(self) -> Dict[str, List[dict]]:
        """Worker step telemetry, merged from task sandboxes when the
        agent surfaces them (LocalProcessAgent.steplog_of); remote
        fleets return {} until their daemons grow the same surface."""
        reader = getattr(self._scheduler.agent, "steplog_of", None)
        if not callable(reader):
            return {}
        out: Dict[str, List[dict]] = {}
        for info in self._scheduler.state_store.fetch_tasks():
            try:
                records = reader(info.name)
            except OSError:
                continue
            if records:
                out[info.name] = records
        return out

    # -- metrics ------------------------------------------------------

    def metrics_json(self) -> Response:
        return 200, self._scheduler.metrics.snapshot()

    def metrics_prometheus(self) -> Tuple[int, str]:
        return 200, self._scheduler.metrics.prometheus()
