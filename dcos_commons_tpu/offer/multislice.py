"""Multi-slice gang placement: the slice-set layer (ISSUE 20).

A ``tpu: slices: N`` gang spans N ICI slices joined by DCN — the
scale axis a single torus cannot reach (SURVEY section 5.8 inter-slice
DCN collectives).  This module owns the SLICE-SET half of gang
placement:

* :func:`eligible_slice_ids` — the PR 9 fully-free-by-slice
  pre-filter, factored out of the evaluator: slices that cannot hold
  even ONE ``topology`` rectangle of fully-free hosts are skipped
  before any anchor search (superset-sound — the per-slice host need
  comes from the hosts' own chip blocks, never the declared spec).
* :func:`place_slice_set` — pick N DISTINCT slices, one contiguous
  ``topology`` rectangle in each (torus adjacency within a slice via
  ``find_subslice``), all N reachable over one DCN fabric (the
  ``dcn_pool`` host attribute; hosts that advertise none share the
  default pool).  Workers are numbered slice-major so
  ``worker_id // hosts_per_slice`` IS the slice index — the mesh
  layer's dcn axis falls exactly on the slice boundary.
* :func:`slice_leaders` — the per-slice coordinator anchors: slice
  k's first worker hosts slice k's rendezvous endpoint, advertised to
  every worker as ``TPU_SLICE_COORDS`` (the global jax.distributed
  coordinator stays on worker 0; the per-slice addresses give
  slice-local barriers and the dcn gradient ring a stable anchor per
  slice).

The evaluator (offer/evaluate.py ``_evaluate_gang``) calls this layer
for EVERY gang — a single-slice gang is the N=1 case — then claims
resources host by host exactly as before.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Set, Tuple

from dcos_commons_tpu.offer.outcome import EvaluationOutcome
from dcos_commons_tpu.offer.torus import find_subslice

# fleet attribute naming the DCN fabric a slice is plugged into; a
# multi-slice gang's slices must share one pool (cross-pool traffic
# would transit a slower backbone the bandwidth model does not price).
# Hosts without the attribute share the DEFAULT ("") pool, so fleets
# that never set it behave as one flat fabric.
DCN_POOL_ATTRIBUTE = "dcn_pool"

# env var carrying the per-slice coordinator addresses, comma-joined
# slice-major ("host0:p0,host1:p1,..."): claim-time facts, injected by
# the evaluator next to TPU_SLICE_INDEX/TPU_NUM_SLICES
ENV_TPU_SLICE_COORDS = "TPU_SLICE_COORDS"

# reservation tag for the slice-local rendezvous port riding each
# slice leader's first task (mirrors COORDINATOR_PORT_NAME)
SLICE_COORDINATOR_PORT_NAME = "slice-coordinator"


def dcn_pool_of(host) -> str:
    """The DCN fabric a host belongs to ("" = the default pool)."""
    return (getattr(host, "attributes", None) or {}).get(
        DCN_POOL_ATTRIBUTE, ""
    )


def hosts_per_slice(tpu) -> int:
    """Hosts one ``topology`` sub-slice occupies — the slice quantum
    every multi-slice size computation shares (admission, elastic
    shrink, trim, worker numbering)."""
    return max(1, tpu.total_chips // max(1, tpu.chips_per_host))


def eligible_slice_ids(index, hosts: Dict[str, object], total_chips: int,
                       generation: str = "") -> Set[str]:
    """Slices that could hold ONE fully-free ``total_chips`` rectangle
    of ``generation`` hosts (any generation when "").

    Torus-neighborhood pre-filter (PR 9): a contiguous rectangle of
    tx*ty chips needs hosts_needed FULLY-FREE hosts inside one slice,
    so slices short of that are skipped before any anchor search.
    The per-slice host need comes from the HOSTS' chip blocks
    (find_subslice tiles by host block, not by the spec's declared
    chips-per-host — a mis-declared spec must not under-approximate).
    Max block area among the slice's free hosts keeps the filter
    superset-sound when blocks are mixed (mixed slices fail the search
    anyway).  The "" bucket (TPU hosts registered without a slice id)
    is a searchable slice like any other.
    """
    eligible: Set[str] = set()
    for s, free in index.fully_free_by_slice().items():
        if generation:
            # the spec's generation is a hard placement fact (the
            # fleet-sizing and admission formulas count per-generation
            # slices; the evaluator must agree or admission admits
            # specs that place on the wrong silicon)
            free = [
                h for h in free
                if h in hosts and hosts[h].generation == generation
            ]
        if not free:
            continue
        area = max(
            (hosts[h].chips_per_host for h in free if h in hosts),
            default=0,
        )
        if area <= 0:
            continue
        if len(free) >= max(1, -(-total_chips // area)):
            eligible.add(s)
    return eligible


@dataclass
class SliceSetPlacement:
    """Result of :func:`place_slice_set`: slice-major ordered host
    snapshots (worker k lives on ``snapshots[k]``) or a failure
    outcome explaining every slice's refusal."""

    outcome: EvaluationOutcome
    snapshots: List = field(default_factory=list)
    slice_ids: Tuple[str, ...] = ()
    hosts_per_slice: int = 0

    @property
    def ok(self) -> bool:
        return bool(self.snapshots)


def place_slice_set(
    snapshots: List,
    tpu,
    eligible: Callable[[object], EvaluationOutcome],
) -> SliceSetPlacement:
    """Pick ``tpu.slices`` distinct slices, one ``topology`` rectangle
    each, all of the spec's ``generation``, all on one DCN pool.

    Greedy first-fit in scan order (deterministic, like every other
    placement path): the first sub-slice pins the gang's DCN pool,
    and subsequent searches only see hosts of that pool.  Greedy
    pool-pinning is sound for the fleets this models — pools partition
    slices, and scan order visits every pool's slices, so if ANY pool
    holds N free slices a permutation of the same greedy scan finds
    it; the failure outcome names the pinned pool so an operator can
    read why a half-free fleet refused.
    """
    n_slices = max(1, tpu.slices)
    generation = getattr(tpu, "generation", "") or ""
    ordered: List = []
    used_slices: Set[str] = set()
    pool: str = ""
    pool_pinned = False
    outcome = EvaluationOutcome.ok(
        "gang", f"{n_slices} slice(s) of {tpu.topology}"
    )
    for _ in range(n_slices):
        candidates = [
            s for s in snapshots
            if s.host.slice_id not in used_slices
            and (not generation or s.host.generation == generation)
            and (not pool_pinned or dcn_pool_of(s.host) == pool)
        ]
        placement = find_subslice(
            candidates, tpu.topology_dims(), tpu.chips_per_host, eligible
        )
        outcome.children.append(placement.outcome)
        if not placement.snapshots:
            outcome.passed = False
            where = (
                f" on dcn pool {pool or 'default'}" if pool_pinned else ""
            )
            outcome.reason = (
                f"no free slice for sub-gang "
                f"{len(used_slices) + 1}/{n_slices}{where} "
                f"(excluded: {sorted(used_slices) or 'none'})"
            )
            return SliceSetPlacement(outcome)
        anchor = placement.snapshots[0].host
        used_slices.add(anchor.slice_id)
        if n_slices > 1 and not pool_pinned:
            pool = dcn_pool_of(anchor)
            pool_pinned = True
        ordered.extend(placement.snapshots)
    return SliceSetPlacement(
        outcome,
        ordered,
        tuple(sorted(used_slices)),
        len(ordered) // n_slices,
    )


def slice_leaders(ordered: List, n_slices: int) -> List:
    """The slice-major leader snapshot of each sub-slice: worker
    ``k * hosts_per_slice`` anchors slice k's coordinator endpoint."""
    if n_slices <= 1 or not ordered:
        return []
    hps = len(ordered) // n_slices
    return [ordered[k * hps] for k in range(n_slices)]
