"""X9: the fleet health plane — metric history, event journal,
anomaly detectors.

Traceview (X4) answers "what just happened"; this package retains and
judges: bounded metric history rings (metrics/registry.py
MetricHistory) behind ``GET /v1/debug/health``, a durable
capacity-bounded event journal (operator verbs, plan transitions,
failovers, admission rejections, recovery actions, detector alerts)
behind ``GET /v1/debug/events?since=``, and per-cycle detectors —
straggler median-ratio scoring off merged steplogs, serving-SLO
watchers off the engine gauges, lease-churn watching off ha.* — whose
suspect-host output feeds placement as a soft sort-last signal.
"""

from dcos_commons_tpu.health.detectors import (
    LeaseChurnWatcher,
    ServingSloWatcher,
    StragglerDetector,
    median_ratio_scores,
)
from dcos_commons_tpu.health.journal import (
    EventJournal,
    PersisterBackend,
    StatePropertyBackend,
)
from dcos_commons_tpu.health.monitor import HealthMonitor

__all__ = [
    "EventJournal",
    "HealthMonitor",
    "LeaseChurnWatcher",
    "PersisterBackend",
    "ServingSloWatcher",
    "StatePropertyBackend",
    "StragglerDetector",
    "median_ratio_scores",
]
