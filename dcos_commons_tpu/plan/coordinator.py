"""PlanCoordinator: merge candidates across plans without collisions.

Reference: scheduler/plan/DefaultPlanCoordinator.java:33-90 — collects
candidate steps from every plan manager while tracking *dirtied
assets* (pod instances already being worked) so two plans (e.g. deploy
and recovery) never touch the same pod simultaneously.
"""

from __future__ import annotations

from typing import List, Sequence, Set

from dcos_commons_tpu.plan.plan_manager import PlanManager
from dcos_commons_tpu.plan.step import Step


class DefaultPlanCoordinator:
    def __init__(self, plan_managers: Sequence[PlanManager]):
        # order = priority: earlier managers claim assets first
        # (the scheduler passes recovery before deploy, as the
        # reference does via plan manager ordering)
        self._managers: List[PlanManager] = list(plan_managers)

    @property
    def plan_managers(self) -> List[PlanManager]:
        return self._managers

    def get_candidates(self) -> List[Step]:
        dirty: Set[str] = set()
        for manager in self._managers:
            dirty |= manager.in_progress_assets()
        candidates: List[Step] = []
        for manager in self._managers:
            for step in manager.get_candidates(set(dirty)):
                assets = step.get_asset_names()
                if assets & dirty:
                    continue
                dirty |= assets
                candidates.append(step)
        return candidates

    def has_work(self) -> bool:
        """New-work signal feeding revive/suppress decisions
        (reference: WorkSetTracker / AbstractScheduler.java:136-160)."""
        return bool(self.get_candidates()) or any(
            not m.get_plan().is_complete and not m.get_plan().is_interrupted()
            and not m.get_plan().has_errors()
            for m in self._managers
        )

    def work_set(self) -> Set[str]:
        """The names of current candidate steps (revive detection)."""
        return {step.name for step in self.get_candidates()}
