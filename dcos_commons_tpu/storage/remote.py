"""Networked persistence: a state server + remote Persister client.

Reference: curator/CuratorPersister.java:43-110 — the reference keeps
ALL scheduler state in ZooKeeper with atomic multi-op transactions so
a scheduler process is disposable: kill it anywhere, restart it
anywhere, and plans resume mid-step.  CuratorLocker (taken in
SchedulerRunner.run) guarantees one active scheduler per service.

This module is that pair for the TPU fleet, ZooKeeper replaced by a
small HTTP state server (one per cluster / control-plane host):

* ``StateServer`` — hierarchical KV over any local Persister
  (FileWalPersister for durability), every mutation under one lock so
  ``apply`` batches stay atomic, plus TTL leases for the scheduler
  instance lock.
* ``RemotePersister`` — the Persister contract over HTTP; network or
  server failures surface as PersisterError, which fails the scheduler
  cycle and (after the crash-to-restart threshold) the process —
  exactly how the reference treats a ZK outage.
* ``RemoteLocker`` — acquire/renew/release of a named TTL lease; the
  renewal thread keeps the lease while the process lives, and a dead
  scheduler's lease expires so a standby can take over (failover).

Protocol (JSON over HTTP):

    POST /v1/kv/get       {path}                -> {found, value?}
    POST /v1/kv/set       {path, value}
    POST /v1/kv/children  {path}                -> {found, children}
    POST /v1/kv/delete    {path}                -> {found}
    POST /v1/kv/apply     {ops: [{op, path, value?}]}   (atomic)
    POST /v1/lock/acquire {name, owner, ttl_s}  -> {acquired, owner}
    POST /v1/lock/release {name, owner}         -> {released}

High availability (see storage/replication.py for the design):

    POST /v1/repl/snapshot {}                   -> {seq, epoch, nodes}
    POST /v1/repl/pull     {from_seq, wait_s}   -> {entries | snapshot_needed}
    POST /v1/repl/promote  {epoch?}             -> {epoch}   (standby -> primary)
    POST /v1/repl/fence    {epoch}              -> {role}    (demote stale primary)
    POST /v1/repl/status   {}                   -> {role, epoch, seq, ...}

Every response carries ``epoch`` and ``role``; every client request
may carry ``_fence`` (the highest epoch the client has seen) — a
primary below that token has been superseded and fences itself.
Standbys answer kv/lock routes with 503 so clients rotate to the
primary.  Values travel base64-encoded.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, List, Optional, Tuple

from dcos_commons_tpu.storage.persister import (
    DeleteOp,
    MemPersister,
    Persister,
    PersisterError,
    SetOp,
    TransactionOp,
)


LEASE_PREFIX = "/__cluster__/leases"
EPOCH_NODE = "/__cluster__/epoch"
# durable fenced marker: a superseded primary must stay fenced across
# process restarts, or a supervisor's auto-restart would resurrect it
# as a primary at the ADOPTED epoch — equal to the new primary's, so
# clients could not tell them apart
FENCED_NODE = "/__cluster__/fenced"

ROLE_PRIMARY = "primary"
ROLE_STANDBY = "standby"
ROLE_FENCED = "fenced"


class NotPrimaryError(PersisterError):
    """Raised on kv/lock routes by a non-primary; maps to HTTP 503 so
    clients rotate servers instead of failing the operation."""


class StateServer:
    """HTTP front end over one local Persister (the cluster's ZK).

    Leases are persisted through the backend (wall-clock expiry), so a
    state-server restart does NOT silently drop the scheduler instance
    lock — the reference's ZK ephemerals survive a ZK follower bounce
    the same way (CuratorLocker over a ZK ensemble)."""

    def __init__(
        self,
        backend: Optional[Persister] = None,
        port: int = 0,
        bind: str = "127.0.0.1",
        auth_token: str = "",
        tls=None,
        advertise_host: str = "",
        replicate_from: str = "",
        ca_file: str = "",
        sync_timeout_s: float = 2.0,
    ):
        from dcos_commons_tpu.security import auth as _auth
        from dcos_commons_tpu.storage.replication import (
            ReplicationLog,
            StandbyTail,
        )

        self._backend = backend or MemPersister()
        self._lock = threading.RLock()
        # lease name -> (owner, wall-clock expiry); mirrored to the
        # backend under LEASE_PREFIX on every mutation
        self._leases: Dict[str, Tuple[str, float]] = self._load_leases()
        self.advertise_host = advertise_host
        self._scheme = _auth.url_scheme(tls)
        # -- HA role + fencing epoch (storage/replication.py) ---------
        self._role = ROLE_STANDBY if replicate_from else ROLE_PRIMARY
        self._epoch = self._load_epoch()
        if self._role == ROLE_PRIMARY and self._backend.exists(FENCED_NODE):
            # a fenced primary restarted by its supervisor must come
            # back FENCED: it adopted the new primary's epoch, so as a
            # primary it would be indistinguishable from the real one.
            # It rejoins by being restarted with --standby-of (the
            # snapshot restore clears the marker).
            self._role = ROLE_FENCED
        self._log = ReplicationLog(sync_timeout_s=sync_timeout_s)
        self._tail: Optional[StandbyTail] = None
        if self._role == ROLE_PRIMARY:
            if self._epoch == 0:
                # fresh cluster: epoch 1 (clients default to fence 0,
                # which never fences anybody)
                self._set_epoch(1)
            # continue the stream where the durable tree left off:
            # a restarted primary has an empty ring, and a standby
            # whose applied seq predates it will be told to snapshot
        else:
            self._tail = StandbyTail(
                self._backend, self._lock, replicate_from,
                auth_token=auth_token, ca_file=ca_file,
                on_epoch=self._adopt_epoch,
            )
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code: int, body: dict) -> None:
                body.setdefault("epoch", server._epoch)
                body.setdefault("role", server._role)
                payload = json.dumps(body).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                # ALL state routes are mutating or state-revealing:
                # with a token set there is no anonymous surface
                if not _auth.check_bearer(self.headers, auth_token):
                    self._reply(*_auth.UNAUTHORIZED)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    server.check_fence(int(body.get("_fence", 0) or 0))
                    if self.path.startswith("/v1/repl/"):
                        # replication routes manage their own locking
                        # (pull long-polls must not hold the kv lock)
                        self._reply(200, server.handle_repl(
                            self.path, body
                        ))
                        return
                    if server._role != ROLE_PRIMARY:
                        # kv/lock surface exists only on the primary;
                        # 503 tells the client to rotate servers
                        self._reply(503, {
                            "error": f"not primary ({server._role})",
                        })
                        return
                    out = server.handle(self.path, body)
                    seq = out.pop("_sync_seq", None)
                    if seq is not None:
                        # bounded-sync barrier OUTSIDE the kv lock: an
                        # attached, caught-up standby must have pulled
                        # this mutation before the client is acked
                        server._log.wait_replicated(seq)
                    self._reply(200, out)
                except NotPrimaryError as e:
                    self._reply(503, {"error": str(e)})
                except PersisterError as e:
                    self._reply(409, {"error": str(e), "path": e.path})
                except Exception as e:
                    self._reply(500, {"error": repr(e)})

        self._server = _auth.wrap_http_server(
            ThreadingHTTPServer((bind, port), Handler), tls
        )
        self._thread: Optional[threading.Thread] = None

    # -- epoch / fencing ----------------------------------------------

    def _load_epoch(self) -> int:
        raw = self._backend.get_or_none(EPOCH_NODE)
        try:
            return int((raw or b"0").decode())
        except ValueError:
            return 0

    def _set_epoch(self, epoch: int) -> None:
        with self._lock:
            self._epoch = epoch
            self._backend.set(EPOCH_NODE, str(epoch).encode())

    def _adopt_epoch(self, epoch: int) -> None:
        """Standby tail learned the primary's epoch from a pull."""
        with self._lock:
            if epoch > self._epoch:
                self._epoch = epoch
                self._backend.set(EPOCH_NODE, str(epoch).encode())

    def check_fence(self, token: int) -> None:
        """A request carrying an epoch above ours proves a newer
        primary exists: if we are (or think we are) the primary, we
        have been superseded and must never accept another write —
        the fencing half of split-brain prevention."""
        if token <= self._epoch:
            return
        with self._lock:
            if token <= self._epoch:
                return
            if self._role == ROLE_PRIMARY:
                self._role = ROLE_FENCED
                try:
                    # durable: fencing must survive a process restart
                    self._backend.set(FENCED_NODE, str(token).encode())
                except PersisterError:
                    pass
            self._epoch = token
            try:
                self._backend.set(EPOCH_NODE, str(token).encode())
            except PersisterError:
                pass

    # -- lease persistence --------------------------------------------

    def _load_leases(self) -> Dict[str, Tuple[str, float]]:
        leases: Dict[str, Tuple[str, float]] = {}
        try:
            names = self._backend.get_children(LEASE_PREFIX)
        except PersisterError:
            return leases
        for name in names:
            try:
                raw = self._backend.get(f"{LEASE_PREFIX}/{name}")
                entry = json.loads(raw or b"{}")
                leases[name] = (entry["owner"], float(entry["expires_at"]))
            except (PersisterError, KeyError, ValueError):
                continue
        return leases

    def _store_lease(self, name: str, owner: str, expires_at: float) -> int:
        return self._mutate([SetOp(
            f"{LEASE_PREFIX}/{name}",
            json.dumps({"owner": owner, "expires_at": expires_at}).encode(),
        )])

    def _drop_lease(self, name: str) -> Optional[int]:
        path = f"{LEASE_PREFIX}/{name}"
        if not self._backend.exists(path):
            return None
        return self._mutate([DeleteOp(path)])

    # -- mutation funnel ----------------------------------------------

    def _mutate(self, ops: List[TransactionOp]) -> int:
        """Every write goes through here: apply to the backend, then
        append to the replication log.  Caller holds self._lock, so
        log order == apply order.  Returns the log seq (the caller's
        bounded-sync barrier)."""
        from dcos_commons_tpu.storage.replication import encode_ops

        self._backend.apply(ops)
        return self._log.append(encode_ops(ops))

    # -- request handling ---------------------------------------------

    def handle(self, route: str, body: dict) -> dict:
        with self._lock:
            if self._role != ROLE_PRIMARY:
                # authoritative re-check UNDER the lock: the unlocked
                # gate in do_POST can race a concurrent fence — once
                # fenced, not one more write may be applied or acked
                raise NotPrimaryError(f"not primary ({self._role})")
            if route == "/v1/kv/get":
                value = None
                try:
                    value = self._backend.get(body["path"])
                    found = True
                except PersisterError:
                    found = False
                return {
                    "found": found,
                    "value": base64.b64encode(value).decode()
                    if value is not None else None,
                }
            if route == "/v1/kv/set":
                seq = self._mutate([SetOp(
                    body["path"], base64.b64decode(body["value"] or "")
                )])
                return {"ok": True, "_sync_seq": seq}
            if route == "/v1/kv/children":
                try:
                    return {
                        "found": True,
                        "children": self._backend.get_children(body["path"]),
                    }
                except PersisterError:
                    return {"found": False, "children": []}
            if route == "/v1/kv/delete":
                if not self._backend.exists(body["path"]):
                    return {"found": False}
                seq = self._mutate([DeleteOp(body["path"])])
                return {"found": True, "_sync_seq": seq}
            if route == "/v1/kv/apply":
                from dcos_commons_tpu.storage.replication import decode_ops

                raw_ops = body.get("ops", [])
                for raw in raw_ops:
                    if raw.get("op") not in ("set", "delete"):
                        raise PersisterError(
                            f"unknown op {raw.get('op')!r}"
                        )
                ops = decode_ops(raw_ops)
                seq = self._mutate(ops)
                return {"ok": True, "applied": len(ops), "_sync_seq": seq}
            if route == "/v1/lock/acquire":
                return self._acquire_locked(
                    body["name"], body["owner"],
                    float(body.get("ttl_s", 15.0)),
                )
            if route == "/v1/lock/release":
                return self._release_locked(body["name"], body["owner"])
            raise PersisterError(f"no route {route}")

    def _acquire_locked(self, name: str, owner: str, ttl_s: float) -> dict:
        # wall-clock expiry (not monotonic): leases must survive a
        # state-server restart via the backend, and monotonic clocks
        # don't cross processes
        now = time.time()
        held = self._leases.get(name)
        if held is not None and held[1] > now and held[0] != owner:
            return {
                "acquired": False,
                "owner": held[0],
                "expires_in": round(held[1] - now, 1),
            }
        # fresh acquire or renewal by the current owner
        self._leases[name] = (owner, now + ttl_s)
        seq = self._store_lease(name, owner, now + ttl_s)
        return {"acquired": True, "owner": owner, "_sync_seq": seq}

    def _release_locked(self, name: str, owner: str) -> dict:
        held = self._leases.get(name)
        if held is not None and held[0] == owner:
            del self._leases[name]
            seq = self._drop_lease(name)
            out = {"released": True}
            if seq is not None:
                out["_sync_seq"] = seq
            return out
        return {"released": False}

    # -- replication routes (storage/replication.py design) -----------

    def handle_repl(self, route: str, body: dict) -> dict:
        if route == "/v1/repl/status":
            out = {"role": self._role, "epoch": self._epoch}
            out.update(self._log.status())
            if self._tail is not None:
                out.update(self._tail.status())
            return out
        if route == "/v1/repl/promote":
            return self.promote(int(body.get("epoch", 0) or 0))
        if route == "/v1/repl/fence":
            # operator verb: demote a stale primary directly (used by
            # `state-server --promote` when the old primary is still
            # reachable, closing the partition window by hand)
            self.check_fence(int(body.get("epoch", 0) or 0))
            return {"role": self._role}
        if self._role != ROLE_PRIMARY:
            raise PersisterError(f"not primary ({self._role}): {route}")
        if route == "/v1/repl/snapshot":
            from dcos_commons_tpu.storage.replication import dump_tree

            with self._lock:
                status = self._log.status()
                return {
                    "seq": status["seq"],
                    "stream_id": self._log.stream_id,
                    "nodes": dump_tree(self._backend),
                }
        if route == "/v1/repl/pull":
            standby_id = str(body.get("standby_id", ""))
            if not standby_id:
                # anonymous pullers would collide as "" and bypass the
                # single-puller guard entirely
                raise PersisterError("pull requires a standby_id")
            # long-poll OUTSIDE the kv lock: the log has its own
            return self._log.pull(
                int(body.get("from_seq", 1)),
                float(body.get("wait_s", 0.0)),
                standby_id,
                str(body.get("stream_id", "")),
            )
        raise PersisterError(f"no route {route}")

    def promote(self, epoch: int = 0) -> dict:
        """Standby -> primary with a fresh fencing epoch.  The log
        continues at the replicated seq so a future standby of THIS
        server starts cleanly; leases are reloaded from the replicated
        tree, so the scheduler's instance lease survives failover."""
        with self._lock:
            if self._role != ROLE_STANDBY:
                # a FENCED server must never be promoted: it carries a
                # pre-failover stale tree, and promoting it would fence
                # the good primary and converge the cluster on stale
                # state.  It rejoins by restarting with --standby-of.
                raise PersisterError(
                    f"can only promote a standby (role={self._role})"
                )
            tail = self._tail
            if (self._epoch == 0
                    and (tail is None or tail.applied_seq == 0)
                    and epoch == 0):
                # never synced: promoting would serve an EMPTY tree at
                # epoch 1 — colliding with the old primary's bootstrap
                # epoch, so fencing could not even tell them apart.
                # An operator who really means it passes an explicit
                # epoch.
                raise PersisterError(
                    "standby never replicated from the primary; "
                    "refusing to promote an empty tree (pass an "
                    "explicit epoch to override)"
                )
            self._tail = None
            if tail is not None:
                # non-blocking: the tail may sit in a long-poll against
                # the dead primary for seconds — failover latency must
                # not pay for that.  signal_stop + the flip below under
                # ONE lock hold guarantees no late entry applies after
                # we start acting as primary.
                tail.signal_stop()
            new_epoch = max(epoch, self._epoch + 1)
            base_seq = tail.applied_seq if tail is not None else 0
            self._role = ROLE_PRIMARY
            self._set_epoch(new_epoch)
            from dcos_commons_tpu.storage.replication import StandbyTail

            # best-effort cleanup of stale cluster markers: the fenced
            # marker must not re-fence this server on restart, and the
            # applied-seq/stream markers describe a standby life that
            # primary-life writes will never update — if this server is
            # later fenced and rejoins with --standby-of, a surviving
            # stale applied value could line up with the new primary's
            # ring and skip snapshot repair, silently keeping divergent
            # unreplicated writes.
            for node in (
                FENCED_NODE,
                StandbyTail.APPLIED_NODE,
                StandbyTail.STREAM_NODE,
            ):
                try:
                    self._backend.recursive_delete(node)
                except PersisterError:
                    pass
            self._log.reset(base_seq)
            self._leases = self._load_leases()
        if tail is not None:
            # reap the thread off the critical path
            threading.Thread(target=tail.stop, daemon=True).start()
        return {"promoted": True, "epoch": new_epoch}

    # -- lifecycle ----------------------------------------------------

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        if self.advertise_host:
            host = self.advertise_host
        elif host in ("0.0.0.0", "::"):
            # announce files must carry a dialable address (ADVICE r2)
            import socket

            host = socket.gethostname()
        return f"{self._scheme}://{host}:{port}"

    def start(self) -> "StateServer":
        if self._tail is not None:
            self._tail.start()
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="state-server", daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        if self._tail is not None:
            self._tail.start()
        self._server.serve_forever()

    def stop(self) -> None:
        tail = self._tail
        if tail is not None:
            tail.stop()
        self._server.shutdown()
        self._server.server_close()
        self._backend.close()


class RemotePersister(Persister):
    """Persister over one or more StateServers.  Failures raise
    PersisterError — the scheduler treats a dead state backend like
    the reference treats a ZK outage: fail the cycle, crash to
    restart.

    HA: ``base_url`` may be a comma-separated list (primary +
    standbys).  Calls rotate to the next server when the current one
    is unreachable or answers 503 (not primary).  The client tracks
    the highest fencing ``epoch`` it has seen, sends it with every
    request (``_fence`` — a superseded primary fences itself on
    receipt), and refuses responses from servers whose epoch is below
    that high-water mark (stale primary)."""

    def __init__(self, base_url: str, timeout_s: float = 10.0,
                 auth_token: str = "", ca_file: str = ""):
        from dcos_commons_tpu.security import auth as _auth

        self._urls = [
            u.strip().rstrip("/")
            for u in base_url.split(",") if u.strip()
        ]
        self._cur = 0
        self._max_epoch = 0
        self._epoch_lock = threading.Lock()
        self._timeout_s = timeout_s
        self._headers = {"Content-Type": "application/json",
                         **_auth.auth_headers(auth_token)}
        self._ssl_ctx = (
            _auth.client_ssl_context(ca_file)
            if any(u.startswith("https") for u in self._urls) else None
        )

    def _note_epoch(self, out: dict) -> None:
        try:
            epoch = int(out.get("epoch", 0) or 0)
        except (TypeError, ValueError):
            return
        with self._epoch_lock:
            if epoch > self._max_epoch:
                self._max_epoch = epoch

    def _call(self, route: str, body: dict) -> dict:
        last_err: Optional[PersisterError] = None
        n = len(self._urls)
        for attempt in range(n):
            idx = (self._cur + attempt) % n
            url = self._urls[idx]
            payload = dict(body)
            payload["_fence"] = self._max_epoch
            data = json.dumps(payload).encode("utf-8")
            req = urllib.request.Request(
                f"{url}{route}", data=data,
                headers=dict(self._headers), method="POST",
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=self._timeout_s,
                    context=self._ssl_ctx if url.startswith("https")
                    else None,
                ) as resp:
                    out = json.loads(resp.read().decode("utf-8"))
            except urllib.error.HTTPError as e:
                try:
                    detail = json.loads(e.read().decode("utf-8"))
                except Exception:
                    detail = {"error": str(e)}
                self._note_epoch(detail)
                if e.code == 503:
                    # standby/fenced server: rotate to find the primary
                    last_err = PersisterError(
                        f"{url}: {detail.get('error', 'not primary')}"
                    )
                    continue
                raise PersisterError(
                    detail.get("error", str(e)), detail.get("path", "")
                )
            except (urllib.error.URLError, OSError) as e:
                last_err = PersisterError(
                    f"state server unreachable: {url}: {e}"
                )
                continue
            epoch = int(out.get("epoch", 0) or 0)
            if epoch and epoch < self._max_epoch:
                # a stale primary's answers must never be trusted: a
                # newer epoch exists, so this server missed a failover
                last_err = PersisterError(
                    f"{url}: stale epoch {epoch} < {self._max_epoch}"
                )
                continue
            self._note_epoch(out)
            self._cur = idx
            return out
        raise last_err or PersisterError("no state servers configured")

    def get(self, path: str) -> Optional[bytes]:
        out = self._call("/v1/kv/get", {"path": path})
        if not out["found"]:
            raise PersisterError(f"path not found: {path}", path)
        value = out.get("value")
        return base64.b64decode(value) if value is not None else None

    def set(self, path: str, value: bytes) -> None:
        self._call(
            "/v1/kv/set",
            {"path": path, "value": base64.b64encode(value).decode()},
        )

    def get_children(self, path: str) -> List[str]:
        out = self._call("/v1/kv/children", {"path": path})
        if not out["found"]:
            raise PersisterError(f"path not found: {path}", path)
        return out["children"]

    def recursive_delete(self, path: str) -> None:
        if not self._call("/v1/kv/delete", {"path": path})["found"]:
            raise PersisterError(f"path not found: {path}", path)

    def apply(self, ops: Iterable[TransactionOp]) -> None:
        from dcos_commons_tpu.storage.replication import encode_ops

        self._call("/v1/kv/apply", {"ops": encode_ops(list(ops))})


class RemoteLocker:
    """Named TTL lease on the state server: the CuratorLocker analogue.

    ``acquire`` takes (or renews) the lease and starts a renewal thread
    at a third of the TTL; if the holder dies, the lease expires and a
    standby scheduler's next acquire succeeds — real failover, not a
    per-host file lock.

    Lease LOSS is fatal to the holder: if a renewal comes back
    ``acquired=false`` (someone else took the lease — we stalled past
    the TTL) or the server stays unreachable beyond the TTL, the
    renewal thread fires ``on_lost`` exactly once and stops.  The
    runner wires ``on_lost`` to crash the scheduler — the reference's
    CuratorLocker exits the process on ZK lock loss for the same
    reason: two active schedulers over one state tree corrupt plans.
    """

    def __init__(
        self,
        base_url: str,
        name: str,
        owner: str,
        ttl_s: float = 15.0,
        timeout_s: float = 5.0,
        auth_token: str = "",
        ca_file: str = "",
    ):
        self._persister = RemotePersister(
            base_url, timeout_s, auth_token=auth_token, ca_file=ca_file
        )
        self.name = name
        self.owner = owner
        self.ttl_s = ttl_s
        # callable(reason: str); set before or after acquire()
        self.on_lost = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _acquire_once(self) -> bool:
        out = self._persister._call(
            "/v1/lock/acquire",
            {"name": self.name, "owner": self.owner, "ttl_s": self.ttl_s},
        )
        return bool(out.get("acquired"))

    def acquire(self) -> bool:
        try:
            if not self._acquire_once():
                return False
        except PersisterError:
            return False
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._renew_loop, name=f"lease-{self.name}", daemon=True
        )
        self._thread.start()
        return True

    def _renew_loop(self) -> None:
        last_renewed = time.monotonic()
        while not self._stop.wait(self.ttl_s / 3.0):
            try:
                if self._acquire_once():
                    last_renewed = time.monotonic()
                    continue
                # someone else holds OUR lease: we stalled past the
                # TTL and a standby took over — we are no longer the
                # instance and must not keep mutating state
                self._lost("lease taken by another scheduler instance")
                return
            except PersisterError as e:
                # transient hiccups are survivable while the lease is
                # still live; once we cannot renew for a full TTL the
                # lease has lapsed server-side and a standby may hold
                # it — same outcome as above
                if time.monotonic() - last_renewed > self.ttl_s:
                    self._lost(f"state server unreachable past TTL: {e}")
                    return

    def _lost(self, reason: str) -> None:
        callback = self.on_lost
        if callback is not None:
            callback(reason)

    def release(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.ttl_s)
        try:
            self._persister._call(
                "/v1/lock/release", {"name": self.name, "owner": self.owner}
            )
        except PersisterError:
            pass  # lease will expire on its own


def main(argv: Optional[list] = None) -> int:
    """``python -m dcos_commons_tpu state-server`` — run the cluster
    state server over a durable file WAL."""
    import argparse

    from dcos_commons_tpu.storage.file_persister import FileWalPersister

    parser = argparse.ArgumentParser(prog="dcos_commons_tpu state-server")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--bind", default="127.0.0.1")
    parser.add_argument(
        "--advertise-host", default="",
        help="hostname/IP to announce instead of the bind address "
             "(required when binding 0.0.0.0 on a multi-host fleet)",
    )
    parser.add_argument("--data-dir", default="./state-server")
    parser.add_argument(
        "--announce-file", default="",
        help="write the URL here once listening (ephemeral ports)",
    )
    parser.add_argument(
        "--auth-token-file", default="",
        help="cluster bearer token file; also $AUTH_TOKEN(_FILE)",
    )
    parser.add_argument("--tls-cert", default="", help="serve HTTPS: cert PEM")
    parser.add_argument("--tls-key", default="", help="serve HTTPS: key PEM")
    parser.add_argument(
        "--ca-file", default="",
        help="CA bundle for talking to an HTTPS primary (standby mode)",
    )
    parser.add_argument(
        "--standby-of", default="",
        help="run as a hot standby replicating from this primary URL; "
             "promote with --promote when the primary dies",
    )
    parser.add_argument(
        "--sync-timeout-s", type=float, default=2.0,
        help="bounded-sync barrier: how long a write waits for the "
             "attached standby before marking it lagging",
    )
    parser.add_argument(
        "--promote", default="", metavar="STANDBY_URL",
        help="operator verb: promote the standby at this URL to "
             "primary (mints a new fencing epoch) and exit",
    )
    parser.add_argument(
        "--fence-old", default="", metavar="OLD_PRIMARY_URL",
        help="with --promote: also demote the old primary if it is "
             "still reachable (closes the partition window)",
    )
    parser.add_argument(
        "--repl-status", default="", metavar="SERVER_URL",
        help="operator verb: print the server's replication status "
             "(role, epoch, seq, per-standby acks) as JSON and exit — "
             "on a primary this is what to alert on; with several "
             "standbys, promote the one whose applied_seq is highest",
    )
    args = parser.parse_args(argv)
    from dcos_commons_tpu.security.auth import load_token

    token = load_token(token_file=args.auth_token_file)
    if args.repl_status:
        import sys

        try:
            out = RemotePersister(
                args.repl_status, timeout_s=5.0,
                auth_token=token, ca_file=args.ca_file,
            )._call("/v1/repl/status", {})
        except (PersisterError, ValueError) as e:
            # ValueError: a scheme-less URL ("host:port") from a
            # hand-typed command — an error message, not a traceback
            print(f"repl-status failed: {e}", file=sys.stderr)
            return 1
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    if args.promote:
        import sys

        client = RemotePersister(
            args.promote, auth_token=token, ca_file=args.ca_file
        )
        try:
            out = client._call("/v1/repl/promote", {})
        except (PersisterError, ValueError) as e:
            # ValueError: scheme-less URL — message, not traceback
            print(f"promote failed: {e}", file=sys.stderr)
            return 1
        epoch = out.get("epoch")
        print(f"promoted {args.promote} to primary at epoch {epoch}")
        if args.fence_old:
            try:
                out = RemotePersister(
                    args.fence_old, timeout_s=5.0,
                    auth_token=token, ca_file=args.ca_file,
                )._call("/v1/repl/fence", {"epoch": epoch})
                role = out.get("role")
                if role == ROLE_PRIMARY:
                    # fence token didn't demote it (epoch collision?):
                    # this is a split-brain hazard, say so loudly
                    print(
                        f"WARNING: {args.fence_old} still reports "
                        f"role=primary after fence at epoch {epoch} — "
                        "shut it down manually before serving traffic",
                        file=sys.stderr,
                    )
                    return 1
                print(f"fenced old primary {args.fence_old} (role={role})")
            except PersisterError as e:
                print(
                    f"old primary not fenced ({e}) — it will fence "
                    "itself on first client contact",
                    file=sys.stderr,
                )
        return 0
    if not token and args.bind not in ("127.0.0.1", "localhost", "::1"):
        import sys

        print(
            "WARNING: state server bound on a non-loopback address with NO "
            "auth token — anyone who can reach this port can clobber all "
            "cluster state. Pass --auth-token-file.",
            file=sys.stderr,
        )
    from dcos_commons_tpu.agent.daemon import _tls_pair_or_die

    server = StateServer(
        FileWalPersister(args.data_dir), port=args.port, bind=args.bind,
        auth_token=token,
        tls=_tls_pair_or_die(args.tls_cert, args.tls_key),
        advertise_host=args.advertise_host,
        replicate_from=args.standby_of,
        ca_file=args.ca_file,
        sync_timeout_s=args.sync_timeout_s,
    )
    if args.announce_file:
        from dcos_commons_tpu.common import atomic_write_text

        atomic_write_text(args.announce_file, server.url + "\n")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
