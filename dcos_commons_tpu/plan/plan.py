"""Plan: an ordered set of phases under one strategy.

Reference: scheduler/plan/Plan.java:23; deploy/update/recovery/
decommission/uninstall are all just Plans with well-known names
(offer/Constants.java).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set

from dcos_commons_tpu.common import TaskStatus
from dcos_commons_tpu.plan.element import Element
from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.status import Status, aggregate
from dcos_commons_tpu.plan.step import Step
from dcos_commons_tpu.plan.strategy import SerialStrategy, Strategy

DEPLOY_PLAN_NAME = "deploy"
UPDATE_PLAN_NAME = "update"
RECOVERY_PLAN_NAME = "recovery"
DECOMMISSION_PLAN_NAME = "decommission"
UNINSTALL_PLAN_NAME = "uninstall"


class Plan(Element):
    def __init__(self, name: str, phases: Sequence[Phase], strategy: Strategy = None):
        super().__init__(name)
        self.phases: List[Phase] = list(phases)
        self.strategy = strategy or SerialStrategy()

    def get_status(self) -> Status:
        if self.has_errors():
            return Status.ERROR
        return aggregate(
            (p.get_status() for p in self.phases),
            interrupted=self.strategy.is_interrupted(),
        )

    def candidates(self, dirty_assets: Set[str]) -> List[Step]:
        steps: List[Step] = []
        for phase in self.strategy.candidates(self.phases, dirty_assets):
            if isinstance(phase, Phase):
                steps.extend(phase.candidates(dirty_assets))
        return steps

    def update(self, status: TaskStatus) -> None:
        for phase in self.phases:
            phase.update(status)

    def interrupt(self) -> None:
        self.strategy.interrupt()

    def proceed(self) -> None:
        self.strategy.proceed()

    def is_interrupted(self) -> bool:
        return self.strategy.is_interrupted()

    def restart(self) -> None:
        for phase in self.phases:
            phase.restart()

    def set_env_overrides(self, env: dict) -> None:
        """Parameterized start: merge operator env into every step's
        launch requirement (reference: PlansQueries start-with-env).
        Sticky until the next parameterized start — re-running a
        backup plan without params reuses the previous target."""
        for phase in self.phases:
            for step in phase.steps:
                requirement = getattr(step, "requirement", None)
                if requirement is not None:
                    requirement.env_overrides = dict(env)

    def force_complete(self) -> None:
        for phase in self.phases:
            phase.force_complete()

    # lookup helpers (used by the HTTP API's plan verbs) -------------

    def phase(self, name_or_id: str) -> Optional[Phase]:
        for phase in self.phases:
            if name_or_id in (phase.name, phase.id):
                return phase
        return None

    def step(self, phase_name: str, step_name: str) -> Optional[Step]:
        phase = self.phase(phase_name)
        if phase is None:
            return None
        for step in phase.steps:
            if step_name in (step.name, step.id):
                return step
        return None

    def all_steps(self) -> List[Step]:
        return [s for p in self.phases for s in p.steps]
