"""Property-based tests for the straggler median-ratio scorer.

The scorer (health/detectors.py median_ratio_scores) is the math the
fleet health plane trusts to demote hosts in placement order, so its
contracts are pinned over generated inputs: permutation invariance
(scores depend on value multisets, never dict/list order), no alert
on a homogeneous fleet (every score is exactly 1.0), and a guaranteed
alert on a k-times outlier whenever k clears the threshold (the
fleet median excludes the outlier by construction at >= 3 hosts).
"""

import pytest

pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from dcos_commons_tpu.health.detectors import (  # noqa: E402
    StragglerDetector,
    median_ratio_scores,
)

# per-host step own-times in a realistic band (seconds); >= 3 samples
# so every generated host clears the scorer's min_samples gate
host_values = st.lists(
    st.floats(min_value=0.01, max_value=10.0,
              allow_nan=False, allow_infinity=False),
    min_size=3, max_size=16,
)
fleets = st.dictionaries(
    st.text(
        alphabet="abcdefgh0123456789-", min_size=1, max_size=12
    ).map(lambda s: f"host-{s}"),
    host_values,
    min_size=3, max_size=12,
)


@settings(max_examples=200, deadline=None)
@given(fleets, st.randoms())
def test_permutation_invariance(fleet, rnd):
    """Same multisets => same scores, whatever order hosts and values
    arrive in (steplog merge order is racy by nature)."""
    base = median_ratio_scores(fleet)
    hosts = list(fleet)
    rnd.shuffle(hosts)
    shuffled = {}
    for host in hosts:
        values = list(fleet[host])
        rnd.shuffle(values)
        shuffled[host] = values
    assert median_ratio_scores(shuffled) == base


@settings(max_examples=200, deadline=None)
@given(
    st.integers(min_value=3, max_value=12),
    st.floats(min_value=0.01, max_value=5.0,
              allow_nan=False, allow_infinity=False),
)
def test_homogeneous_fleet_never_alerts(n_hosts, step_s):
    """Every host identical => every score exactly 1.0; no threshold
    above 1 can fire."""
    fleet = {f"h{i}": [step_s] * 4 for i in range(n_hosts)}
    scores = median_ratio_scores(fleet)
    assert set(scores) == set(fleet)
    assert all(score == 1.0 for score in scores.values())
    detector = StragglerDetector(threshold=1.5)
    events = detector.observe({
        host: [{"wall_s": v, "blocked_s": 0.0} for v in values]
        for host, values in fleet.items()
    })
    assert events == [] and detector.suspects == {}


@settings(max_examples=200, deadline=None)
@given(
    st.integers(min_value=3, max_value=12),
    st.floats(min_value=0.05, max_value=2.0,
              allow_nan=False, allow_infinity=False),
    st.floats(min_value=2.5, max_value=20.0,
              allow_nan=False, allow_infinity=False),
)
def test_k_times_outlier_always_alerts(n_hosts, step_s, k):
    """One host at k x the homogeneous fleet scores exactly k (the
    fleet median is the healthy value at >= 3 hosts with one outlier
    ... n_hosts >= 3 means healthy hosts are the strict majority), so
    any threshold <= k fires, and only for that host."""
    fleet = {f"h{i}": [step_s] * 4 for i in range(n_hosts)}
    fleet["straggler"] = [step_s * k] * 4
    detector = StragglerDetector(threshold=2.0)
    events = detector.observe({
        host: [{"wall_s": v, "blocked_s": 0.0} for v in values]
        for host, values in fleet.items()
    })
    assert set(detector.suspects) == {"straggler"}
    assert len(events) == 1
    assert events[0]["host"] == "straggler"
    assert abs(detector.scores["straggler"] - k) < 1e-6
    # healthy hosts stay at exactly 1.0
    for i in range(n_hosts):
        assert detector.scores[f"h{i}"] == 1.0
