"""Multi-service mode: N services in one framework.

Reference: scheduler/multi/ — fan-out, namespaced state, footprint
discipline, dynamic add/remove over HTTP, restart resume from the
ServiceStore.
"""

import json
import urllib.error
import urllib.request

import pytest

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.http import ApiServer
from dcos_commons_tpu.multi import (
    MultiServiceScheduler,
    ParallelFootprintDiscipline,
)
from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
from dcos_commons_tpu.scheduler import SchedulerConfig
from dcos_commons_tpu.specification.yaml_spec import from_yaml
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing import FakeAgent


@pytest.fixture(autouse=True)
def _lock_order_checker():
    """Multi-service mode nests MultiServiceScheduler._lock over each
    per-service DefaultScheduler._lock; the lock-order checker fails
    the test if any cycle (deadlock risk) shows up in that graph."""
    from conftest import lockcheck_guard

    yield from lockcheck_guard()


def svc_yaml(name, count=1):
    return f"""
name: {name}
pods:
  app:
    count: {count}
    tasks:
      main:
        goal: RUNNING
        cmd: "serve-{name}"
        cpus: 0.1
        memory: 32
"""


def make_multi(persister=None, agent=None, discipline=None, hosts=3):
    return MultiServiceScheduler(
        persister=persister or MemPersister(),
        inventory=SliceInventory(
            [TpuHost(host_id=f"h{i}") for i in range(hosts)]
        ),
        agent=agent or FakeAgent(),
        scheduler_config=SchedulerConfig(backoff_enabled=False),
        discipline=discipline,
    )


def ack_running(multi, task_name):
    task_id = multi.agent.task_id_of(task_name)
    assert task_id, f"no launch for {task_name}"
    multi.agent.send(TaskStatus(task_id=task_id, state=TaskState.RUNNING,
                                ready=True))


def test_two_services_share_fleet_with_namespaced_state():
    multi = make_multi()
    multi.add_service(from_yaml(svc_yaml("alpha")))
    multi.add_service(from_yaml(svc_yaml("beta")))
    multi.run_cycle()
    ack_running(multi, "app-0-main")  # alpha's launch
    # both services deploy a pod named app-0-main — namespaced state
    # keeps them separate
    multi.run_cycle()
    alpha = multi.get_service("alpha")
    beta = multi.get_service("beta")
    for _ in range(4):
        for info in multi.agent.launched:
            multi.agent.send(TaskStatus(task_id=info.task_id,
                                        state=TaskState.RUNNING, ready=True))
        multi.run_cycle()
    assert alpha.deploy_manager.get_plan().is_complete
    assert beta.deploy_manager.get_plan().is_complete
    assert alpha.state_store.fetch_task("app-0-main") is not None
    assert beta.state_store.fetch_task("app-0-main") is not None
    assert "serve-alpha" in alpha.state_store.fetch_task("app-0-main").command
    assert "serve-beta" in beta.state_store.fetch_task("app-0-main").command
    # two separate launches despite identical task names
    assert len(multi.agent.launched) == 2


def test_footprint_discipline_serializes_growth():
    multi = make_multi(discipline=ParallelFootprintDiscipline(1))
    multi.add_service(from_yaml(svc_yaml("one")))
    multi.add_service(from_yaml(svc_yaml("two")))
    multi.run_cycle()
    # only ONE service may grow footprint: one launch so far
    assert len(multi.agent.launched) == 1
    first = multi.agent.launched[0]
    multi.agent.send(TaskStatus(task_id=first.task_id,
                                state=TaskState.RUNNING, ready=True))
    multi.run_cycle()  # first completes; slot frees
    multi.run_cycle()  # second service now grows
    assert len(multi.agent.launched) == 2


def test_remove_service_uninstalls_and_drops():
    multi = make_multi()
    multi.add_service(from_yaml(svc_yaml("gone")))
    multi.run_cycle()
    ack_running(multi, "app-0-main")
    multi.run_cycle()
    assert multi.get_service("gone").deploy_manager.get_plan().is_complete

    multi.uninstall_service("gone")
    for _ in range(5):
        multi.run_cycle()
    assert multi.service_names() == []
    assert "app-0-main" in multi.agent.killed_names()
    # namespace subtree wiped, framework id retained
    assert multi.persister.get_children_or_empty("/gone") == []
    assert multi.framework_store is not None


def test_restart_reloads_services_from_store():
    persister = MemPersister()
    agent = FakeAgent()
    multi = make_multi(persister=persister, agent=agent)
    multi.add_service(from_yaml(svc_yaml("keep")))
    multi.run_cycle()
    ack_running(multi, "app-0-main")
    multi.run_cycle()

    # new process over the same persister: service comes back, resumed
    reborn = make_multi(persister=persister, agent=agent)
    assert reborn.service_names() == ["keep"]
    reborn.run_cycle()
    service = reborn.get_service("keep")
    assert service.deploy_manager.get_plan().is_complete
    # no duplicate launch on resume
    assert len(agent.launched) == 1


def test_multi_http_surface():
    multi = make_multi()
    server = ApiServer(multi=multi).start()
    try:
        def get(path):
            with urllib.request.urlopen(server.url + path) as resp:
                return json.loads(resp.read().decode())

        def send(method, path, data=None):
            req = urllib.request.Request(
                server.url + path, method=method,
                data=data.encode() if data else b"",
            )
            with urllib.request.urlopen(req) as resp:
                return json.loads(resp.read().decode())

        assert get("/v1/multi") == []
        send("PUT", "/v1/multi/websvc", svc_yaml("websvc"))
        assert get("/v1/multi") == ["websvc"]
        multi.run_cycle()
        ack_running(multi, "app-0-main")
        multi.run_cycle()
        # per-service routing: plans + pod status through /v1/multi
        plan = get("/v1/multi/websvc/v1/plans/deploy")
        assert plan["status"] == "COMPLETE"
        pods = get("/v1/multi/websvc/v1/pod")
        assert pods == ["app-0"]
        send("DELETE", "/v1/multi/websvc")
        for _ in range(5):
            multi.run_cycle()
        assert get("/v1/multi") == []
    finally:
        server.stop()


def test_second_service_launch_does_not_kill_first():
    """Regression: _kill_previous_launches must kill by the task id in
    THIS service's state store, not by an agent-wide name scan — beta
    launching app-0-main must not kill alpha's running app-0-main."""
    multi = make_multi()
    multi.add_service(from_yaml(svc_yaml("alpha")))
    multi.run_cycle()
    ack_running(multi, "app-0-main")
    multi.run_cycle()
    alpha = multi.get_service("alpha")
    assert alpha.deploy_manager.get_plan().is_complete
    alpha_id = alpha.state_store.fetch_task("app-0-main").task_id

    multi.add_service(from_yaml(svc_yaml("beta")))
    for _ in range(4):
        multi.run_cycle()
        for info in multi.agent.launched:
            if info.task_id in multi.agent.active_task_ids():
                multi.agent.send(TaskStatus(task_id=info.task_id,
                                            state=TaskState.RUNNING,
                                            ready=True))
    assert alpha_id not in multi.agent.kills
    assert alpha_id in multi.agent.active_task_ids()
    assert alpha.deploy_manager.get_plan().is_complete
    assert multi.get_service("beta").deploy_manager.get_plan().is_complete


def test_uninstall_one_service_spares_others():
    """Regression: a namespaced uninstall must only kill task ids its
    own state store owns, never sweep the shared agent's full set."""
    multi = make_multi()
    multi.add_service(from_yaml(svc_yaml("keep")))
    multi.add_service(from_yaml(svc_yaml("gone")))
    for _ in range(4):
        multi.run_cycle()
        for info in multi.agent.launched:
            if info.task_id in multi.agent.active_task_ids():
                multi.agent.send(TaskStatus(task_id=info.task_id,
                                            state=TaskState.RUNNING,
                                            ready=True))
    keep = multi.get_service("keep")
    assert keep.deploy_manager.get_plan().is_complete
    keep_id = keep.state_store.fetch_task("app-0-main").task_id
    gone_id = multi.get_service("gone").state_store.fetch_task(
        "app-0-main").task_id

    multi.uninstall_service("gone")
    for _ in range(5):
        multi.run_cycle()
    assert multi.service_names() == ["keep"]
    assert gone_id in multi.agent.kills
    assert keep_id not in multi.agent.kills
    assert keep_id in multi.agent.active_task_ids()


def test_orphan_index_equivalent_to_full_scan():
    """The incremental orphan index (ISSUE 13 satellite, PR 9
    remainder) must match the full O(services x tasks) scan after
    EVERY mutation kind: launches, status-driven kills, task
    erasure, service add and uninstall."""
    import random

    multi = make_multi(hosts=6)
    rng = random.Random(7)
    multi.add_service(from_yaml(svc_yaml("alpha", count=2)))
    multi.add_service(from_yaml(svc_yaml("beta", count=2)))

    def full_scan():
        return {
            info.task_id
            for service in multi.services().values()
            for info in service.state_store.fetch_tasks()
        }

    def check():
        services = multi.services()
        incremental = multi._expected_task_ids(services)
        assert incremental == full_scan()
        # second read is the cached path — must agree too
        assert multi._expected_task_ids(services) == full_scan()

    check()
    for _ in range(30):
        op = rng.choice(["cycle", "ack", "fail", "clear"])
        if op == "cycle":
            multi.run_cycle()
        elif op == "ack":
            launched = list(multi.agent.launched)
            if launched:
                info = rng.choice(launched)
                multi.agent.send(TaskStatus(
                    task_id=info.task_id, state=TaskState.RUNNING,
                    ready=True))
                multi.run_cycle()
        elif op == "fail":
            launched = list(multi.agent.launched)
            if launched:
                info = rng.choice(launched)
                multi.agent.send(TaskStatus(
                    task_id=info.task_id, state=TaskState.FAILED))
                multi.run_cycle()
        elif op == "clear":
            service = multi.get_service(rng.choice(["alpha", "beta"]))
            names = service.state_store.fetch_task_names()
            if names:
                service.state_store.clear_task(rng.choice(names))
        check()
    # service teardown drops its ids from the union and the index
    multi.uninstall_service("beta")
    deadline = 40
    while "beta" in multi.services() and deadline:
        multi.run_cycle()
        check()
        deadline -= 1
    assert "beta" not in multi.services(), "uninstall never finished"
    multi._expected_task_ids(multi.services())  # prunes removed services
    assert "beta" not in multi._orphan_index
