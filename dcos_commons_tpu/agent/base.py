"""Agent contract between the scheduler and host-local executors."""

from __future__ import annotations

from typing import List, Set

from dcos_commons_tpu.common import TaskInfo, TaskStatus


class Agent:
    """What the scheduler needs from the thing that runs tasks.

    Reference analogues: launch = OfferAccepter LAUNCH operations,
    kill = TaskKiller -> driver.killTask, active_task_ids = the task
    reconciliation query (ImplicitReconciler / ExplicitReconciler).
    """

    # True when launch payloads cross a network (per-host daemons):
    # security validators demand an authed channel only then — a
    # local/sim agent writes cert material straight to disk
    is_remote = False

    def launch(self, task_infos: List[TaskInfo]) -> None:
        """Start the given tasks.  Must be idempotent per task_id."""
        raise NotImplementedError

    def kill(self, task_id: str, grace_period_s: float = 0.0) -> None:
        """Request termination; a terminal TaskStatus must follow."""
        raise NotImplementedError

    def active_task_ids(self) -> Set[str]:
        """Task ids currently known (running or starting) — the
        reconciliation source of truth."""
        raise NotImplementedError

    def poll(self) -> List[TaskStatus]:
        """Drain pending status transitions (RUNNING, FINISHED, ...)."""
        raise NotImplementedError
