"""Certificate authority: per-task TLS artifact issuance.

Reference: dcos/clients/CertificateAuthorityClient.java (CSR signing
against the DC/OS CA) consumed by offer/evaluate/TLSEvaluationStage
(cert + key + keystore artifacts placed in the task).  TPU-first: the
scheduler owns a CA (root key generated once and persisted via the
Persister, so scheduler restarts keep issuing from the same root) and
stamps each transport-encryption task with cert/key/ca PEMs delivered
as 0600 sandbox files.
"""

from __future__ import annotations

import datetime
from typing import List, Optional, Tuple

CA_KEY_PATH = "/security/ca/key.pem"
CA_CERT_PATH = "/security/ca/cert.pem"


class CertificateAuthority:
    def __init__(self, ca_key_pem: bytes, ca_cert_pem: bytes):
        self._key_pem = ca_key_pem
        self._cert_pem = ca_cert_pem

    # -- construction -------------------------------------------------

    @staticmethod
    def create(common_name: str = "dcos-commons-tpu CA") -> "CertificateAuthority":
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID

        key = ec.generate_private_key(ec.SECP256R1())
        name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
        )
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (
            x509.CertificateBuilder()
            .subject_name(name)
            .issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=3650))
            .add_extension(
                x509.BasicConstraints(ca=True, path_length=0), critical=True
            )
            .sign(key, hashes.SHA256())
        )
        return CertificateAuthority(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            ),
            cert.public_bytes(serialization.Encoding.PEM),
        )

    @staticmethod
    def load_or_create(persister) -> "CertificateAuthority":
        """Root key/cert persisted alongside scheduler state so
        restarts keep the same trust root."""
        key = persister.get_or_none(CA_KEY_PATH)
        cert = persister.get_or_none(CA_CERT_PATH)
        if key and cert:
            return CertificateAuthority(key, cert)
        ca = CertificateAuthority.create()
        persister.apply([
            _set(CA_KEY_PATH, ca._key_pem),
            _set(CA_CERT_PATH, ca._cert_pem),
        ])
        return ca

    @property
    def ca_cert_pem(self) -> bytes:
        return self._cert_pem

    # -- issuance -----------------------------------------------------

    def issue(
        self,
        common_name: str,
        sans: Optional[List[str]] = None,
        days: int = 825,
    ) -> Tuple[bytes, bytes]:
        """(cert_pem, key_pem) for one task endpoint, signed by the CA.

        Reference: TLSEvaluationStage builds CSR with the task's DNS
        names as SANs; here the scheduler passes the task name +
        hostname."""
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID

        ca_key = serialization.load_pem_private_key(self._key_pem, None)
        ca_cert = x509.load_pem_x509_certificate(self._cert_pem)
        key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (
            x509.CertificateBuilder()
            .subject_name(x509.Name(
                [x509.NameAttribute(NameOID.COMMON_NAME, common_name[:64])]
            ))
            .issuer_name(ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(
                x509.BasicConstraints(ca=False, path_length=None),
                critical=True,
            )
        )
        import ipaddress

        alt_names: list = []
        for n in (sans or []):
            if not n:
                continue
            try:
                # IP literals must land in iPAddress SANs or client
                # hostname verification of e.g. https://127.0.0.1 fails
                alt_names.append(x509.IPAddress(ipaddress.ip_address(n)))
            except ValueError:
                alt_names.append(x509.DNSName(n))
        if alt_names:
            builder = builder.add_extension(
                x509.SubjectAlternativeName(alt_names), critical=False
            )
        cert = builder.sign(ca_key, hashes.SHA256())
        return (
            cert.public_bytes(serialization.Encoding.PEM),
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            ),
        )


def _set(path: str, value: bytes):
    from dcos_commons_tpu.storage.persister import SetOp

    return SetOp(path, value)
