"""Process entrypoints for the framework.

    python -m dcos_commons_tpu serve svc.yml --topology cluster.yml
    python -m dcos_commons_tpu agent --host-id h0 --workdir ./sandbox
    python -m dcos_commons_tpu cli  <verb> ...
    python -m dcos_commons_tpu state-server --data-dir ./cluster-state
    python -m dcos_commons_tpu analyze            # static analysis: lint+specs+spmd+plan+shard

Reference: the pair of process mains the reference ships — the
scheduler process (SchedulerRunner.java:82 via each framework's
Main.java) and the task-side bootstrap (sdk/bootstrap/main.go:466) —
plus the operator CLI binary (sdk/cli/main.go:1-12).
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv else 1
    command, rest = argv[0], argv[1:]
    if command == "serve":
        from dcos_commons_tpu.runtime.runner import serve_main

        return serve_main(rest)
    if command == "agent":
        from dcos_commons_tpu.agent.daemon import main as agent_main

        return agent_main(rest)
    if command == "cli":
        from dcos_commons_tpu.cli.commands import main as cli_main

        return cli_main(rest)
    if command == "state-server":
        from dcos_commons_tpu.storage.remote import main as state_main

        return state_main(rest)
    if command == "package":
        from dcos_commons_tpu.tools.packaging import main as package_main

        return package_main(rest)
    if command == "certs":
        from dcos_commons_tpu.security.auth import certs_main

        return certs_main(rest)
    if command in ("analyze", "lint"):
        # sdklint: framework lint + spec analyzer + spmdcheck +
        # plancheck + shardcheck (same entry point as
        # `python -m dcos_commons_tpu.analysis`); `analyze` with no
        # arguments runs everything
        from dcos_commons_tpu.analysis.__main__ import main as analysis_main

        return analysis_main(rest)
    print(
        f"unknown command {command!r}; "
        "try serve | agent | cli | state-server | package | certs "
        "| analyze",
        file=sys.stderr,
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
