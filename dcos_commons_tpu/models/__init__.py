"""Workload model zoo for frameworks/jax.

The reference SDK has no data plane (SURVEY.md: "the workloads are
whatever the service YAML launches"); these are the flagship workloads
the TPU rebuild ships so a user can stand up real training pods:

- transformer.py  decoder-only LM, pure-JAX pytrees, scan-over-layers,
                  bf16 compute, RoPE + GQA + SwiGLU, pallas kernels,
                  dp/fsdp/tp/sp shardings for pjit + pp pipeline trunk
- moe.py          mixture-of-experts FFN, einsum dispatch, ep-parallel
                  all_to_all expert exchange
- mlp.py          MNIST-scale MLP (the BASELINE.json config-3 demo)
"""

from dcos_commons_tpu.models.transformer import (
    TransformerConfig,
    config_from_env,
    init_params,
    loss_fn,
    make_train_step,
    forward,
    pipeline_forward,
    pipeline_loss_fn,
    pipeline_param_specs,
)
from dcos_commons_tpu.models.decode import (
    decode_step,
    generate,
    init_kv_cache,
    prefill,
    prefill_into_slot,
    sample_token,
)
from dcos_commons_tpu.models.moe import (
    MoEConfig,
    expert_shard_spec,
    init_moe_params,
    moe_ffn,
    moe_sharding_rules,
)
from dcos_commons_tpu.models.mlp import MlpConfig, mlp_forward, mlp_init, mlp_train_step
from dcos_commons_tpu.models.quantize import (
    dequantize_weight,
    quantize_params_int8,
)

__all__ = [
    "MlpConfig",
    "MoEConfig",
    "TransformerConfig",
    "config_from_env",
    "decode_step",
    "dequantize_weight",
    "expert_shard_spec",
    "forward",
    "generate",
    "init_kv_cache",
    "init_moe_params",
    "init_params",
    "prefill",
    "prefill_into_slot",
    "sample_token",
    "loss_fn",
    "make_train_step",
    "mlp_forward",
    "mlp_init",
    "mlp_train_step",
    "moe_ffn",
    "moe_sharding_rules",
    "pipeline_forward",
    "pipeline_loss_fn",
    "pipeline_param_specs",
    "quantize_params_int8",
]
