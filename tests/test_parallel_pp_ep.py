"""Pipeline (pp) and expert (ep) parallelism on the virtual CPU mesh.

Both are compared against their single-device oracles: pipelining and
expert dispatch are pure re-schedulings of the same math, so the
outputs must agree to float tolerance.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from dcos_commons_tpu.parallel.compat import shard_map
from jax.sharding import PartitionSpec as P

from dcos_commons_tpu.models import (
    MoEConfig,
    TransformerConfig,
    init_moe_params,
    init_params,
    loss_fn,
    moe_ffn,
    pipeline_loss_fn,
    pipeline_param_specs,
)
from dcos_commons_tpu.parallel.mesh import MeshSpec, make_mesh
from dcos_commons_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_apply,
    split_microbatches,
)

CONFIG = TransformerConfig(
    vocab=128, d_model=64, n_layers=4, n_heads=4, n_kv_heads=4,
    d_ff=128, max_seq=32, dtype=jnp.float32, remat=False,
)


# -- pipeline ---------------------------------------------------------


def test_split_merge_microbatches_roundtrip():
    x = jnp.arange(24.0).reshape(8, 3)
    micro = split_microbatches(x, 4)
    assert micro.shape == (4, 2, 3)
    np.testing.assert_array_equal(np.asarray(merge_microbatches(micro)),
                                  np.asarray(x))
    with pytest.raises(ValueError):
        split_microbatches(x, 3)


def test_pipeline_apply_matches_sequential():
    """4-stage toy pipeline == sequential layer application."""
    mesh = make_mesh(MeshSpec(pp=4))
    key = jax.random.key(0)
    d = 16
    w = jax.random.normal(key, (4, d, d), jnp.float32) * 0.3
    x = jax.random.normal(jax.random.key(1), (8, d), jnp.float32)

    def stage_fn(w_local, x):
        def layer(x, wi):
            return jnp.tanh(x @ wi), None

        x, _ = jax.lax.scan(layer, x, w_local)
        return x

    # oracle: all four layers sequentially
    oracle = stage_fn(w, x)

    micro = split_microbatches(x, 4)
    with mesh:
        from dcos_commons_tpu.parallel.pipeline import last_stage_value

        def run(w, micro):
            out = pipeline_apply(stage_fn, w, micro, "pp")
            return last_stage_value(out, "pp")

        out = jax.jit(
            shard_map(run, mesh=mesh, in_specs=(P("pp"), P()),
                      out_specs=P(), check_vma=False)
        )(w, micro)
    np.testing.assert_allclose(
        np.asarray(merge_microbatches(out)), np.asarray(oracle),
        atol=1e-5, rtol=1e-5,
    )


def test_pipeline_transformer_loss_matches_dense():
    """pp=4 pipelined flagship trunk == plain forward, incl. grads."""
    mesh = make_mesh(MeshSpec(pp=4))
    params = init_params(CONFIG, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 32), 0, CONFIG.vocab)
    targets = jax.random.randint(jax.random.key(2), (8, 32), 0, CONFIG.vocab)
    oracle = loss_fn(CONFIG, params, tokens, targets)

    piped = shard_map(
        functools.partial(pipeline_loss_fn, CONFIG, n_micro=4, axis_name="pp"),
        mesh=mesh,
        in_specs=(pipeline_param_specs(params), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    with mesh:
        loss, grads = jax.jit(jax.value_and_grad(
            lambda p: piped(p, tokens, targets)
        ))(params)
    np.testing.assert_allclose(float(loss), float(oracle), atol=1e-4, rtol=1e-4)
    # gradients must match the dense ones (backward pipeline correct)
    dense_grads = jax.grad(
        lambda p: loss_fn(CONFIG, p, tokens, targets)
    )(params)
    flat, _ = jax.tree.flatten(grads)
    dflat, _ = jax.tree.flatten(dense_grads)
    for g, dg in zip(flat, dflat):
        np.testing.assert_allclose(np.asarray(g), np.asarray(dg),
                                   atol=5e-4, rtol=5e-4)


# -- mixture of experts ----------------------------------------------


MOE = MoEConfig(d_model=32, d_ff=64, n_experts=8, top_k=2,
                capacity_factor=8.0, dtype=jnp.float32)


def test_moe_dense_forward_finite_and_trains():
    params = init_moe_params(MOE, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    y, aux = moe_ffn(MOE, params, x)
    assert y.shape == x.shape
    assert jnp.isfinite(aux)
    # gradient flows through routing + experts
    def loss(p):
        out, aux = moe_ffn(MOE, p, x)
        return (out ** 2).mean() + 0.01 * aux

    grads = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(grads):
        assert bool(jnp.all(jnp.isfinite(leaf)))


def test_moe_capacity_drops_tokens():
    """Tiny capacity must zero out overflow tokens, not crash."""
    tight = MoEConfig(d_model=32, d_ff=64, n_experts=8, top_k=1,
                      capacity_factor=0.25, dtype=jnp.float32)
    params = init_moe_params(tight, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    y, _ = moe_ffn(tight, params, x)
    assert y.shape == x.shape
    assert bool(jnp.all(jnp.isfinite(y)))


def test_moe_ep_sharded_matches_dense():
    """ep=8: expert-parallel all_to_all path == single-device path.

    Capacity is per-rank in the sharded path, so use a generous
    capacity_factor and per-rank token counts that never overflow —
    then routing decisions are token-local and results must agree.
    """
    mesh = make_mesh(MeshSpec(ep=8))
    params = init_moe_params(MOE, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    y_dense, _ = moe_ffn(MOE, params, x)

    from dcos_commons_tpu.models import expert_shard_spec

    sharded = shard_map(
        functools.partial(moe_ffn, MOE, axis_name="ep"),
        mesh=mesh,
        in_specs=(expert_shard_spec(), P("ep")),
        out_specs=(P("ep"), P()),
        check_vma=False,
    )
    with mesh:
        y_ep, aux = jax.jit(sharded)(params, x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_dense),
                               atol=1e-5, rtol=1e-5)
    assert jnp.isfinite(aux)


def test_moe_ep_gradients_finite():
    mesh = make_mesh(MeshSpec(ep=4))
    config = MoEConfig(d_model=32, d_ff=64, n_experts=8, top_k=2,
                       capacity_factor=4.0, dtype=jnp.float32)
    params = init_moe_params(config, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (32, 32), jnp.float32)

    from dcos_commons_tpu.models import expert_shard_spec

    def body(p, x):
        y, aux = moe_ffn(config, p, x, axis_name="ep")
        return jax.lax.pmean((y ** 2).mean(), "ep") + 0.01 * aux

    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(expert_shard_spec(), P("ep")),
        out_specs=P(), check_vma=False,
    )
    with mesh:
        grads = jax.jit(jax.grad(lambda p: sharded(p, x)))(params)
    for leaf in jax.tree.leaves(grads):
        assert bool(jnp.all(jnp.isfinite(leaf)))


def test_moe_sorted_dispatch_matches_onehot_dropfree():
    """impl="sorted" (argsort + row gather/scatter) == impl="onehot"
    in the drop-free regime — same routing decisions, same gates, no
    [t,E,C] tensors.  f32 on CPU, so agreement is tight."""
    params = init_moe_params(MOE, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    y_ref, aux_ref = moe_ffn(MOE, params, x)
    y_sorted, aux_sorted = moe_ffn(MOE, params, x, impl="sorted")
    np.testing.assert_allclose(
        np.asarray(y_sorted), np.asarray(y_ref), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_allclose(
        float(aux_sorted), float(aux_ref), atol=1e-6
    )
    # gradients flow and agree
    def loss(p, impl):
        out, aux = moe_ffn(MOE, p, x, impl=impl)
        return (out ** 2).mean() + 0.01 * aux

    g_ref = jax.grad(lambda p: loss(p, "onehot"))(params)
    g_sorted = jax.grad(lambda p: loss(p, "sorted"))(params)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_sorted)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-5, rtol=1e-4
        )


def test_moe_sorted_capacity_drop_priority_matches_onehot():
    """Under capacity pressure both impls drop the SAME entries: every
    token's 1st choice outranks any token's 2nd choice (choice-major
    priority), ties broken by token order."""
    tight = MoEConfig(d_model=32, d_ff=64, n_experts=4, top_k=2,
                      capacity_factor=0.5, dtype=jnp.float32)
    params = init_moe_params(tight, jax.random.key(0))
    x = jax.random.normal(jax.random.key(3), (64, 32), jnp.float32)
    y_ref, _ = moe_ffn(tight, params, x)
    y_sorted, _ = moe_ffn(tight, params, x, impl="sorted")
    np.testing.assert_allclose(
        np.asarray(y_sorted), np.asarray(y_ref), atol=1e-5, rtol=1e-5
    )


def test_moe_sorted_ep_sharded_matches_dense():
    """The sorted dispatch composes with expert parallelism: the same
    all_to_all wire pattern around the gather/scatter."""
    mesh = make_mesh(MeshSpec(ep=8))
    params = init_moe_params(MOE, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    y_dense, _ = moe_ffn(MOE, params, x)

    from dcos_commons_tpu.models import expert_shard_spec

    sharded = shard_map(
        functools.partial(moe_ffn, MOE, axis_name="ep", impl="sorted"),
        mesh=mesh,
        in_specs=(expert_shard_spec(), P("ep")),
        out_specs=(P("ep"), P()),
        check_vma=False,
    )
    with mesh:
        y_ep, aux = jax.jit(sharded)(params, x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_dense),
                               atol=1e-5, rtol=1e-5)
    assert jnp.isfinite(aux)


def test_moe_flagship_impl_knob_equivalence():
    """TransformerConfig.moe_impl flips the whole model's dispatch;
    drop-free forwards agree."""
    from dcos_commons_tpu.models import TransformerConfig, forward, init_params

    base = dict(
        vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq=32, dtype=jnp.float32, remat=False,
        n_experts=4, moe_top_k=2, moe_capacity_factor=4.0,
    )
    cfg_a = TransformerConfig(**base, moe_impl="onehot")
    cfg_b = TransformerConfig(**base, moe_impl="sorted")
    params = init_params(cfg_a, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, 64)
    np.testing.assert_allclose(
        np.asarray(forward(cfg_b, params, tokens)),
        np.asarray(forward(cfg_a, params, tokens)),
        atol=1e-5, rtol=1e-5,
    )
