"""The HTTP front door: RequestRouter behind real sockets.

Wires the transport-free ``RequestRouter`` (router/core.py) to the
world:

* **discovery** — polls the scheduler's ``GET /v1/endpoints/<name>``
  (the reference's EndpointsResource/VIP surface) for the serve
  pods' live addresses.  The response carries a ``generation`` stamp
  (ledger + task-store mutation counters, http/api.py): an unchanged
  generation costs one compare and NO pod-set rebuild — the PR 9
  quiet-fleet discipline applied to discovery.  Backends arrive with
  their scheduler-side state, so a pod entering pause/decommission
  flips to draining here without waiting for its /stats to go dark.
* **stats polling** — each pod's ``GET /stats`` feeds the router's
  staleness-gated telemetry; an unreachable pod simply stops
  refreshing and ages out (router/telemetry.py), it is never scored
  on last-good numbers.
* **the client surface** — ``POST /generate`` routes one request
  (pod errors pass through with their original status; pod deaths
  fail over under the retry budget and 502 only when it is
  exhausted; an empty pod set is 503).  ``GET /stats`` serves the
  router's own watcher-compatible gauges, ``GET /pods`` the per-pod
  debug rows, and ``POST /drain?pod=`` / ``POST /undrain?pod=`` the
  drain runbook's verbs.

The router's gauges mirror to ``servestats.json`` in the sandbox on
the poll cadence, so a router task feeds the scheduler's
/v1/debug/serving, /v1/debug/router, and the ServingSloWatcher
through the exact plumbing serve pods already use.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from dcos_commons_tpu.router.core import (
    ROUTERSTATS_NAME,
    NoPodAvailableError,
    PodTransportError,
    RequestRouter,
)
from dcos_commons_tpu.serve.migration import SessionMigratedError


class PodHttpError(RuntimeError):
    """The pod ANSWERED with an HTTP error — an application verdict,
    passed through to the client verbatim, never retried."""

    def __init__(self, code: int, body: bytes):
        super().__init__(f"pod answered {code}")
        self.code = code
        self.body = body


def http_send(name: str, address: str, request: dict,
              timeout_s: float = 630.0) -> list:
    """POST /generate to one pod.  Connection-level failures raise
    ``PodTransportError`` (no response was produced: safe to fail
    over); HTTP error responses raise ``PodHttpError`` (the pod's
    verdict: pass through).  ``timeout_s`` must sit STRICTLY above
    the pods' SERVE_QUEUE_TIMEOUT_S: a saturated pod answers 503 at
    that mark, and the socket timer firing first would misread
    saturation as pod death (failover storm under load)."""
    payload = json.dumps(request).encode("utf-8")
    req = urllib.request.Request(
        f"http://{address}/generate", data=payload,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            body = json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        raw = e.read()
        if e.code == 409:
            # the pod moved the session mid-flight (serve/migration.py):
            # 409 {"migrated_to", "dest_rid"} tells the router WHERE to
            # collect the finished tokens — a redirect, not a failure
            try:
                verdict = json.loads(raw.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                verdict = {}
            moved_to = verdict.get("migrated_to")
            if isinstance(moved_to, str) and moved_to:
                raise SessionMigratedError(
                    int(verdict.get("rid", -1)), moved_to,
                    int(verdict.get("dest_rid", -1)),
                ) from e
        raise PodHttpError(e.code, raw) from e
    except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
        raise PodTransportError(f"{name} ({address}): {e}") from e
    tokens = body.get("tokens")
    if not isinstance(tokens, list):
        raise PodTransportError(f"{name} returned a bodiless reply")
    return tokens


def migrate_drain(router: RequestRouter, pod: str, dest: str,
                  timeout_s: float = 120.0) -> dict:
    """Drive the cache-preserving half of ``/drain?pod=X&to=Y``: ask
    the SOURCE pod to migrate its live sessions to ``dest`` (the serve
    worker's one-shot drain verb, serve/migration.py).  Best-effort by
    design — any failure leaves the legacy wait-out drain in charge
    and is reported, never raised (the drain itself already took)."""
    state = router.describe()["pods"]
    src_row, dest_row = state.get(pod), state.get(dest)
    if src_row is None or dest_row is None:
        return {"error": f"unknown pod {pod if src_row is None else dest}"}
    payload = json.dumps({
        "verb": "drain", "dests": {dest: dest_row["address"]},
    }).encode("utf-8")
    req = urllib.request.Request(
        f"http://{src_row['address']}/migrate", data=payload,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except (urllib.error.URLError, OSError, ValueError) as e:
        return {"error": f"migration drain failed: {e}"}


def fetch_endpoint(scheduler_url: str, endpoint: str,
                   timeout_s: float = 5.0,
                   auth_token: str = "") -> dict:
    """One discovery poll: the scheduler's endpoint body ({name,
    address, generation, backends})."""
    from dcos_commons_tpu.security import auth as _auth

    req = urllib.request.Request(
        f"{scheduler_url}/v1/endpoints/{endpoint}",
        headers=_auth.auth_headers(auth_token),
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def fetch_pod_stats(address: str, timeout_s: float = 5.0) -> dict:
    with urllib.request.urlopen(
        f"http://{address}/stats", timeout=timeout_s
    ) as resp:
        body = json.loads(resp.read().decode("utf-8"))
    return body if isinstance(body, dict) else {}


class RouterServer:
    """The deployable front door: discovery + stats poll loop + the
    client HTTP surface over one ``RequestRouter``."""

    def __init__(
        self,
        scheduler_url: str,
        endpoint: str = "vip:inference",
        port: int = 0,
        host: str = "0.0.0.0",
        poll_interval_s: float = 1.0,
        stats_path: Optional[str] = None,
        auth_token: str = "",
        request_timeout_s: float = 630.0,
        discover: Optional[Callable[[], dict]] = None,
        pod_stats: Optional[Callable[[str], dict]] = None,
        log: Optional[Callable[[str], None]] = print,
        **router_kw,
    ):
        self._scheduler_url = scheduler_url.rstrip("/")
        self._endpoint = endpoint
        self._poll_interval_s = float(poll_interval_s)
        self._stats_path = stats_path
        self._auth_token = auth_token
        self._log = log
        self._discover = discover or (lambda: fetch_endpoint(
            self._scheduler_url, self._endpoint,
            auth_token=self._auth_token,
        ))
        self._pod_stats = pod_stats or fetch_pod_stats
        self.router = RequestRouter(
            send=lambda name, address, request: http_send(
                name, address, request, timeout_s=request_timeout_s,
            ),
            log=log,
            **router_kw,
        )
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        # set by refresh_once from BOTH the starting caller and the
        # router-poll thread; an Event gives the flip a memory fence
        # instead of relying on a benign torn bool
        self._refreshed = threading.Event()
        router = self.router

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code: int, body) -> None:
                payload = body if isinstance(body, bytes) else \
                    json.dumps(body).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/stats":
                    self._reply(200, router.stats())
                elif path == "/pods":
                    self._reply(200, router.describe())
                elif path == "/rebalance":
                    # advisory only: the operator (or remediation)
                    # reads the suggestion and drives the migration
                    self._reply(200, {
                        "suggestion": router.rebalance_suggestion(),
                    })
                else:
                    self._reply(404, {"error": f"no route {path}"})

            def do_POST(self):
                from urllib.parse import parse_qs, urlparse

                parsed = urlparse(self.path)
                if parsed.path in ("/drain", "/undrain"):
                    query = parse_qs(parsed.query)
                    pod = (query.get("pod") or [""])[0]
                    body = {"pod": pod,
                            "draining": parsed.path == "/drain"}
                    if parsed.path == "/drain":
                        # ?to= names the migration destination: the
                        # pod's live sessions move there WITH their
                        # pages (the worker's drain verb) and its
                        # chain claims re-point instead of being
                        # dropped (cache-preserving drain)
                        dest = (query.get("to") or [""])[0]
                        ok = router.drain(pod, migrated_to=dest or None)
                        if ok and dest:
                            body["report"] = migrate_drain(
                                router, pod, dest
                            )
                    else:
                        ok = router.undrain(pod)
                    if ok:
                        self._reply(200, body)
                    else:
                        self._reply(404, {"error": f"no pod {pod}"})
                    return
                if parsed.path != "/generate":
                    self._reply(404, {"error": f"no route {parsed.path}"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length))
                    rows = body["tokens"]
                    if not isinstance(rows, list) or not rows:
                        raise ValueError("tokens must be non-empty")
                    # each row routes independently: sibling rows of
                    # one request may land on DIFFERENT pods (the
                    # router's unit of placement is the row/session)
                    out = [
                        router.submit(
                            row,
                            int(body.get("max_new_tokens", 32)),
                            temperature=float(
                                body.get("temperature", 0.0)
                            ),
                            eos=body.get("eos"),
                        )
                        for row in rows
                    ]
                    self._reply(200, {"tokens": out})
                except PodHttpError as e:
                    self._reply(e.code, e.body)  # the pod's verdict
                except NoPodAvailableError as e:
                    self._reply(503, {"error": str(e)})
                except PodTransportError as e:
                    self._reply(502, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — caller error
                    self._reply(400, {"error": str(e)})

        try:
            self._server = ThreadingHTTPServer((host, port), Handler)
        except OSError:
            # assigned port taken on a shared machine: bind ephemeral
            # and ADVERTISE it (the endpoints `advertise: true` flow)
            self._server = ThreadingHTTPServer((host, 0), Handler)
            if log is not None:
                log(f"router: port {port} in use; bound "
                    f"{self._server.server_address[1]} instead")
        self.router.annotate_stats(
            http_port=int(self._server.server_address[1])
        )
        self._serve_thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    # -- the poll loop ------------------------------------------------

    def refresh_once(self) -> None:
        """One discovery + stats round (also the deterministic test
        surface).  Discovery failures leave the last-known pod set
        serving — a scheduler failover must not blind the front door;
        stats failures age the pod out through the staleness gate."""
        try:
            body = self._discover()
        except Exception as e:  # noqa: BLE001 — keep serving on last-known
            if self._log is not None:
                self._log(f"router: discovery failed: {e}")
        else:
            backends: Dict[str, dict] = {}
            for entry in body.get("backends", []):
                backends[entry.get("task", entry["address"])] = entry
            if not backends:
                # bare address lists (older scheduler): synthesize
                backends = {
                    addr: {"address": addr}
                    for addr in body.get("address", [])
                }
            self.router.update_pods(
                backends, generation=body.get("generation")
            )
        state = self.router.describe()
        for name, row in state["pods"].items():
            if row["discovery_draining"]:
                # scheduler-side drain: the pod is pausing/replacing
                # and its stats are going away.  An OPERATOR-drained
                # pod keeps being polled — its gauges show the drain
                # progressing, and undrain needs them fresh.
                continue
            try:
                stats = self._pod_stats(row["address"])
            except Exception:  # noqa: BLE001, sdklint: disable=swallowed-exception — an unreachable pod ages out through the staleness gate; liveness is the scheduler's job
                continue
            self.router.observe_stats(name, stats)
        if self._stats_path is not None:
            self.router.write_stats(self._stats_path)
        self._refreshed.set()

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.refresh_once()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                if self._log is not None:
                    self._log(f"router: poll round failed: {e}")
            self._stop.wait(self._poll_interval_s)

    def _start_polling(self) -> None:
        """One shared startup sequence for both entry points: the
        first request must see a pod set (skip the refresh only when
        the caller already ran one, e.g. a readiness gate)."""
        if not self._refreshed.is_set():
            self.refresh_once()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="router-poll", daemon=True
        )
        self._poll_thread.start()

    def start(self) -> "RouterServer":
        self._start_polling()
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, name="router-http",
            daemon=True,
        )
        self._serve_thread.start()
        return self

    def serve_forever(self) -> None:
        self._start_polling()
        self._server.serve_forever()

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10)


def default_stats_path() -> str:
    return os.path.join(os.environ.get("SANDBOX", "."), ROUTERSTATS_NAME)
