"""Plan element status machine.

Reference: scheduler/plan/Status.java:23-78 — the full vocabulary
including the WAITING (operator interrupt) and DELAYED (launch
backoff) caveats called out in SURVEY.md section 7 hard part 5.
"""

from __future__ import annotations

import enum
from typing import Iterable


class Status(enum.Enum):
    ERROR = "ERROR"            # element has errors (bad spec / failed update)
    WAITING = "WAITING"        # operator interrupted; will not be offered work
    PENDING = "PENDING"        # no work started
    PREPARED = "PREPARED"      # placement evaluated, ops generated
    STARTING = "STARTING"      # tasks launched, awaiting RUNNING
    STARTED = "STARTED"        # tasks RUNNING, awaiting readiness/goal
    COMPLETE = "COMPLETE"      # goal reached
    IN_PROGRESS = "IN_PROGRESS"  # aggregate: some children done, some not
    DELAYED = "DELAYED"        # launch backoff after crash-loop

    @property
    def is_complete(self) -> bool:
        return self is Status.COMPLETE

    @property
    def is_running(self) -> bool:
        """Work actively underway (reference: Status.isRunning)."""
        return self in (
            Status.PREPARED,
            Status.STARTING,
            Status.STARTED,
            Status.IN_PROGRESS,
        )

    @property
    def is_working(self) -> bool:
        """Eligible for or doing work: not terminal, not parked."""
        return self in (
            Status.PENDING,
            Status.PREPARED,
            Status.STARTING,
            Status.STARTED,
            Status.IN_PROGRESS,
            Status.DELAYED,
        )


def aggregate(child_statuses: Iterable[Status], interrupted: bool = False) -> Status:
    """Roll child statuses up to a parent element.

    Reference: the aggregation rules in PlanUtils/Element.getStatus:
    ERROR dominates; an interrupt shows WAITING while incomplete;
    all-complete is COMPLETE; untouched is PENDING; otherwise
    IN_PROGRESS (with DELAYED surfaced when nothing else is moving).
    """
    statuses = list(child_statuses)
    if not statuses:
        return Status.COMPLETE
    if any(s is Status.ERROR for s in statuses):
        return Status.ERROR
    if all(s is Status.COMPLETE for s in statuses):
        return Status.COMPLETE
    if interrupted:
        return Status.WAITING
    if all(s in (Status.PENDING, Status.WAITING) for s in statuses):
        # children individually interrupted still read WAITING
        return Status.WAITING if any(
            s is Status.WAITING for s in statuses
        ) else Status.PENDING
    moving = [s for s in statuses if s.is_running]
    if not moving and any(s is Status.DELAYED for s in statuses):
        return Status.DELAYED
    return Status.IN_PROGRESS
