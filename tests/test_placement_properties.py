"""Property-based placement-rule tests.

Drives max-per / group-by / round-robin through randomized fleets and
task distributions, asserting the invariants the rules exist to
provide — under arrangements unit tests don't enumerate.
"""

import math

import pytest

pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from dcos_commons_tpu.common import TaskInfo
from dcos_commons_tpu.offer.inventory import ResourceSnapshot, TpuHost
from dcos_commons_tpu.offer.placement import (
    PlacementContext,
    parse_placement,
)

ZONES = ["za", "zb", "zc"]


def fleet_and_tasks(draw):
    n_hosts = draw(st.integers(min_value=1, max_value=6))
    hosts = [
        TpuHost(
            host_id=f"h{i}",
            hostname=f"h{i}",
            zone=draw(st.sampled_from(ZONES)),
            cpus=8.0,
            memory_mb=16384,
        )
        for i in range(n_hosts)
    ]
    n_tasks = draw(st.integers(min_value=0, max_value=8))
    tasks = [
        TaskInfo(
            name=f"app-{i}-main",
            pod_type="app",
            pod_index=i,
            agent_id=draw(st.sampled_from([h.host_id for h in hosts])),
        )
        for i in range(n_tasks)
    ]
    return hosts, tasks


def snap(host):
    return ResourceSnapshot(
        host, host.cpus, host.memory_mb, host.disk_mb,
        set(host.chip_ids()), set(),
    )


def counts_by(field, hosts, tasks):
    by_host = {h.host_id: h for h in hosts}
    out = {}
    for t in tasks:
        value = getattr(by_host[t.agent_id], field)
        out[value] = out.get(value, 0) + 1
    return out


@settings(max_examples=80, deadline=None)
@given(data=st.data(), cap=st.integers(min_value=1, max_value=3))
def test_max_per_host_invariant(data, cap):
    """Following the rule's verdicts can never exceed the cap."""
    hosts, tasks = fleet_and_tasks(data.draw)
    rule = parse_placement(f"max-per-host:{cap}")
    ctx = PlacementContext(
        pod_type="app",
        existing_tasks=tasks,
        hosts={h.host_id: h for h in hosts},
    )
    per_host = counts_by("hostname", hosts, tasks)
    for host in hosts:
        verdict = rule.filter(snap(host), ctx).passed
        count = per_host.get(host.hostname, 0)
        # rule passes exactly while the host is under its cap
        assert verdict == (count < cap), (
            f"cap={cap} host={host.hostname} count={count} "
            f"verdict={verdict}"
        )


@settings(max_examples=80, deadline=None)
@given(data=st.data())
def test_round_robin_never_widens_imbalance(data):
    """A placement the rule admits keeps max-min zone spread <= its
    value before the placement + 1 (the rule only fills the floor)."""
    hosts, tasks = fleet_and_tasks(data.draw)
    rule = parse_placement("round-robin:zone")
    ctx = PlacementContext(
        pod_type="app",
        existing_tasks=tasks,
        hosts={h.host_id: h for h in hosts},
    )
    zones_present = {h.zone for h in hosts}
    zone_counts = {
        z: counts_by("zone", hosts, tasks).get(z, 0) for z in zones_present
    }
    floor = min(zone_counts.values())
    for host in hosts:
        if rule.filter(snap(host), ctx).passed:
            # admitted placements are always into a floor zone
            assert zone_counts[host.zone] == floor, (
                f"admitted into {host.zone} at {zone_counts[host.zone]} "
                f"while floor is {floor}"
            )
    # and at least one host is always admissible (no deadlock)
    assert any(
        rule.filter(snap(h), ctx).passed for h in hosts
    )


@settings(max_examples=60, deadline=None)
@given(data=st.data(), expected=st.integers(min_value=1, max_value=4))
def test_group_by_stays_within_ceiling(data, expected):
    hosts, tasks = fleet_and_tasks(data.draw)
    rule = parse_placement(f"group-by:zone:{expected}")
    ctx = PlacementContext(
        pod_type="app",
        existing_tasks=tasks,
        hosts={h.host_id: h for h in hosts},
    )
    zone_counts = counts_by("zone", hosts, tasks)
    total = len(tasks) + 1
    ceiling = math.ceil(total / expected)
    for host in hosts:
        verdict = rule.filter(snap(host), ctx).passed
        # exact biconditional: the rule passes precisely while the
        # host's zone is under the ceiling
        assert verdict == (zone_counts.get(host.zone, 0) < ceiling)
