"""TaskKiller: kill with retries until a terminal status lands.

Reference: framework/TaskKiller.java — kills are recorded and
re-issued every cycle until the state store shows a terminal status
for the task id, surviving lost kill requests and scheduler restarts
(pending kills are re-derived from non-terminal statuses of tasks
flagged for killing).
"""

from __future__ import annotations

import threading
from typing import Dict, Set

from dcos_commons_tpu.agent.base import Agent
from dcos_commons_tpu.common import TaskStatus


class TaskKiller:
    def __init__(self, agent: Agent):
        self._agent = agent
        self._pending: Dict[str, float] = {}  # task_id -> grace period
        self._lock = threading.Lock()

    def kill(self, task_id: str, grace_period_s: float = 0.0) -> None:
        with self._lock:
            self._pending[task_id] = grace_period_s
        self._agent.kill(task_id, grace_period_s)

    def handle_status(self, status: TaskStatus) -> None:
        if status.state.is_terminal:
            with self._lock:
                self._pending.pop(status.task_id, None)

    def retry_pending(self) -> None:
        """Called each scheduler cycle: re-issue unacknowledged kills."""
        with self._lock:
            pending = dict(self._pending)
        active = self._agent.active_task_ids()
        for task_id, grace in pending.items():
            if task_id in active:
                self._agent.kill(task_id, grace)

    def pending_ids(self) -> Set[str]:
        with self._lock:
            return set(self._pending)
