"""The C++ task supervisor + agent restart recovery.

Reference: sdk/bootstrap/main.go — the reference puts NATIVE code at
the task boundary (a static Go binary prepended to every command);
here the native piece is the agent-side task_exec supervisor, which
makes task fates durable: pid + exit status live in the sandbox, so a
crashed-and-restarted agent daemon reconstructs every task instead of
losing them with its heap.
"""

import time


from dcos_commons_tpu.agent.local import LocalProcessAgent
from dcos_commons_tpu.common import TaskInfo, TaskState
from dcos_commons_tpu.native import task_exec_path


def wait_for_state(agent, task_id, state, timeout_s=10.0, collected=None):
    statuses = collected if collected is not None else []
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        statuses.extend(agent.poll())
        if any(
            s.task_id == task_id and s.state is state for s in statuses
        ):
            return statuses
        time.sleep(0.05)
    raise AssertionError(
        f"no {state} for {task_id}; saw "
        f"{[(s.task_id, s.state.value) for s in statuses]}"
    )


def test_native_binary_builds():
    assert task_exec_path(), "g++ is baked into this image"


def test_native_launch_captures_output_and_exit(tmp_path):
    agent = LocalProcessAgent(str(tmp_path / "w"))
    agent.launch_one(TaskInfo(
        name="t-0-a", task_id="t-0-a__1",
        command="echo out-line && echo err-line >&2 && exit 7",
    ))
    wait_for_state(agent, "t-0-a__1", TaskState.FAILED)
    sandbox = tmp_path / "w" / "t-0-a"
    assert (sandbox / "stdout").read_text().strip() == "out-line"
    assert (sandbox / "stderr").read_text().strip() == "err-line"
    assert (sandbox / ".super" / "t-0-a__1" / "exit_status"
            ).read_text().strip() == "7"
    agent.shutdown()


def test_native_kill_grace_then_escalation(tmp_path):
    agent = LocalProcessAgent(str(tmp_path / "w"))
    agent.launch_one(
        TaskInfo(
            name="t-0-g", task_id="t-0-g__1",
            command=(
                'trap "echo cleaning; sleep 0.2; exit 0" TERM; sleep 60'
            ),
        ),
        kill_grace_s=5.0,
    )
    # let the shell install its trap
    time.sleep(0.5)
    agent.kill("t-0-g__1", grace_period_s=5.0)
    statuses = wait_for_state(agent, "t-0-g__1", TaskState.KILLED)
    out = (tmp_path / "w" / "t-0-g" / "stdout").read_text()
    assert "cleaning" in out  # graceful path ran, not SIGKILL
    agent.shutdown()


def test_native_kill_time_grace_overrides_launch_grace(tmp_path):
    """The grace passed to kill() — not the one fixed at launch — must
    drive the supervisor's SIGKILL escalation: a task that ignores
    SIGTERM under a long launch-time grace dies within the SHORT
    kill-time grace (advisor round-2 finding on agent/local.py kill)."""
    agent = LocalProcessAgent(str(tmp_path / "w"))
    agent.launch_one(
        TaskInfo(
            name="t-0-o", task_id="t-0-o__1",
            command='trap "" TERM; sleep 60',
        ),
        kill_grace_s=45.0,  # launch-time default: far too long
    )
    time.sleep(0.5)  # let the shell install its trap
    t0 = time.monotonic()
    agent.kill("t-0-o__1", grace_period_s=1.0)
    wait_for_state(agent, "t-0-o__1", TaskState.KILLED, timeout_s=15.0)
    # well under the 45s launch grace => the 1s override was honored
    assert time.monotonic() - t0 < 10.0
    agent.shutdown()


def test_agent_restart_recovers_running_and_exited_tasks(tmp_path):
    """The durability claim end to end: agent 1 launches a long task
    and a short one, 'crashes' (dropped without shutdown), and agent 2
    over the same workdir resumes the live task and reports the
    finished one's exact exit fate."""
    workdir = str(tmp_path / "w")
    first = LocalProcessAgent(workdir)
    first.launch_one(TaskInfo(
        name="live-0-main", task_id="live-0-main__1",
        command="sleep 30",
    ))
    first.launch_one(TaskInfo(
        name="done-0-main", task_id="done-0-main__1",
        command="exit 0",
    ))
    # wait for the short task's supervisor to persist exit_status,
    # WITHOUT polling first (its fate must come from disk, not memory)
    deadline = time.monotonic() + 10
    exit_file = (tmp_path / "w" / "done-0-main" / ".super"
                 / "done-0-main__1" / "exit_status")
    while time.monotonic() < deadline and not exit_file.exists():
        time.sleep(0.05)
    assert exit_file.exists()
    # agent 1 "crashes": no shutdown, no kills — tasks keep running
    del first

    second = LocalProcessAgent(workdir)
    assert "live-0-main__1" in second.active_task_ids()
    statuses = second.poll()
    by_id = {(s.task_id, s.state) for s in statuses}
    assert ("done-0-main__1", TaskState.FINISHED) in by_id
    assert ("live-0-main__1", TaskState.RUNNING) in by_id
    # the recovered live task is still killable
    second.kill("live-0-main__1", grace_period_s=0.5)
    wait_for_state(second, "live-0-main__1", TaskState.KILLED)
    second.shutdown()


def test_recovered_exit_reported_exactly_once(tmp_path):
    workdir = str(tmp_path / "w")
    first = LocalProcessAgent(workdir)
    first.launch_one(TaskInfo(
        name="once-0-main", task_id="once-0-main__1", command="exit 5",
    ))
    deadline = time.monotonic() + 10
    exit_file = (tmp_path / "w" / "once-0-main" / ".super"
                 / "once-0-main__1" / "exit_status")
    while time.monotonic() < deadline and not exit_file.exists():
        time.sleep(0.05)
    del first
    second = LocalProcessAgent(workdir)
    assert any(
        s.task_id == "once-0-main__1" and s.state is TaskState.FAILED
        for s in second.poll()
    )
    # a third restart must NOT re-report the stale fate
    third = LocalProcessAgent(workdir)
    assert not any(
        s.task_id == "once-0-main__1" for s in third.poll()
    )


def test_relaunch_clears_stale_exit_record(tmp_path):
    """A new incarnation of the same task name must not be declared
    dead by its predecessor's exit_status file."""
    workdir = str(tmp_path / "w")
    agent = LocalProcessAgent(workdir)
    agent.launch_one(TaskInfo(
        name="re-0-main", task_id="re-0-main__1", command="exit 1",
    ))
    wait_for_state(agent, "re-0-main__1", TaskState.FAILED)
    agent.launch_one(TaskInfo(
        name="re-0-main", task_id="re-0-main__2", command="sleep 10",
    ))
    statuses = []
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        statuses.extend(agent.poll())
        if any(
            s.task_id == "re-0-main__2" and s.state is TaskState.RUNNING
            for s in statuses
        ):
            break
        time.sleep(0.05)
    assert not any(
        s.task_id == "re-0-main__2" and s.state.is_terminal
        for s in statuses
    )
    agent.shutdown()


def test_python_fallback_when_native_disabled(tmp_path):
    agent = LocalProcessAgent(str(tmp_path / "w"), use_native=False)
    agent.launch_one(TaskInfo(
        name="py-0-main", task_id="py-0-main__1",
        command="echo plain && exit 0",
    ))
    wait_for_state(agent, "py-0-main__1", TaskState.FINISHED)
    assert (tmp_path / "w" / "py-0-main" / "stdout").read_text().strip() \
        == "plain"
    agent.shutdown()
