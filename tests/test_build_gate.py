"""Build gate: every source file compiles and every module imports.

Reference: the root build's lint/style gates (checkstyle/findbugs in
build.gradle) — the cheap CI tripwire that catches a broken file
before any test exercises it.  Python's analogue: byte-compile every
source file (syntax) and import every library module (broken imports,
circular imports, missing deps) — modules only exercised by slow e2e
paths would otherwise fail late or not at all.
"""

import importlib
import os
import py_compile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _source_files():
    roots = ("dcos_commons_tpu", "frameworks", "tests")
    out = []
    for root in roots:
        for dirpath, dirs, files in os.walk(os.path.join(REPO, root)):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            out += [
                os.path.join(dirpath, f) for f in files
                if f.endswith(".py")
            ]
    out += [
        os.path.join(REPO, f)
        for f in ("bench.py", "__graft_entry__.py")
    ]
    return sorted(out)


def test_every_source_file_compiles(tmp_path):
    failures = []
    for i, path in enumerate(_source_files()):
        try:
            py_compile.compile(
                path, doraise=True, cfile=str(tmp_path / f"{i}.pyc")
            )
        except py_compile.PyCompileError as e:
            failures.append(str(e))
    assert not failures, "\n".join(failures)


def _library_modules():
    pkg_root = os.path.join(REPO, "dcos_commons_tpu")
    for dirpath, dirs, files in os.walk(pkg_root):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in files:
            if not f.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, f), REPO)
            mod = rel[:-3].replace(os.sep, ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            yield mod


@pytest.mark.parametrize("module", sorted(set(_library_modules())))
def test_library_module_imports(module):
    importlib.import_module(module)


# -- AST lint: the checkstyle/findbugs-class checks ---------------------
#
# Byte-compile catches syntax; import catches wiring.  These catch the
# static-analysis class the reference gates on (gradle/checkstyle/,
# findbugs): dead imports, always-true asserts, duplicated dict keys,
# mutable default arguments, bare excepts.

import ast
import re


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # quoted annotations ("StandbyTail", "Optional[Foo]"): a
            # CLASS-LIKE (capitalized) word inside a string counts as
            # used.  Lowercase words stay excluded — otherwise any
            # docstring mentioning "time" or "os" would mask a dead
            # stdlib import, the most common kind.
            used.update(
                w for w in re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value)
                if w[:1].isupper()
            )
    return used


def _lint_file(path: str) -> list:
    with open(path) as f:
        source = f.read()
    tree = ast.parse(source, filename=path)
    findings = []
    rel = os.path.relpath(path, REPO)
    lines = source.splitlines()

    def noqa(node) -> bool:
        """# noqa anywhere on the construct's line SPAN suppresses —
        the offending member of a multi-line def/dict may not be on
        the construct's first line (docs promise 'on the line')."""
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        return any(
            "noqa" in lines[i - 1]
            for i in range(node.lineno, min(end, len(lines)) + 1)
        )

    # unused imports (module-level only: function-local imports are
    # this repo's lazy-loading idiom and always immediately used);
    # __init__.py re-export surfaces are exempt
    if os.path.basename(path) != "__init__.py":
        used = _used_names(tree)
        for node in tree.body:
            names = []
            if isinstance(node, ast.Import):
                names = [
                    (a.asname or a.name.split(".")[0], node) for a in node.names
                ]
            elif isinstance(node, ast.ImportFrom) and node.module != "__future__":
                names = [(a.asname or a.name, node) for a in node.names]
            for name, imp in names:
                if name not in used and not noqa(imp):
                    findings.append(
                        f"{rel}:{imp.lineno}: unused import {name!r}"
                    )
    for node in ast.walk(tree):
        if isinstance(node, ast.Assert) and isinstance(
            node.test, ast.Tuple
        ) and node.test.elts and not noqa(node):
            findings.append(
                f"{rel}:{node.lineno}: assert on a non-empty tuple is "
                "always true (missing parentheses split?)"
            )
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            if not noqa(node):
                findings.append(
                    f"{rel}:{node.lineno}: bare except: catches "
                    "SystemExit/KeyboardInterrupt"
                )
        elif isinstance(node, ast.Dict):
            keys = [
                ast.dump(k) for k in node.keys
                if isinstance(k, ast.Constant)
            ]
            dupes = {k for k in keys if keys.count(k) > 1}
            if dupes and not noqa(node):
                findings.append(
                    f"{rel}:{node.lineno}: duplicate literal dict "
                    f"key(s): earlier values are silently dropped"
                )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in node.args.defaults + node.args.kw_defaults:
                # noqa is checked on the DEFAULT's own span, not the
                # whole function (an unrelated noqa deep in the body
                # must not suppress this)
                if isinstance(
                    default, (ast.List, ast.Dict, ast.Set)
                ) and not noqa(default):
                    findings.append(
                        f"{rel}:{node.lineno}: mutable default "
                        f"argument in {node.name}() is shared between "
                        "calls"
                    )
    return findings


def test_ast_lint_gate():
    failures = []
    for path in _source_files():
        failures += _lint_file(path)
    assert not failures, (
        f"{len(failures)} lint finding(s):\n" + "\n".join(failures)
    )


def test_lint_rules_and_noqa_contract(tmp_path):
    """The documented contract: each rule fires on its pattern, and
    '# noqa' ON THE OFFENDING LINE suppresses it — including when the
    construct spans multiple lines."""
    flagged = tmp_path / "flagged.py"
    flagged.write_text(
        "import os\n"                                # unused
        "def f(\n"
        "    cache={},\n"                            # mutable default
        "):\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"                              # bare except
        "        pass\n"
        "    assert (True,\n"
        "            'oops')\n"                      # tuple assert
        "    return {'k': 1,\n"
        "            'k': 2}\n"                      # duplicate key
    )
    findings = "\n".join(_lint_file(str(flagged)))
    for token in ("unused import", "mutable default", "bare except",
                  "tuple", "duplicate"):
        assert token in findings, (token, findings)
    clean = tmp_path / "clean.py"
    clean.write_text(
        "import os  # noqa\n"
        "def f(\n"
        "    cache={},  # noqa — deliberate static state\n"
        "):\n"
        "    try:\n"
        "        pass\n"
        "    except:  # noqa\n"
        "        pass\n"
        "    assert (True,\n"
        "            'oops')  # noqa\n"
        "    return {'k': 1,\n"
        "            'k': 2}  # noqa\n"
    )
    assert _lint_file(str(clean)) == []
