"""Write-through full-tree RAM cache over any Persister.

Reference: storage/PersisterCache.java — the reference wraps its
ZooKeeper persister in a full-tree cache to cut read round-trips;
disabled via DISABLE_STATE_CACHE (scheduler/SchedulerConfig.java).
Our FileWalPersister is already RAM-backed, but the cache matters for
future remote persisters (etcd) and preserves the reference contract.
"""

from __future__ import annotations

import threading
from typing import Iterable, List, Optional

from dcos_commons_tpu.storage.persister import (
    MemPersister,
    Persister,
    TransactionOp,
)


class PersisterCache(Persister):
    def __init__(self, backend: Persister) -> None:
        self._backend = backend
        self._lock = threading.RLock()
        self._cache = MemPersister()
        self._load()

    def _load(self) -> None:
        # Load errors must propagate and fail the boot: a partially
        # warmed cache would authoritatively answer "path not found"
        # for state that exists, making a running service look like a
        # fresh install.
        def walk(path: str) -> None:
            if path != "/":
                value = self._backend.get(path)
                if value is not None:
                    self._cache.set(path, value)
                else:
                    self._cache.ensure_node(path)
            for child in self._backend.get_children(path):
                walk(path.rstrip("/") + "/" + child)

        walk("/")

    def get(self, path: str) -> Optional[bytes]:
        with self._lock:
            return self._cache.get(path)

    def set(self, path: str, value: bytes) -> None:
        with self._lock:
            self._backend.set(path, value)
            self._cache.set(path, value)

    def get_children(self, path: str) -> List[str]:
        with self._lock:
            return self._cache.get_children(path)

    def recursive_delete(self, path: str) -> None:
        with self._lock:
            self._backend.recursive_delete(path)
            self._cache.recursive_delete(path)

    def apply(self, ops: Iterable[TransactionOp]) -> None:
        with self._lock:
            ops = list(ops)
            self._backend.apply(ops)
            self._cache.apply(ops)

    def close(self) -> None:
        self._backend.close()
