"""docs/yaml-reference.md cannot rot: its canonical example parses,
and every key the parser accepts appears in the doc.

Reference: docs/pages/yaml-reference.md (567 lines) is the original
dialect's contract; here the contract is enforced by CI.
"""

import os
import re

from dcos_commons_tpu.specification import GoalState, from_yaml

DOC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs",
    "yaml-reference.md",
)


def doc_text() -> str:
    with open(DOC, encoding="utf-8") as f:
        return f.read()


def canonical_yaml() -> str:
    match = re.search(r"```yaml\n(.*?)```", doc_text(), re.DOTALL)
    assert match, "yaml-reference.md lost its canonical example"
    return match.group(1)


def test_canonical_example_parses_and_round_trips():
    spec = from_yaml(
        canonical_yaml(),
        env={"DEBUG_MODE": "true", "CORPUS_SHA256": "aa" * 32},
    )
    assert spec.name == "example"
    assert spec.service_tld == "corp.internal"
    assert spec.web_url.startswith("http://example.ui")
    assert spec.replacement_failure_policy.min_replace_delay_s == 120
    worker = spec.pod("worker")
    assert worker.gang and worker.tpu.topology == "4x4"
    assert worker.count == 4
    assert worker.allow_decommission
    assert worker.secrets[0].env_key == "HUB_TOKEN"
    node = worker.task("node")
    assert "--verbose" in node.cmd  # boolean section rendered
    assert node.resources.ports[0].vip == "node:7077"
    assert node.resources.ports[0].env_key == "RPC_PORT"
    assert node.health_check.max_consecutive_failures == 3
    assert node.readiness_check.interval_s == 2
    assert node.discovery_prefix == "node"
    assert node.kill_grace_period_s == 30
    assert node.transport_encryption[0].name == "node-tls"
    dests = {u.effective_dest() for u in node.uris}
    assert "data/corpus.tar" in dests
    assert "tokenizer.model" in dests  # pod-level uri merged in
    assert {v.container_path for v in node.volumes} == {
        "shared-scratch", "node-data",
    }
    sidecar = worker.task("sidecar")
    assert sidecar.goal is GoalState.FINISH and not sidecar.essential
    assert set(spec.plans) == {"deploy", "snapshot"}
    # the custom plan compiles too (generator path)
    from dcos_commons_tpu.testing import AdvanceCycles, ServiceTestRunner

    from dcos_commons_tpu.scheduler import SchedulerConfig

    runner = ServiceTestRunner(spec=spec, scheduler_config=SchedulerConfig(
        backoff_enabled=False, revive_capacity=1_000_000,
        secrets_dir="/tmp",
    ))
    runner.run([AdvanceCycles(1)])
    assert set(runner.world.scheduler.plans()) >= {"deploy", "snapshot"}


def test_every_documented_key_is_used_by_the_example():
    """The doc's tables and its example stay in sync: each table key
    appears in the canonical YAML (so a renamed/removed key breaks
    this test, forcing a doc update)."""
    yaml_text = canonical_yaml()
    table_keys = re.findall(r"^\| `([a-z0-9-]+)`", doc_text(), re.M)
    assert len(table_keys) > 30
    # keys that legitimately appear under a different spelling in the
    # example (volume vs volumes are alternates)
    alternates = {"volumes": ("volume", "volumes")}
    for key in table_keys:
        spellings = alternates.get(key, (key,))
        assert any(f"{s}:" in yaml_text for s in spellings), (
            f"documented key {key!r} missing from the canonical example"
        )
