"""Worker-side distributed bootstrap: the rendezvous shim.

The moral equivalent of the reference's sdk/bootstrap DNS-wait
(sdk/bootstrap/main.go:218-289): instead of each task resolving its
own DNS record, workers read the scheduler-issued env contract
(offer/evaluate.py) and call jax.distributed.initialize against the
coordinator address the scheduler allocated on worker 0's host.  The
scheduler persisted that address in the FrameworkStore, so restarts
rendezvous at the same point.
"""

from __future__ import annotations

import logging
import os
from typing import Mapping, Optional

LOG = logging.getLogger(__name__)


def initialize_from_env(
    env: Optional[Mapping[str, str]] = None, timeout_s: int = 300
) -> dict:
    """Initialize jax.distributed from the scheduler env contract.

    Returns the parsed contract.  Single-worker pods (no
    COORDINATOR_ADDRESS) skip initialization — jax runs locally.
    """
    env = env if env is not None else os.environ
    contract = {
        "coordinator": env.get("COORDINATOR_ADDRESS", ""),
        "worker_id": int(env.get("TPU_WORKER_ID", "0") or 0),
        "worker_count": int(env.get("TPU_WORKER_COUNT", "1") or 1),
        # 0 is the "probe the local runtime" sentinel, not a chip
        # count; options.json's 4 only applies to rendered deploys
        # sdklint: disable=config-default-drift — autodetect sentinel
        "chips_per_host": int(env.get("TPU_CHIPS_PER_HOST", "0") or 0),
        "topology": env.get("TPU_TOPOLOGY", ""),
        "generation": env.get("TPU_GENERATION", ""),
    }
    if contract["worker_count"] > 1 and contract["coordinator"]:
        import jax

        LOG.info(
            "jax.distributed.initialize(%s, %d/%d)",
            contract["coordinator"],
            contract["worker_id"],
            contract["worker_count"],
        )
        jax.distributed.initialize(
            coordinator_address=contract["coordinator"],
            num_processes=contract["worker_count"],
            process_id=contract["worker_id"],
            initialization_timeout=timeout_s,
        )
    return contract
